"""Signal-driven autoscaler: engine signals in, gang admissions/releases out.

The control loop folds per-replica engine signals — queue depth, slot
occupancy, KV-page footprint, host-gap — from the router's health polls
(``/v1/stats``) with the profile observatory's per-class throughput, and
turns them into scale decisions executed through the scheduler's own
admission surface.  Three layers, deliberately separable:

- **ScalingPolicy** — the knobs (watermarks, hysteresis depth, cooldowns,
  min/max bounds).  Plain data.
- **PolicyEngine** — the decision state machine: ``evaluate(signals, n,
  now)`` → up | down | hold.  PURE given its inputs and its own state
  (an explicit ``now`` instead of wall-clock reads), which is what makes
  offline scoring honest: ``score_policy`` replays the journal's
  recorded ``fleet`` records through a fresh PolicyEngine and reports
  what the candidate WOULD have done against what the incumbent did —
  the same replay-gated promotion story the what-if rater path uses.
- **Autoscaler** — the loop: poll, evaluate, journal EVERY evaluation as
  a ``fleet`` record (annotations in the flight recorder's stream, like
  ``profile`` records — dense-seq audited, never allocator mutations),
  and drive the executor on up/down.

Executors are duck-typed (``scale_up(reason, generation_pref)`` →
replica name or None; ``scale_down(name, reason)`` → bool).
:class:`SchedulerGangExecutor` is the production shape: a new replica is
a pod admitted through the extender's HTTP filter → bind verbs (so the
scale-up IS a journaled gang admission, visible to replay and every
scheduling invariant), placed onto the feasible node whose TPU
generation ranks highest in the profile observatory's measured
throughput-per-chip for the fleet's workload class (the
heterogeneity-aware Gavel policy); a release drains the replica at the
router first, then deletes the pod so the reconciliation path journals
the forget.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import asdict, dataclass
from typing import Optional

from ..journal import JOURNAL
from ..metrics import FLEET_EVENTS, FLEET_SCALE_LATENCY, KV_MIGRATIONS
from ..profile import PROFILER, generation_preference
from ..tracing import TRACER

__all__ = [
    "Autoscaler",
    "PolicyEngine",
    "ScalingPolicy",
    "SchedulerGangExecutor",
    "fold_signals",
    "generation_preference",  # canonical definition lives in profile/
    "score_policy",
]

log = logging.getLogger("tpu-scheduler")


@dataclass
class ScalingPolicy:
    """Watermarks + pacing for the decision state machine.  ``name``
    labels journal records so offline scoring can tell policies apart."""

    name: str = "default"
    min_replicas: int = 1
    max_replicas: int = 8
    # scale up when ANY of these breach...
    queue_high: float = 4.0  # mean queued requests per replica
    occupancy_high: float = 0.85  # active slots / total slots
    page_high: float = 0.9  # KV pages in use / total
    # ...scale down only when ALL of these clear
    queue_low: float = 0.25
    occupancy_low: float = 0.25
    # consecutive breaching evaluations required before acting (one noisy
    # poll must not flap the fleet)
    hysteresis_rounds: int = 2
    up_cooldown_s: float = 10.0
    down_cooldown_s: float = 60.0

    def to_dict(self) -> dict:
        return asdict(self)


def fold_signals(per_replica: list[dict]) -> dict:
    """Aggregate per-replica ``/v1/stats`` payloads into the scalar
    signals the policy thresholds read.  Missing fields fold as zero —
    a replica that never answered stats must not block scaling math."""
    n = max(1, len(per_replica))
    queued = sum(int(s.get("queued", 0)) for s in per_replica)
    active = sum(int(s.get("active_slots", 0)) for s in per_replica)
    batch = sum(int(s.get("max_batch", 0)) for s in per_replica)
    pages_total = sum(int(s.get("total_pages", 0)) for s in per_replica)
    pages_free = sum(int(s.get("free_pages", 0)) for s in per_replica)
    # /v1/stats' host_gap payload carries mean_ms/last_ms (the p50/p99
    # live only in the /metrics histogram, drained at scrape time)
    gaps = [
        float(s["host_gap"]["mean_ms"])
        for s in per_replica
        if isinstance(s.get("host_gap"), dict)
        and "mean_ms" in s["host_gap"]
    ]
    return {
        "replicas": len(per_replica),
        "queued": queued,
        "queue_per_replica": round(queued / n, 3),
        "occupancy": round(active / batch, 4) if batch else 0.0,
        "page_util": (
            round(1.0 - pages_free / pages_total, 4) if pages_total else 0.0
        ),
        "host_gap_ms": round(sum(gaps) / len(gaps), 3) if gaps else 0.0,
    }


class PolicyEngine:
    """The hysteresis/cooldown/bounds state machine over a policy.  One
    instance per (policy, stream): the live Autoscaler owns one, and
    ``score_policy`` builds a fresh one per offline run."""

    def __init__(self, policy: ScalingPolicy):
        self.policy = policy
        self.up_streak = 0
        self.down_streak = 0
        self.last_up = float("-inf")
        self.last_down = float("-inf")
        # why the last evaluation held: "bounds" | "cooldown" | None.
        # The LIVE Autoscaler turns this into metrics; the engine itself
        # is side-effect-free so offline score_policy replays cannot
        # pollute the real process's counters.
        self.suppressed = None

    def evaluate(
        self, signals: dict, n_replicas: int, now: float,
        total_replicas: Optional[int] = None,
        warming_replicas: int = 0,
        slo: Optional[dict] = None,
    ):
        """(action, reason) with action ∈ up | down | hold.
        ``n_replicas`` counts ROUTABLE ('up') replicas; ``total_replicas``
        counts every registered one (incl. warming/draining/down) — the
        floor restore below caps on the TOTAL, or a fleet whose replicas
        are all draining (relay outage) would admit a new pod every tick
        until the cluster is full.  ``warming_replicas`` counts replicas
        mid-compile-warm-up: capacity already admitted but not yet
        routable — a scale-up while one is warming would double-buy the
        same breach, so ups are suppressed until the warm-up lands (the
        readiness-gating half of the warm-start compilation plane).
        ``slo`` is the SLO plane's burn posture (``SLO.scaling_input``:
        ``{"burning": bool, "breached": [...]}``, journaled verbatim in
        the ``fleet`` record and replayed by ``score_policy``) — a
        burning error budget counts as a scale-up breach, so the fleet
        grows on budget burn BEFORE queue depth moves; it also vetoes a
        scale-down (shrinking a fleet that is blowing its SLO is never
        right, however idle the queue looks).  PURE input like every
        other: None (no SLO plane) reproduces the historic behavior
        exactly."""
        p = self.policy
        self.suppressed = None
        total = n_replicas if total_replicas is None else total_replicas
        slo_burning = bool(slo and slo.get("burning"))
        if n_replicas < p.min_replicas:
            # the floor is not a watermark decision — but it still
            # respects the up-cooldown (one restore per cooldown window,
            # not one per tick while a replica boots) and the total cap
            self.up_streak = self.down_streak = 0
            if total >= p.max_replicas:
                self.suppressed = "bounds"
                return "hold", (
                    f"below min_replicas but {total} total replicas at "
                    f"max_replicas ({p.max_replicas})"
                )
            if warming_replicas > 0:
                self.suppressed = "warming"
                return "hold", (
                    f"below min_replicas but {warming_replicas} "
                    "replica(s) warming (capacity in flight)"
                )
            if now - self.last_up < p.up_cooldown_s:
                self.suppressed = "cooldown"
                return "hold", "below min_replicas (up cooldown)"
            self.last_up = now
            return "up", f"below min_replicas ({n_replicas}<{p.min_replicas})"
        breach_up = (
            signals.get("queue_per_replica", 0.0) >= p.queue_high
            or signals.get("occupancy", 0.0) >= p.occupancy_high
            or signals.get("page_util", 0.0) >= p.page_high
            or slo_burning
        )
        breach_down = (
            signals.get("queue_per_replica", 0.0) <= p.queue_low
            and signals.get("occupancy", 0.0) <= p.occupancy_low
            and not slo_burning
        )
        self.up_streak = self.up_streak + 1 if breach_up else 0
        self.down_streak = self.down_streak + 1 if breach_down else 0
        if breach_up:
            if self.up_streak < p.hysteresis_rounds:
                return "hold", f"up hysteresis {self.up_streak}/{p.hysteresis_rounds}"
            if total >= p.max_replicas:
                # cap on TOTAL registered replicas, same as the floor
                # branch: counting only routable ones would let the
                # fleet grow past the bound whenever one is draining
                self.suppressed = "bounds"
                return "hold", f"at max_replicas ({p.max_replicas})"
            if warming_replicas > 0:
                # a previous scale-up is still pre-lowering its compile
                # lattice: the breach that bought it is the breach still
                # showing — buying another replica for the same breach
                # is the compile-storm version of flapping
                self.suppressed = "warming"
                return "hold", (
                    f"{warming_replicas} replica(s) warming "
                    "(scale-up already in flight)"
                )
            if now - self.last_up < p.up_cooldown_s:
                self.suppressed = "cooldown"
                return "hold", "up cooldown"
            self.up_streak = 0
            self.last_up = now
            return "up", self._breach_reason(signals, slo)
        if breach_down:
            if self.down_streak < p.hysteresis_rounds:
                return "hold", f"down hysteresis {self.down_streak}/{p.hysteresis_rounds}"
            if n_replicas <= p.min_replicas:
                self.suppressed = "bounds"
                return "hold", f"at min_replicas ({p.min_replicas})"
            if now - self.last_down < p.down_cooldown_s:
                self.suppressed = "cooldown"
                return "hold", "down cooldown"
            self.down_streak = 0
            self.last_down = now
            return "down", "idle (queue and occupancy below low watermarks)"
        return "hold", "within watermarks"

    def _breach_reason(self, signals: dict,
                       slo: Optional[dict] = None) -> str:
        p = self.policy
        parts = []
        if signals.get("queue_per_replica", 0.0) >= p.queue_high:
            parts.append(
                f"queue/replica {signals['queue_per_replica']}"
                f">={p.queue_high}"
            )
        if signals.get("occupancy", 0.0) >= p.occupancy_high:
            parts.append(f"occupancy {signals['occupancy']}>={p.occupancy_high}")
        if signals.get("page_util", 0.0) >= p.page_high:
            parts.append(f"page_util {signals['page_util']}>={p.page_high}")
        if slo and slo.get("burning"):
            for b in (slo.get("breached") or [])[:2]:
                parts.append(
                    f"slo burn {b.get('wclass')}:{b.get('objective')} "
                    f"short={b.get('burn_short')} long={b.get('burn_long')}"
                )
        return "; ".join(parts) or "breach"


class Autoscaler:
    """The control loop.  ``replicas`` is the router's ReplicaSet (the
    signal source AND the unit of draining); ``executor`` owns the
    mechanics of adding/removing a replica."""

    def __init__(
        self,
        replicas,
        executor,
        policy: Optional[ScalingPolicy] = None,
        interval_s: float = 5.0,
        wclass: str = "serve",
        profiler=None,
        migrator=None,
        shed_queue_margin: float = 0.0,
        slo_provider=None,
        clock=time.monotonic,
        extra_replica_sets=None,
    ):
        """``slo_provider``: callable → the SLO plane's burn posture
        (``SLO.scaling_input`` is the production shape; None while no
        objectives are loaded).  The posture is a PURE evaluate input —
        journaled inside every ``fleet`` record (``slo`` field) so
        ``score_policy`` replays candidates against exactly the burn
        history the incumbent saw; a burning budget triggers scale-up
        before queue depth moves and vetoes scale-down.

        ``migrator``: duck-typed live-migration command —
        ``migrator(src_name, dst_name) -> dict`` with at least ``ok``
        (``FleetRouter.migrate_session`` is the production shape).  With
        one wired, the autoscaler REBALANCES in-flight sessions instead
        of only trading replicas: a hot replica sheds a session to the
        idlest one when their queue depths diverge by
        ``shed_queue_margin`` (> 0 enables; checked on 'hold' ticks so
        shedding never races a scale action), and scale-down migrates
        the victim's live sessions away instead of waiting out their
        generation.  Every commanded migration journals a ``kv_migrate``
        annotation — the decision trail replay audits alongside
        ``fleet`` records.

        ``extra_replica_sets``: additional ``ReplicaSet``s whose 'up'
        stats fold into ``signals()`` alongside the primary set.  A
        sharded data plane (federation ``RouterRing``) runs one router
        per shard, each polling its own ``ReplicaSet`` — the scaler
        must see fleet-wide queue/occupancy, not one shard's slice, or
        a hot shard hides behind a cold one's averages."""
        self.replicas = replicas
        self.extra_replica_sets = list(extra_replica_sets or [])
        self.executor = executor
        self.policy = policy or ScalingPolicy()
        self.engine = PolicyEngine(self.policy)
        self.interval_s = max(0.05, float(interval_s))
        self.wclass = wclass
        self.profiler = profiler if profiler is not None else PROFILER
        self.migrator = migrator
        self.shed_queue_margin = float(shed_queue_margin)
        self.slo_provider = slo_provider
        # time source for tick's default ``now`` — the digital twin
        # (twin/) injects a VirtualClock so cooldowns/hysteresis run in
        # simulated time; live scalers keep time.monotonic
        self.clock = clock
        self.evaluations = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.sheds = 0
        self.last_shed: Optional[dict] = None
        self.last_decision: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one evaluation ------------------------------------------------------

    def signals(self) -> dict:
        # 'up' replicas only: a draining replica's stats FREEZE at its
        # last poll (the health loop stops refreshing it), so folding
        # them would scale on dead data — and its queued work reroutes
        # to the up set as it drains anyway
        reps = [r for r in self.replicas.all() if r.state == "up"]
        for rs in self.extra_replica_sets:
            reps.extend(r for r in rs.all() if r.state == "up")
        return fold_signals([r.stats for r in reps])

    def _victim(self) -> Optional[str]:
        """Scale-down victim: the least-loaded routable replica (its
        in-flight streams finish during the drain; new sessions go
        elsewhere the moment it flips to draining)."""
        candidates = self.replicas.routable()
        if not candidates:
            return None
        return min(candidates, key=lambda r: r.load_key()).name

    def tick(self, now: Optional[float] = None) -> dict:
        """Evaluate once; journal the evaluation; execute a decision.
        Returns the decision record (also kept as ``last_decision``)."""
        now = self.clock() if now is None else now
        self.evaluations += 1
        sig = self.signals()
        all_reps = self.replicas.all()
        n = len([r for r in all_reps if r.state == "up"])
        total = len(all_reps)
        warming = len([r for r in all_reps if r.state == "warming"])
        slo = None
        if self.slo_provider is not None:
            try:
                slo = self.slo_provider()
            except Exception:
                # the SLO plane failing must never take the scaler with
                # it — posture degrades to "no SLO input", the historic
                # behavior
                log.exception("fleet slo provider failed")
                slo = None
        action, reason = self.engine.evaluate(
            sig, n, now, total_replicas=total, warming_replicas=warming,
            slo=slo,
        )
        if self.engine.suppressed == "bounds":
            FLEET_EVENTS.inc("bounds_suppressed")
        elif self.engine.suppressed == "cooldown":
            FLEET_EVENTS.inc("cooldown_suppressed")
        elif self.engine.suppressed == "warming":
            FLEET_EVENTS.inc("warming_suppressed")
        gen_pref = (
            self.profiler.generation_preference(self.wclass)
            if self.profiler.enabled
            else []
        )
        rec = {
            "action": action,
            "reason": reason,
            "signals": sig,
            "replicas": n,
            "replicas_total": total,
            "warming": warming,
            "slo": slo,
            "policy": self.policy.name,
            "wclass": self.wclass,
            "generation_pref": gen_pref or None,
            "executed": False,
            "target": None,
        }
        if self.executor is None and action in ("up", "down"):
            # advisory mode (no executor wired — e.g. a real cluster
            # where replica processes are an operator's deployment
            # controller's job): the decision is journaled and surfaced,
            # never executed
            rec["reason"] = f"{reason} (advisory: no executor)"
            FLEET_EVENTS.inc("advisory")
        elif action == "up":
            t0 = time.perf_counter()
            with TRACER.span(
                "fleet.scale_up", reason=reason, replicas=n,
            ) as sp:
                try:
                    name = self.executor.scale_up(reason, gen_pref)
                except Exception:
                    log.exception("fleet scale-up failed")
                    name = None
                if name:
                    rec["executed"] = True
                    rec["target"] = name
                    self.scale_ups += 1
                    FLEET_EVENTS.inc("scale_up")
                    FLEET_SCALE_LATENCY.observe(
                        value=time.perf_counter() - t0
                    )
                    sp.set_attr("replica", name)
                else:
                    FLEET_EVENTS.inc("scale_up_failed")
                    sp.end(status="error")
        elif action == "down":
            victim = self._victim()
            rec["target"] = victim
            if victim is None:
                FLEET_EVENTS.inc("scale_down_failed")
            else:
                t0 = time.perf_counter()
                with TRACER.span(
                    "fleet.scale_down", reason=reason, replica=victim,
                ) as sp:
                    self.replicas.drain(victim, reason="scale-down")
                    if self.migrator is not None:
                        # rebalance instead of draining: hand the
                        # victim's live sessions to surviving replicas
                        # (≤1 lost chunk each, token-identical), so the
                        # release waits on byte relays, not generation
                        rec["migrated_off"] = self._migrate_off(victim)
                    try:
                        ok = self.executor.scale_down(victim, reason)
                    except Exception:
                        log.exception("fleet scale-down failed")
                        ok = False
                    if ok:
                        rec["executed"] = True
                        self.scale_downs += 1
                        FLEET_EVENTS.inc("scale_down")
                        FLEET_SCALE_LATENCY.observe(
                            value=time.perf_counter() - t0
                        )
                    else:
                        # failed release: the replica must come back
                        # (a pinned drain forever leaks capacity)
                        self.replicas.undrain(
                            victim, reason="scale-down failed; restored"
                        )
                        FLEET_EVENTS.inc("scale_down_failed")
                        sp.end(status="error")
        else:
            FLEET_EVENTS.inc("hold")
            if self.migrator is not None and self.shed_queue_margin > 0:
                # load rebalance rides 'hold' ticks only: a shed must
                # never race a scale action it could invalidate
                shed = self._maybe_shed()
                if shed is not None:
                    rec["shed"] = shed
        if JOURNAL.enabled:
            JOURNAL.record("fleet", **rec)
        self.last_decision = rec
        return rec

    # -- in-flight session rebalance (disaggregated data plane) --------------

    def _journal_migrate(self, src: str, dst: str, reason: str,
                         res: dict) -> None:
        """One ``kv_migrate`` annotation per commanded migration —
        replay counts them next to fleet records (never an allocator
        mutation); what-if skips them."""
        if not JOURNAL.enabled:
            return
        JOURNAL.record(
            "kv_migrate",
            src=src,
            dst=dst,
            reason=reason,
            ok=bool(res.get("ok")),
            pages=res.get("pages_shipped"),
            tokens_done=res.get("tokens_done"),
            slot=res.get("slot"),
            error=res.get("error"),
        )

    def _queue_key(self, r) -> int:
        return int(r.stats.get("queued", 0)) + int(r.inflight)

    def _maybe_shed(self) -> Optional[dict]:
        """One session hop per tick, hottest → idlest replica, when
        their queue depths diverge past ``shed_queue_margin`` and the
        hot one actually has a live session to hand off."""
        # prefill-role replicas take no completion traffic (the router's
        # invariant) — they must not become migration DESTINATIONS
        # either, or the shed lands a decode token loop on them
        ups = [
            r for r in self.replicas.all()
            if r.state == "up" and getattr(r, "role", "both") != "prefill"
        ]
        if len(ups) < 2:
            return None
        busy = max(ups, key=self._queue_key)
        idle = min(ups, key=self._queue_key)
        if (
            busy is idle
            or self._queue_key(busy) - self._queue_key(idle)
            < self.shed_queue_margin
            or int(busy.stats.get("active_slots", 0)) < 1
        ):
            return None
        try:
            res = self.migrator(busy.name, idle.name)
        except Exception as e:  # noqa: BLE001 — a failed shed is data
            res = {"ok": False, "error": str(e)}
        ok = bool(res.get("ok"))
        if ok:
            self.sheds += 1
            KV_MIGRATIONS.inc("shed")
            FLEET_EVENTS.inc("shed_executed")
        else:
            KV_MIGRATIONS.inc("shed_failed")
            FLEET_EVENTS.inc("shed_failed")
        self._journal_migrate(busy.name, idle.name, "shed", res)
        out = {
            "src": busy.name, "dst": idle.name, "ok": ok,
            "error": res.get("error"),
        }
        self.last_shed = out
        return out

    def _migrate_off(self, victim: str) -> int:
        """Scale-down rebalance: migrate the draining victim's live
        sessions to the least-loaded surviving replicas, bounded by its
        slot count (each hop journals a ``kv_migrate``).  Returns
        sessions moved; stops at the first 'nothing live' verdict."""
        v = self.replicas.get(victim)
        if v is None:
            return 0
        moved = 0
        budget = max(1, int(v.stats.get("max_batch", 1)))
        for _ in range(budget):
            survivors = [
                r for r in self.replicas.all()
                if r.state == "up" and r.name != victim
                and getattr(r, "role", "both") != "prefill"
            ]
            if not survivors:
                break
            dst = min(survivors, key=self._queue_key)
            try:
                res = self.migrator(victim, dst.name)
            except Exception as e:  # noqa: BLE001 — failed hop is data
                res = {"ok": False, "error": str(e)}
            if not res.get("ok"):
                # 409 = no live session left: the clean exit
                if res.get("status") != 409:
                    KV_MIGRATIONS.inc("shed_failed")
                    self._journal_migrate(
                        victim, dst.name, "scale_down", res
                    )
                break
            moved += 1
            KV_MIGRATIONS.inc("shed")
            self._journal_migrate(victim, dst.name, "scale_down", res)
        return moved

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    log.exception("fleet autoscaler tick failed")

        self._thread = threading.Thread(
            target=loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def debug_state(self) -> dict:
        return {
            "policy": self.policy.to_dict(),
            "wclass": self.wclass,
            "evaluations": self.evaluations,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "sheds": self.sheds,
            "shed_queue_margin": self.shed_queue_margin,
            "last_shed": self.last_shed,
            "last_decision": self.last_decision,
        }


def score_policy(events: list[dict], policy: ScalingPolicy) -> dict:
    """Offline policy scoring over a recorded journal: feed every
    recorded ``fleet`` evaluation's signals through a FRESH PolicyEngine
    for ``policy`` and compare its decisions with the incumbent's.  The
    candidate sees the same signal stream at the same (recorded)
    timestamps — cooldowns and hysteresis replay faithfully — so an
    operator can score a watermark change against yesterday's traffic
    before promoting it (the same journal-first promotion bar the
    what-if rater path set)."""
    engine = PolicyEngine(policy)
    t0: Optional[float] = None
    evaluations = agreements = 0
    would = {"up": 0, "down": 0, "hold": 0}
    recorded = {"up": 0, "down": 0, "hold": 0}
    disagreements: list[dict] = []
    for rec in events:
        if rec.get("type") != "fleet":
            continue
        evaluations += 1
        t = float(rec.get("t", 0.0))
        if t0 is None:
            t0 = t
        n_up = int(rec.get("replicas", 0))
        action, reason = engine.evaluate(
            rec.get("signals") or {}, n_up, t - t0,
            total_replicas=int(rec.get("replicas_total", n_up)),
            warming_replicas=int(rec.get("warming", 0)),
            slo=rec.get("slo"),
        )
        rec_action = rec.get("action", "hold")
        would[action] = would.get(action, 0) + 1
        recorded[rec_action] = recorded.get(rec_action, 0) + 1
        if action == rec_action:
            agreements += 1
        elif len(disagreements) < 16:
            disagreements.append({
                "seq": rec.get("seq"),
                "recorded": rec_action,
                "candidate": action,
                "candidate_reason": reason,
                "signals": rec.get("signals"),
            })
    return {
        "policy": policy.name,
        "evaluations": evaluations,
        "agreements": agreements,
        "agreement_pct": round(100.0 * agreements / evaluations, 2)
        if evaluations else 0.0,
        "candidate_decisions": would,
        "recorded_decisions": recorded,
        "disagreements": disagreements,
    }


class SchedulerGangExecutor:
    """Scale through the scheduler's HTTP surface (see the module
    docstring).  Pluggable mechanics:

    - ``pod_factory(serial) -> Pod``: the replica pod template (workload
      class annotated, TPU demand sized for one replica);
    - ``spawner(pod, node) -> Replica``: actually start the serving
      process and return its router-facing Replica (in-process engines
      in tests/tools; a StatefulSet/operator in a real cluster);
    - ``releaser(replica_name, pod) -> None``: stop the serving process.

    The admission round-trips go over HTTP (``/scheduler/filter`` →
    ``/scheduler/bind``) so a scale-up exercises exactly the verbs — and
    lands exactly the journal records — a kube-scheduler-admitted pod
    would."""

    def __init__(
        self,
        cluster,
        scheduler_addr: tuple,
        replicas,
        pod_factory,
        spawner,
        releaser=None,
        drain_timeout_s: float = 30.0,
        http_timeout_s: float = 10.0,
    ):
        # ``cluster``: pod/node store with create_pod/delete_pod/list_nodes
        # (FakeCluster in tests/tools; the REST cluster view in-cluster)
        self.cluster = cluster
        self.scheduler_addr = scheduler_addr
        self.replicas = replicas
        self.pod_factory = pod_factory
        self.spawner = spawner
        self.releaser = releaser
        self.drain_timeout_s = drain_timeout_s
        self.http_timeout_s = http_timeout_s
        self.serial = 0
        self.pods: dict[str, object] = {}  # replica name → Pod

    # scheduler 503s are leaderless-window answers (leader fencing/
    # failing over; routes.py stamps Retry-After) — retried under the
    # shared jittered backoff honoring the server's floor, bounded by
    # one deadline per operation.  Anything else fails fast: a 4xx/5xx
    # with a body is a real verdict, not a window.
    RETRY_DEADLINE_S = 15.0

    def _request(self, method: str, path: str, body=None) -> dict:
        import http.client

        from ..utils.backoff import Backoff

        bo = Backoff(base_s=0.25, max_s=5.0,
                     deadline_s=self.RETRY_DEADLINE_S)
        while True:
            conn = http.client.HTTPConnection(
                *self.scheduler_addr, timeout=self.http_timeout_s
            )
            try:
                if method == "POST":
                    conn.request(
                        "POST", path, json.dumps(body),
                        {"Content-Type": "application/json"},
                    )
                else:
                    conn.request("GET", path)
                resp = conn.getresponse()
                data = resp.read()
                if resp.status == 503:
                    try:
                        floor = float(resp.headers.get("Retry-After", "0"))
                    except (TypeError, ValueError):
                        floor = 0.0
                    if bo.sleep(floor_s=min(floor, 5.0)):
                        continue
                if resp.status != 200:
                    raise RuntimeError(
                        f"{path} -> {resp.status}: {data[:200]}"
                    )
                return json.loads(data)
            finally:
                conn.close()

    def _post(self, path: str, body: dict) -> dict:
        return self._request("POST", path, body)

    def _get(self, path: str) -> dict:
        return self._request("GET", path)

    def _node_generations(self) -> dict[str, str]:
        # summary mode: one aggregate poll instead of a node-list walk
        # with a label read per node (and never the full per-node chip
        # dict a classic /scheduler/status at fleet scale would ship)
        try:
            st = self._get("/scheduler/status?summary=1&generations=1")
            out: dict[str, str] = {}
            for sched in st.get("schedulers", []):
                out.update(sched.get("node_generations") or {})
            if out:
                return out
        except Exception:
            pass
        from ..utils import consts

        out = {}
        try:
            for node in self.cluster.list_nodes():
                out[node.metadata.name] = (
                    node.metadata.labels or {}
                ).get(consts.LABEL_TPU_ACCELERATOR, "")
        except Exception:
            pass
        return out

    def scale_up(self, reason: str, generation_pref: list) -> Optional[str]:
        self.serial += 1
        pod = self.pod_factory(self.serial)
        self.cluster.create_pod(pod)
        gens = self._node_generations()
        node_names = sorted(gens)
        filt = self._post(
            "/scheduler/filter",
            {"Pod": pod.to_dict(), "NodeNames": node_names},
        )
        feasible = filt.get("NodeNames") or []
        if filt.get("Error") or not feasible:
            log.warning(
                "fleet scale-up: no feasible node (%s)",
                filt.get("Error") or "all filtered",
            )
            try:
                self.cluster.delete_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
            except Exception:
                pass
            return None
        # heterogeneity-aware target: among feasible nodes, prefer the
        # generation with the highest measured tokens/s/chip for this
        # class; scheduler feasibility order breaks ties
        rank = {g: i for i, g in enumerate(generation_pref)}
        target = min(
            feasible,
            key=lambda n: (rank.get(gens.get(n, ""), len(rank)),
                           feasible.index(n)),
        )
        bind = self._post(
            "/scheduler/bind",
            {
                "PodName": pod.metadata.name,
                "PodNamespace": pod.metadata.namespace,
                "PodUID": pod.metadata.uid,
                "Node": target,
            },
        )
        if bind.get("Error"):
            log.warning("fleet scale-up bind failed: %s", bind["Error"])
            try:
                self.cluster.delete_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
            except Exception:
                pass
            return None
        try:
            replica = self.spawner(pod, target)
        except Exception:
            # the pod is BOUND (chips charged, bind journaled) but no
            # serving process exists: delete it so reconciliation frees
            # the chips — otherwise every failed spawn leaks a bound
            # ghost replica and the still-breaching signals bind another
            # one next tick
            log.exception("fleet spawner failed; releasing bound pod")
            try:
                self.cluster.delete_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
            except Exception:
                log.exception("fleet spawner-rollback pod delete failed")
            return None
        self.replicas.add(replica)
        self.pods[replica.name] = pod
        return replica.name

    def scale_down(self, name: str, reason: str) -> bool:
        r = self.replicas.get(name)
        if r is None:
            return False
        # wait for the router's in-flight streams to the replica to end
        # (it is already draining — no new sessions arrive); jittered
        # growth instead of the old constant 20ms busy-poll — long
        # drains back off to coarse checks, short ones stay snappy
        from ..utils.backoff import Backoff

        bo = Backoff(base_s=0.02, max_s=0.5, jitter=0.3,
                     deadline_s=self.drain_timeout_s)
        while r.inflight > 0 and bo.sleep():
            pass
        if r.inflight > 0:
            return False  # still streaming: refuse, autoscaler restores
        pod = self.pods.pop(name, None)
        if self.releaser is not None:
            try:
                self.releaser(name, pod)
            except Exception:
                log.exception("fleet releaser failed for %s", name)
        if pod is not None:
            try:
                # the delete flows through watch/reconcile → forget_pod →
                # a journaled release, the same path any dead pod takes
                self.cluster.delete_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
            except Exception:
                log.exception("fleet scale-down pod delete failed")
        self.replicas.remove(name)
        return True
