"""Live gang resize: grow/shrink a running SPMD serving gang without a
cold restart.

The defrag subsystem already proved the primitive: ``migrate_pod``'s
journaled evict→rebind transaction moves a live pod with at most one
in-flight chunk lost (the ``defrag/hooks.py`` drain/elastic-resume
contract).  Resize extends that transaction shape to MEMBERSHIP change:

- **grow(gang, new_pods)** — admit new members into a live gang: filter
  → per-member allocation through the gang split-phase primitives
  (``gang_allocate``: validating commit + journaled ``bind``
  ``source="resize"``) → annotation-ledger write, all bracketed by the
  drain/elastic-resume hooks over the EXISTING members (an SPMD gang
  reshards when membership changes; every member pauses at a chunk
  boundary, so the whole resize costs each member at most one in-flight
  chunk — the migration contract, extended to resharding).
- **shrink(gang, victims)** — release members: journaled ``forget``
  (``source="resize"``) + annotation strip, same hook bracketing.

Both are ALL-OR-NOTHING: any failure reverses the executed members with
compensating journaled operations (the defrag round's reverse-order
rollback discipline), so the gang is never left part-resized.  Targets
are NOT cordoned (a cordon would reject the next member of a multi-pod
grow sharing the node; the validating per-member commit already turns a
placement race into a clean rollback).  When
a grow target does not fit anywhere, one defrag unblocking round is
tried first (``planner.run_round(want=...)``) — membership change and
migration compose through the same journal.

Every completed resize emits ONE ``resize`` journal record summarizing
the gang's new membership; replay verifies two invariants against the
rebuilt state (journal/replay.py):

- **chip conservation** — every member charges exactly the recorded
  per-member demand (chips can be added or released only WITH a member,
  never created or destroyed in flight), and
- **gang all-or-nothing** — the recorded membership matches the live
  member set exactly: no surviving evictee, no half-admitted joiner.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..core.request import pod_gang_key
from ..journal import JOURNAL
from ..metrics import FLEET_EVENTS, TimedLock
from ..tracing import TRACER

log = logging.getLogger("tpu-scheduler")


def member_chips(opt) -> int:
    """Whole-chip count a member's option charges (fractional allocs
    count their chip footprint — one shared chip is one chip)."""
    return sum(len(a.coords) for a in opt.allocs if a.needs_tpu)


class GangResizer:
    """Membership-change transactions over one scheduler engine.

    ``hooks``: ``defrag.hooks.MigrationHook`` list — ``drain(pod, node)``
    before the membership change, ``resume(pod, node)`` after (success
    AND rollback), applied to every member whose engine must pause for
    the reshard.  ``defrag``: optional DefragPlanner consulted when a
    grow target fits nowhere.  Rank 14 lock: below defrag (15) — the two
    never nest, but both sit under the engine registry lock (20) they
    acquire inside."""

    def __init__(
        self,
        sched,
        clientset,
        hooks: Optional[list] = None,
        defrag=None,
    ):
        self.sched = sched
        self.clientset = clientset
        self.hooks = list(hooks or [])
        self.defrag = defrag
        self._lock = TimedLock("resize", rank=14)
        self.resizes = 0
        self.last_result: Optional[dict] = None

    # -- membership view -----------------------------------------------------

    def members(self, gang: str) -> dict:
        """pod key → (node, Option) for the gang's LIVE members (the
        scheduler ledger filtered through the pods' gang annotation)."""
        with self.sched.lock:
            ledger = dict(self.sched.pod_maps)
        out = {}
        for key, (node, opt) in ledger.items():
            ns, _, name = key.partition("/")
            try:
                pod = self.clientset.get_pod(ns, name)
            except Exception:
                continue
            if pod_gang_key(pod) == gang and not pod.is_completed():
                out[key] = (node, opt, pod)
        return out

    # -- hook bracketing -----------------------------------------------------

    def _drain_all(self, members: dict) -> None:
        for key, (node, _opt, _pod) in sorted(members.items()):
            for h in self.hooks:
                try:
                    h.drain(key, node)
                except Exception:
                    log.exception("resize drain hook failed for %s", key)

    def _resume_all(self, members: dict) -> None:
        for key, (node, _opt, _pod) in sorted(members.items()):
            for h in self.hooks:
                try:
                    h.resume(key, node)
                except Exception:
                    log.exception("resize resume hook failed for %s", key)

    def _journal_resize(
        self, gang: str, members: dict, added, removed, chips_each: int,
        source: str, trace_id=None,
    ):
        """One ``resize`` record at the transaction's commit point —
        emitted under the ENGINE lock so it orders after every member
        bind/forget the transaction journaled and before any racing
        mutation (the same ordering rule every allocator record obeys)."""
        if not JOURNAL.enabled:
            return None
        with self.sched.lock:
            return JOURNAL.record(
                "resize",
                gang=gang,
                members=sorted(members),
                chips_per_member=chips_each,
                added=sorted(added) or None,
                removed=sorted(removed) or None,
                source=source,
                trace_id=trace_id,
            )

    # -- grow ----------------------------------------------------------------

    def grow(
        self,
        gang: str,
        new_pods: list,
        node_names: Optional[list] = None,
        generation_pref: Optional[list] = None,
    ) -> dict:
        """Admit ``new_pods`` (already created in the cluster, gang
        annotations in place) into the live gang.  ``node_names``
        defaults to every known node; ``generation_pref`` is a TPU
        generation ranking (``generation_preference(...)``'s output —
        the same list the autoscaler's executor consumes): feasible
        nodes are ordered by their allocator's generation against it,
        scheduler feasibility order breaking ties."""
        sched = self.sched
        if node_names is None:
            node_names = sorted(
                n.metadata.name for n in self.clientset.list_nodes()
            )
        with self._lock, TRACER.span(
            "fleet.resize", gang=gang, grow=len(new_pods),
        ) as sp:
            existing = self.members(gang)
            chips_each = (
                member_chips(next(iter(existing.values()))[1])
                if existing else 0
            )
            executed: list[tuple] = []  # (node, pod, opt)
            self._drain_all(existing)
            try:
                for pod in new_pods:
                    ok, _failed = sched.assume(list(node_names), pod)
                    if not ok and self.defrag is not None:
                        # one defrag unblocking round, then refilter —
                        # membership change composes with migration
                        # through the same journal.  Want = the member's
                        # own whole-chip demand (existing members when
                        # the gang is live, the pod's request otherwise)
                        from ..core.request import request_from_pod

                        tpu = [
                            u for u in request_from_pod(pod).units
                            if u.needs_tpu
                        ]
                        want = (
                            chips_each
                            or (tpu[0].chip_count if tpu else 0)
                            or 1,
                            1,
                        )
                        try:
                            self.defrag.run_round(sched=sched, want=want)
                        except RuntimeError:
                            pass
                        ok, _failed = sched.assume(list(node_names), pod)
                    if not ok:
                        raise RuntimeError(
                            f"resize grow: no feasible node for {pod.key}"
                        )
                    rank = {
                        g: i for i, g in enumerate(generation_pref or [])
                    }
                    def node_gen(n):
                        na = sched.allocators.get(n)
                        return getattr(na, "generation", "") if na else ""
                    target = min(
                        ok,
                        key=lambda n: (
                            rank.get(node_gen(n), len(rank)), n,
                        ),
                    )
                    # NO cordon here: cordoning the target would make the
                    # NEXT member's filter reject it (a multi-pod grow
                    # whose members share a node would spuriously fail),
                    # and gang_allocate is a validating commit anyway — a
                    # racing bind stealing the chips raises cleanly into
                    # the all-or-nothing rollback below
                    opt = sched.gang_allocate(target, pod, source="resize")
                    executed.append((target, pod, opt))
                    if chips_each == 0:
                        chips_each = member_chips(opt)
                    elif member_chips(opt) != chips_each:
                        raise RuntimeError(
                            f"resize grow: {pod.key} got "
                            f"{member_chips(opt)} chips, gang members "
                            f"hold {chips_each} (demand skew)"
                        )
                    sched.gang_annotate(pod, opt, target)
                after = dict(existing)
                for node, pod, opt in executed:
                    after[pod.key] = (node, opt, pod)
                seq = self._journal_resize(
                    gang, after, added=[p.key for _n, p, _o in executed],
                    removed=[], chips_each=chips_each, source="grow",
                    trace_id=sp.trace_id or None,
                )
                self.resizes += 1
                FLEET_EVENTS.inc("resize_executed")
                result = {
                    "gang": gang,
                    "action": "grow",
                    "added": [p.key for _n, p, _o in executed],
                    "members": sorted(after),
                    "chips_per_member": chips_each,
                    "journal_seq": seq,
                }
                self.last_result = result
                return result
            except Exception as e:
                FLEET_EVENTS.inc("resize_failed")
                # all-or-nothing: reverse executed members (journaled
                # forgets) + strip their ledger entries, reverse order
                for node, pod, opt in reversed(executed):
                    try:
                        sched.gang_unallocate(
                            node, pod, opt, source="resize_rollback"
                        )
                        sched.gang_strip_annotations(pod)
                    except Exception:
                        log.exception(
                            "resize rollback of %s failed — run a journal "
                            "replay audit", pod.key,
                        )
                raise RuntimeError(f"resize grow failed (rolled back): {e}") from e
            finally:
                self._resume_all(existing)

    # -- shrink --------------------------------------------------------------

    def shrink(self, gang: str, victim_keys: list) -> dict:
        """Release ``victim_keys`` from the live gang (journaled forgets
        + ledger strip), all-or-nothing with re-admission rollback."""
        sched = self.sched
        with self._lock, TRACER.span(
            "fleet.resize", gang=gang, shrink=len(victim_keys),
        ) as sp:
            existing = self.members(gang)
            missing = [k for k in victim_keys if k not in existing]
            if missing:
                raise RuntimeError(
                    f"resize shrink: {missing} not live members of {gang}"
                )
            remaining = {
                k: v for k, v in existing.items() if k not in victim_keys
            }
            chips_each = (
                member_chips(next(iter(remaining.values()))[1])
                if remaining
                else member_chips(existing[victim_keys[0]][1])
            )
            executed: list[tuple] = []
            self._drain_all(existing)
            try:
                for key in sorted(victim_keys):
                    node, opt, pod = existing[key]
                    sched.forget_pod(pod, source="resize")
                    executed.append((node, pod, opt))
                    sched.gang_strip_annotations(pod)
                seq = self._journal_resize(
                    gang, remaining, added=[],
                    removed=sorted(victim_keys), chips_each=chips_each,
                    source="shrink", trace_id=sp.trace_id or None,
                )
                self.resizes += 1
                FLEET_EVENTS.inc("resize_executed")
                result = {
                    "gang": gang,
                    "action": "shrink",
                    "removed": sorted(victim_keys),
                    "members": sorted(remaining),
                    "chips_per_member": chips_each,
                    "journal_seq": seq,
                }
                self.last_result = result
                return result
            except Exception as e:
                FLEET_EVENTS.inc("resize_failed")
                for node, pod, opt in reversed(executed):
                    try:
                        # re-admission: validating transact back onto the
                        # SAME chips (just freed; a racing bind would
                        # raise → the audit-loudly path)
                        sched.gang_apply_option(
                            node, pod, opt, source="resize_rollback"
                        )
                        sched.gang_annotate(pod, opt, node)
                    except Exception:
                        log.exception(
                            "resize shrink rollback of %s failed — run a "
                            "journal replay audit", pod.key,
                        )
                raise RuntimeError(
                    f"resize shrink failed (rolled back): {e}"
                ) from e
            finally:
                self._resume_all(existing)

    def debug_state(self) -> dict:
        return {"resizes": self.resizes, "last_result": self.last_result}
