"""Journal discipline: emit-site vs replay-dispatch exhaustiveness and the
allocator-mutation choke-point rules.

The flight recorder's whole value rests on two properties no test can
prove exhaustively:

1. **Every record type emitted anywhere in the package has a matching
   handler in the replay engine** — a new record type that replay treats
   as "unknown" silently drops state from every offline audit.  Emit
   sites are ``JOURNAL.record("type", ...)`` calls (string literal), the
   call sites of thin wrappers that forward a parameter into
   ``JOURNAL.record`` (``_journal_event``-style), and literal
   ``{"type": "..."}`` dicts inside the journal package itself (the
   checkpoint writer bypasses ``record()``).  Handler sets are the
   string constants ``replay()`` / ``what_if()`` compare the record type
   against.  Rules:
   - ``journal-unhandled-type``   — emitted, no ``replay()`` handler.
   - ``journal-whatif-unhandled`` — emitted, ``what_if()`` neither
     handles nor explicitly skips it (silent indifference is how the two
     functions drift; the MAINTENANCE NOTE in replay.py demands the
     mirror stays conscious).
   - ``journal-dead-handler``     — ``replay()`` handles a type nothing
     emits (stale handler, or a mutation path that stopped journaling).
   - ``journal-dynamic-type``     — a wrapper call site passes a
     non-literal record type: exhaustiveness can no longer be checked.

2. **Allocator mutations happen only inside the journaling perimeter.**
   - ``journal-setslot-outside-core`` — ``_set_slot``/``_set_total`` (the
     single packed-state choke point) called outside core/allocator.py +
     core/chip.py.
   - ``journal-unjournaled-mutation`` — a live ``NodeAllocator``
     mutation (``na.allocate/forget/add/refresh_from_node``) from a
     function that neither journals (directly or via a wrapper) nor is
     reachable only through journaling callers.  Clone-context ChipSet
     ``transact``/``cancel`` is exempt when the function visibly builds
     clones (``.clone()`` in its body) or lives in a core/replay module.
"""

from __future__ import annotations

import ast
from typing import Optional

from . import Finding
from .callgraph import PackageIndex, _dotted

MUTATION_ATTRS = ("allocate", "forget", "add", "refresh_from_node")
NA_RECEIVERS = ("na", "allocator", "nalloc")
CHIPSET_MUT_ATTRS = ("transact", "cancel")
CHIPSET_RECEIVERS = ("cs", "chips", "cs_to", "cs_from", "chipset")
CLONE_RECEIVERS = ("scratch", "clone", "clones", "sim", "dest")


def _is_journal_record(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == "record"
        and _dotted(f.value) is not None
        and _dotted(f.value).split(".")[-1] == "JOURNAL"
    )


def check_journal(index: PackageIndex, cfg) -> list:
    findings: list[Finding] = []

    emitted: dict[str, tuple] = {}     # type → (module, line)
    # wrapper function name → (positional index of type_ incl. self,
    # parameter name, defined-as-method)
    wrappers: dict[str, tuple] = {}
    dynamic_sites: list[tuple] = []    # (module, line, qualname, wrapper)

    # pass 1: direct emit sites + wrapper definitions
    for q, info in index.functions.items():
        params = [a.arg for a in info.node.args.args]
        for node in ast.walk(info.node):
            if not (isinstance(node, ast.Call) and _is_journal_record(node)):
                continue
            if not node.args:
                continue
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                emitted.setdefault(a0.value, (info.module, node.lineno))
            elif isinstance(a0, ast.Name) and a0.id in params:
                wrappers[info.name] = (
                    params.index(a0.id), a0.id, info.cls is not None
                )
            else:
                dynamic_sites.append(
                    (info.module, node.lineno, q, "JOURNAL.record")
                )

    # pass 2: wrapper call sites contribute their literal types.  A site
    # the scan cannot resolve (keyword mismatch, out-of-range, computed
    # value) is flagged journal-dynamic-type, NEVER skipped — a silently
    # uncounted emit site is exactly the hole this pass exists to close.
    for q, info in index.functions.items():
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            fname = None
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            if fname not in wrappers:
                continue
            pos, pname, is_method = wrappers[fname]
            # self-forwarding inside the wrapper itself is pass 1's site
            if info.name == fname:
                continue
            a = None
            for kw in node.keywords:  # keyword-style: _journal_event(type_="x")
                if kw.arg == pname:
                    a = kw.value
                    break
            if a is None:
                arg_pos = pos
                if is_method and isinstance(node.func, ast.Attribute):
                    arg_pos = pos - 1  # 'self' is implicit at a bound call
                if 0 <= arg_pos < len(node.args):
                    a = node.args[arg_pos]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                emitted.setdefault(a.value, (info.module, node.lineno))
            else:
                dynamic_sites.append((info.module, node.lineno, q, fname))

    # pass 3: literal {"type": "..."} dicts inside the journal package
    for rel, mi in index.modules.items():
        if "journal/" not in rel and not rel.startswith("journal"):
            continue
        if rel.endswith(cfg.replay_module):
            continue  # replay builds nothing it emits
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (
                        isinstance(k, ast.Constant) and k.value == "type"
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                    ):
                        emitted.setdefault(v.value, (rel, node.lineno))

    # handler sets from the replay module
    replay_mod = None
    for rel in index.modules:
        if rel.endswith(cfg.replay_module):
            replay_mod = rel
            break
    # the batch replay() wrapper delegates record dispatch to the
    # incremental ReplayEngine.apply (the journal-shipping follower's
    # entry point) — handler sets union both, so either layout lints
    replay_handled = _handled_types(
        index, replay_mod, "replay"
    ) | _handled_types(index, replay_mod, "ReplayEngine.apply")
    whatif_handled = _handled_types(index, replay_mod, "what_if")

    if replay_mod is not None:
        for t, (mod, line) in sorted(emitted.items()):
            if t not in replay_handled:
                findings.append(Finding(
                    rule="journal-unhandled-type",
                    file=mod, line=line,
                    key=f"journal-unhandled-type::{t}",
                    message=(
                        f"journal record type {t!r} is emitted here but "
                        f"{replay_mod}::replay() has no handler for it — "
                        "a new record type must never silently skip replay"
                    ),
                ))
            if whatif_handled and t not in whatif_handled:
                findings.append(Finding(
                    rule="journal-whatif-unhandled",
                    file=mod, line=line,
                    key=f"journal-whatif-unhandled::{t}",
                    message=(
                        f"journal record type {t!r} is emitted here but "
                        f"{replay_mod}::what_if() neither handles nor "
                        "explicitly skips it (add it to a handler or the "
                        "skip tuple — the replay mirror must stay conscious)"
                    ),
                ))
        for t in sorted(replay_handled - set(emitted)):
            if t in cfg.dead_handler_allow:
                continue
            findings.append(Finding(
                rule="journal-dead-handler",
                file=replay_mod, line=0,
                key=f"journal-dead-handler::{t}",
                message=(
                    f"replay() handles record type {t!r} but nothing in the "
                    "package emits it — stale handler, or a mutation path "
                    "that stopped journaling"
                ),
            ))

    for mod, line, q, wrapper in dynamic_sites:
        findings.append(Finding(
            rule="journal-dynamic-type",
            file=mod, line=line,
            key=f"journal-dynamic-type::{mod}::{q.split('::')[-1]}::{wrapper}",
            message=(
                f"{wrapper}() is passed a non-literal record type — "
                "emit/replay exhaustiveness cannot be checked for this site"
            ),
        ))

    # -- choke-point rules -------------------------------------------------
    journaling = _journaling_functions(index, wrappers)

    for q, info in index.functions.items():
        in_exempt = any(
            info.module.endswith(m) for m in cfg.journal_exempt_modules
        )
        in_setslot_mod = any(
            info.module.endswith(m) for m in cfg.setslot_modules
        )
        for node in ast.walk(info.node):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in ("_set_slot", "_set_total") and not in_setslot_mod:
                findings.append(Finding(
                    rule="journal-setslot-outside-core",
                    file=info.module, line=node.lineno,
                    key=(
                        f"journal-setslot-outside-core::{info.module}::"
                        f"{q.split('::')[-1]}"
                    ),
                    message=(
                        f"direct {attr}() call outside the ChipSet choke "
                        f"modules ({', '.join(cfg.setslot_modules)}) — all "
                        "packed-state writes must flow through ChipSet/"
                        "ChipRef so journaled commit points see them"
                    ),
                ))
                continue
            if in_exempt or in_setslot_mod or info.module.endswith("core/node.py"):
                continue
            recv = _recv_of(node.func.value)
            is_na_mut = attr in MUTATION_ATTRS and _looks_na(recv)
            is_cs_mut = (
                attr in CHIPSET_MUT_ATTRS
                and _looks_chipset(recv)
                and not info.has_clone_call
                and recv not in CLONE_RECEIVERS
                and recv not in _clone_locals(info)
            )
            if not (is_na_mut or is_cs_mut):
                continue
            if q in journaling:
                continue
            findings.append(Finding(
                rule="journal-unjournaled-mutation",
                file=info.module, line=node.lineno,
                key=(
                    f"journal-unjournaled-mutation::{info.module}::"
                    f"{q.split('::')[-1]}::{recv}.{attr}"
                ),
                message=(
                    f"live allocator mutation {recv}.{attr}() in a function "
                    "that never journals — every mutation must be reachable "
                    "only through a journaling choke point (JOURNAL.record "
                    "or a _journal_* wrapper in the same function)"
                ),
            ))
    return findings


def _recv_of(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        return _recv_of(node.value)
    return ""


def _looks_na(recv: str) -> bool:
    return recv in NA_RECEIVERS or recv.startswith("na_")


def _looks_chipset(recv: str) -> bool:
    return recv in CHIPSET_RECEIVERS


def _clone_locals(info) -> set:
    """Local names visibly bound to cloned chip state: assigned from a
    call whose name mentions 'clone' (``get_clone``/``_clone_ctx``/…) or
    from a subscript of a clone container.  Mutating a clone is planning,
    not a live allocator commit."""
    out = getattr(info, "_clone_locals", None)
    if out is not None:
        return out
    out = set()
    for node in ast.walk(info.node):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        src = None
        if isinstance(v, ast.Call):
            f = v.func
            fname = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else ""
            )
            if "clone" in fname:
                src = True
        elif isinstance(v, ast.Subscript):
            base = _recv_of(v.value)
            if base in CLONE_RECEIVERS:
                src = True
        if src:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
                elif isinstance(t, ast.Tuple):
                    for e in t.elts:
                        if isinstance(e, ast.Name):
                            out.add(e.id)
    info._clone_locals = out
    return out


def _journaling_functions(index: PackageIndex, wrappers: dict) -> set:
    """Functions inside the journaling perimeter: a direct JOURNAL.record
    call, or a call (by name) to a function that itself emits — the
    ``_journal_event``/``_journal_migrate``/``_journal_resize`` wrapper
    pattern, whether or not the wrapper forwards a type parameter."""
    direct = set()
    for q, info in index.functions.items():
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call) and _is_journal_record(node):
                direct.add(q)
                break
    emitter_names = {q.split("::")[-1].split(".")[-1] for q in direct}
    emitter_names.update(wrappers)
    out = set(direct)
    for q, info in index.functions.items():
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            fname = None
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            if fname in emitter_names:
                out.add(q)
                break
    return out


def _handled_types(index: PackageIndex, replay_mod: Optional[str], func: str) -> set:
    """String constants the named function compares a record's type
    against (``t == "x"``, ``t in ("a", "b")``)."""
    if replay_mod is None:
        return set()
    info = index.functions.get(f"{replay_mod}::{func}")
    if info is None:
        return set()
    # the dispatch variable: any name assigned from rec.get("type") /
    # rec["type"] — only comparisons against THAT name count (the replay
    # body compares plenty of other strings)
    type_vars = set()
    for node in ast.walk(info.node):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        v = node.value
        if (
            isinstance(v, ast.Call)
            and isinstance(v.func, ast.Attribute)
            and v.func.attr == "get"
            and v.args
            and isinstance(v.args[0], ast.Constant)
            and v.args[0].value == "type"
        ) or (
            isinstance(v, ast.Subscript)
            and isinstance(v.slice, ast.Constant)
            and v.slice.value == "type"
        ):
            type_vars.add(tgt.id)
    out = set()
    for node in ast.walk(info.node):
        if not isinstance(node, ast.Compare):
            continue
        if not (isinstance(node.left, ast.Name) and node.left.id in type_vars):
            continue
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)):
                if isinstance(comp, ast.Constant) and isinstance(comp.value, str):
                    out.add(comp.value)
            elif isinstance(op, (ast.In, ast.NotIn)):
                if isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                    for elt in comp.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            out.add(elt.value)
    return out
