"""AST package index + heuristic call graph for the analysis passes.

Resolution strategy (deliberately simple, documented so findings can be
read back to source):

- Functions are keyed ``relpath::Class.method`` / ``relpath::func``.
- ``self.m(...)`` resolves within the enclosing class, then its
  package-local base classes.
- Well-known receiver names resolve through ``RECEIVER_CLASS_HINTS``
  (``sched`` → TPUUnitScheduler, ``na``/``na_*`` → NodeAllocator, the
  process-global singletons JOURNAL/TRACER/PROFILER, …).
- A bare name resolves to a same-module def, then a ``from x import y``
  target, then a unique package-wide def.
- Anything else falls back to every package def of that name, capped at
  ``MAX_NAME_CANDIDATES`` and filtered through ``COMMON_NAMES`` —
  over-approximate where cheap, silent where the name is too generic to
  mean anything.

Lock model: ``TimedLock("name", rank=N[, reentrant=True])`` assignments
to ``self.attr`` (or module globals) define RANKED locks;
``threading.Lock()/RLock()/Condition()`` define PLAIN locks (they opt out
of the rank hierarchy but still count for the finalizer rule).  A
``with``-block over a resolved lock establishes held-context for every
call lexically inside it; bare ``.acquire()`` marks the function as an
acquirer without establishing context (release-flow is not modeled).
Try-locks (``blocking=False``) and timeout-bounded acquires are exempt,
mirroring the runtime checker in ``metrics.TimedLock``.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Optional

# Receiver variable/attr basename → class name.  The codebase's naming is
# consistent enough that this table IS the type inference.
RECEIVER_CLASS_HINTS = {
    "sched": "TPUUnitScheduler",
    "scheduler": "TPUUnitScheduler",
    "engine": "TPUUnitScheduler",
    "na": "NodeAllocator",
    "allocator": "NodeAllocator",
    "nalloc": "NodeAllocator",
    "planner": "DefragPlanner",
    "resizer": "GangResizer",
    "coordinator": "GangCoordinator",
    "JOURNAL": "Journal",
    "TRACER": "Tracer",
    "PROFILER": "WorkloadProfiler",
}

# Names too generic for package-wide fallback resolution (they still
# resolve through self/hints).
COMMON_NAMES = frozenset(
    "get set add pop push put items keys values append extend update copy "
    "clear close open read write send recv join split strip sort index "
    "count remove insert encode decode format replace start stop run flush "
    "lower upper status name keys get_pod info debug warning error "
    "exception to_dict from_record record_step wait notify notify_all "
    "acquire release submit result cancel done "
    "match fullmatch search sub findall finditer group groups compile".split()
)
MAX_NAME_CANDIDATES = 4

# Direct blocking primitives (dotted-name match) for the
# no-blocking-under-control-plane-lock rule.
BLOCKING_CALLS = {
    "urllib.request.urlopen": "HTTP (urlopen)",
    "urlopen": "HTTP (urlopen)",
    "os.fsync": "fsync",
    "fsync": "fsync",
    "subprocess.run": "subprocess",
    "subprocess.Popen": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "time.sleep": "sleep",
    "socket.create_connection": "socket connect",
}
# any call whose dotted path starts with one of these roots is treated as
# potentially blocking (XLA compile/dispatch can stall for seconds)
BLOCKING_ROOTS = ("jax.",)


@dataclass(frozen=True)
class LockDef:
    key: str          # "Class.attr" or "module_relpath::NAME"
    lock_name: str    # TimedLock label, or the attr/global name
    rank: Optional[int]
    reentrant: bool
    kind: str         # "timed" | "plain"


@dataclass
class Acquire:
    lock: LockDef
    line: int
    bare: bool  # .acquire() outside a with (no held-context established)
    held: tuple = ()  # LockDefs with-held at the acquire site


@dataclass
class CallSite:
    recv: str         # receiver basename ('' = bare name, 'self', 'sched', …)
    attr: str         # called name
    line: int
    held: tuple       # LockDefs held (with-context) at this site


@dataclass
class FunctionInfo:
    qualname: str
    module: str       # relpath
    cls: Optional[str]
    name: str
    line: int
    acquires: list = field(default_factory=list)   # [Acquire]
    calls: list = field(default_factory=list)      # [CallSite]
    blocking: list = field(default_factory=list)   # [(label, line, held)]
    has_clone_call: bool = False                   # '.clone(' appears inside
    node: object = None                            # the ast def node


def _dotted(node) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _recv_basename(node) -> str:
    """Basename of a call/lock receiver: self.sched.lock → 'sched';
    clones[n].transact → 'clones'; sched.lock → 'sched'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Subscript):
        return _recv_basename(node.value)
    if isinstance(node, ast.Call):
        return _recv_basename(node.func)
    return ""


def _lock_ctor(call: ast.Call) -> Optional[tuple]:
    """(kind, lock_name, rank, reentrant) when ``call`` constructs a lock."""
    name = _dotted(call.func)
    if name is None:
        return None
    base = name.split(".")[-1]
    if base == "TimedLock":
        lock_name = ""
        if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
            call.args[0].value, str
        ):
            lock_name = call.args[0].value
        rank = None
        reentrant = False
        for kw in call.keywords:
            if kw.arg == "rank" and isinstance(kw.value, ast.Constant):
                rank = kw.value.value
            if kw.arg == "reentrant" and isinstance(kw.value, ast.Constant):
                reentrant = bool(kw.value.value)
        if len(call.args) > 1 and isinstance(call.args[1], ast.Constant):
            reentrant = bool(call.args[1].value)
        return ("timed", lock_name, rank, reentrant)
    if base in ("Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"):
        if name in (base, f"threading.{base}"):
            return ("plain", base, None, base in ("RLock", "Condition"))
    return None


class _FunctionScanner(ast.NodeVisitor):
    """Populate one FunctionInfo: acquisitions, held-context call sites,
    direct blocking primitives."""

    def __init__(self, index: "PackageIndex", info: FunctionInfo, cls: Optional[str]):
        self.index = index
        self.info = info
        self.cls = cls
        self.held: list[LockDef] = []

    # nested defs get their own FunctionInfo; don't descend here
    def visit_FunctionDef(self, node):
        if node is not self.info.node:
            return
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self.generic_visit(node)

    def _resolve_lock(self, expr) -> Optional[LockDef]:
        return self.index.resolve_lock(expr, self.info.module, self.cls)

    def visit_With(self, node):
        resolved = []
        for item in node.items:
            ld = self._resolve_lock(item.context_expr)
            if ld is not None:
                self.info.acquires.append(
                    Acquire(
                        ld, item.context_expr.lineno, bare=False,
                        held=tuple(self.held) + tuple(resolved),
                    )
                )
                resolved.append(ld)
            else:
                self.visit(item.context_expr)
        self.held.extend(resolved)
        for stmt in node.body:
            self.visit(stmt)
        for _ in resolved:
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        func = node.func
        dotted = _dotted(func)
        # blocking primitive?
        if dotted is not None:
            label = BLOCKING_CALLS.get(dotted)
            if label is None and any(
                dotted.startswith(r) for r in BLOCKING_ROOTS
            ):
                label = f"jax dispatch ({dotted})"
            if label is not None:
                self.info.blocking.append(
                    (label, node.lineno, tuple(self.held))
                )
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr == "clone":
                self.info.has_clone_call = True
            if attr == "acquire":
                ld = self._resolve_lock(func.value)
                if ld is not None and not _acquire_exempt(node):
                    # held context recorded so lockdep can flag a bare
                    # acquire INSIDE a with-held lock in the same
                    # function (neither the direct-nesting walk nor the
                    # call-path rule sees that shape)
                    self.info.acquires.append(
                        Acquire(ld, node.lineno, bare=True,
                                held=tuple(self.held))
                    )
                self.generic_visit(node)
                return
            recv = ""
            if isinstance(func.value, ast.Name):
                recv = func.value.id
            else:
                recv = _recv_basename(func.value)
            self.info.calls.append(
                CallSite(recv, attr, node.lineno, tuple(self.held))
            )
        elif isinstance(func, ast.Name):
            self.info.calls.append(
                CallSite("", func.id, node.lineno, tuple(self.held))
            )
        self.generic_visit(node)


def _acquire_exempt(call: ast.Call) -> bool:
    """Try-locks and timeout-bounded acquires cannot deadlock — same
    exemption as the runtime checker."""
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and a0.value is False:
            return True
        if len(call.args) > 1:  # explicit timeout positional
            a1 = call.args[1]
            if not (isinstance(a1, ast.Constant) and a1.value in (-1,)):
                return True
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
        if kw.arg == "timeout":
            if not (isinstance(kw.value, ast.Constant) and kw.value.value == -1):
                return True
    return False


@dataclass
class ModuleInfo:
    relpath: str
    tree: ast.Module
    source: str
    # name → list of qualnames (module-level defs incl. nested)
    defs: dict = field(default_factory=dict)
    # from-import: local name → imported name
    from_imports: dict = field(default_factory=dict)
    # module-level mutable containers: name → lineno
    mutable_globals: dict = field(default_factory=dict)


class PackageIndex:
    def __init__(self, root: str):
        self.root = root
        self.modules: dict[str, ModuleInfo] = {}          # relpath → ModuleInfo
        self.functions: dict[str, FunctionInfo] = {}      # qualname → info
        self.classes: dict[str, dict] = {}                # class → {module, bases, methods{name→qualname}}
        self.class_locks: dict[tuple, LockDef] = {}       # (class, attr) → LockDef
        self.module_locks: dict[tuple, LockDef] = {}      # (relpath, name) → LockDef
        self.by_name: dict[str, list] = {}                # func name → [qualname]
        self.finalizer_roots: list[tuple] = []            # (qualname, via, line)

    # -- loading -------------------------------------------------------------

    @classmethod
    def load(cls, root: str) -> "PackageIndex":
        from . import iter_py_files

        idx = cls(root)
        for path in iter_py_files(root):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as fh:
                    src = fh.read()
                tree = ast.parse(src, filename=path)
            except (OSError, SyntaxError):
                continue
            idx.modules[rel] = ModuleInfo(rel, tree, src)
        for mi in idx.modules.values():
            idx._collect_defs(mi)
        for mi in idx.modules.values():
            idx._collect_finalizers(mi)
        for mi in idx.modules.values():
            idx._scan_functions(mi)
        return idx

    def _collect_finalizers(self, mi: ModuleInfo) -> None:
        """Runs after EVERY module's defs are registered, so a finalize
        callback defined in another module still resolves."""
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted in ("weakref.finalize", "finalize") and len(node.args) >= 2:
                    cb_name = _dotted(node.args[1])
                    if cb_name:
                        for q in self.by_name.get(cb_name.split(".")[-1], []):
                            self.finalizer_roots.append(
                                (q, "weakref.finalize", node.lineno)
                            )
        for cname, entry in self.classes.items():
            if entry["module"] == mi.relpath and "__del__" in entry["methods"]:
                self.finalizer_roots.append(
                    (entry["methods"]["__del__"], "__del__", 0)
                )

    def _collect_defs(self, mi: ModuleInfo) -> None:
        for node in mi.tree.body:
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    mi.from_imports[alias.asname or alias.name] = alias.name
            elif isinstance(node, ast.Assign):
                self._module_assign(mi, node)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                # annotated module globals: `_BUF: list = []`
                if isinstance(node.target, ast.Name):
                    synth = ast.Assign(targets=[node.target], value=node.value)
                    ast.copy_location(synth, node)
                    self._module_assign(mi, synth)
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.ClassDef):
                entry = self.classes.setdefault(
                    node.name,
                    {"module": mi.relpath, "bases": [], "methods": {}},
                )
                entry["bases"] = [
                    b for b in (_dotted(x) for x in node.bases) if b
                ]
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        q = f"{mi.relpath}::{node.name}.{item.name}"
                        entry["methods"][item.name] = q
                        self._register_function(mi, item, node.name, q)
                    elif isinstance(item, ast.Assign):
                        pass  # class-level locks are rare; self.attr wins
                # lock attrs assigned in any method body
                for item in ast.walk(node):
                    if isinstance(item, ast.Assign) and len(item.targets) == 1:
                        tgt = item.targets[0]
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and isinstance(item.value, ast.Call)
                        ):
                            lk = _lock_ctor(item.value)
                            if lk is not None:
                                kind, lname, rank, reent = lk
                                self.class_locks[(node.name, tgt.attr)] = LockDef(
                                    key=f"{node.name}.{tgt.attr}",
                                    lock_name=lname or tgt.attr,
                                    rank=rank, reentrant=reent, kind=kind,
                                )
        # module-level (non-class) functions, incl. nested
        for node in ast.walk(mi.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._enclosing_class(mi, node) is None:
                    q = f"{mi.relpath}::{node.name}"
                    self._register_function(mi, node, None, q)

    def _module_assign(self, mi: ModuleInfo, node: ast.Assign) -> None:
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        v = node.value
        if isinstance(v, ast.Call):
            lk = _lock_ctor(v)
            if lk is not None:
                kind, lname, rank, reent = lk
                self.module_locks[(mi.relpath, name)] = LockDef(
                    key=f"{mi.relpath}::{name}", lock_name=lname or name,
                    rank=rank, reentrant=reent, kind=kind,
                )
                return
            ctor = _dotted(v.func)
            if ctor in ("list", "dict", "set", "collections.deque", "deque") \
                    and not v.args:
                mi.mutable_globals[name] = node.lineno
        elif isinstance(v, (ast.List, ast.Dict, ast.Set)):
            # literal-initialized module containers: only EMPTY ones are
            # runtime mutation buffers; populated literals are config
            # tables (never mutated off-lock by design)
            if isinstance(v, ast.List) and not v.elts:
                mi.mutable_globals[name] = node.lineno
            elif isinstance(v, ast.Dict) and not v.keys:
                mi.mutable_globals[name] = node.lineno
            elif isinstance(v, ast.Set) and not v.elts:
                mi.mutable_globals[name] = node.lineno

    def _enclosing_class(self, mi: ModuleInfo, func) -> Optional[str]:
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if item is func:
                        return node.name
        return None

    def _register_function(self, mi, node, cls, qualname) -> None:
        if qualname in self.functions:
            return
        info = FunctionInfo(
            qualname=qualname, module=mi.relpath, cls=cls,
            name=node.name, line=node.lineno, node=node,
        )
        self.functions[qualname] = info
        self.by_name.setdefault(node.name, []).append(qualname)
        mi.defs.setdefault(node.name, []).append(qualname)

    def _scan_functions(self, mi: ModuleInfo) -> None:
        for info in self.functions.values():
            if info.module != mi.relpath:
                continue
            scanner = _FunctionScanner(self, info, info.cls)
            scanner.visit(info.node)

    # -- resolution ----------------------------------------------------------

    def resolve_lock(self, expr, module: str, cls: Optional[str]) -> Optional[LockDef]:
        if isinstance(expr, ast.Name):
            return self.module_locks.get((module, expr.id))
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        recv = expr.value
        if isinstance(recv, ast.Name) and recv.id == "self" and cls:
            ld = self._class_lock(cls, attr)
            if ld is not None:
                return ld
        # foreign receiver: hint table, then unique-attr fallback
        base = _recv_basename(recv) if not (
            isinstance(recv, ast.Name) and recv.id == "self"
        ) else ""
        hint = self._hint_class(base)
        if hint is not None:
            ld = self._class_lock(hint, attr)
            if ld is not None:
                return ld
        cands = [
            ld for (c, a), ld in self.class_locks.items() if a == attr
        ]
        if len(cands) == 1:
            return cands[0]
        return None

    def _class_lock(self, cls: str, attr: str) -> Optional[LockDef]:
        seen = set()
        stack = [cls]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            ld = self.class_locks.get((c, attr))
            if ld is not None:
                return ld
            entry = self.classes.get(c)
            if entry:
                stack.extend(b.split(".")[-1] for b in entry["bases"])
        return None

    def _hint_class(self, base: str) -> Optional[str]:
        if not base:
            return None
        if base in RECEIVER_CLASS_HINTS:
            return RECEIVER_CLASS_HINTS[base]
        if base.startswith("na_"):
            return "NodeAllocator"
        if base.startswith("sched"):
            return "TPUUnitScheduler"
        return None

    def resolve_call(self, site: CallSite, caller: FunctionInfo) -> list:
        """Candidate callee qualnames for a call site."""
        attr, recv = site.attr, site.recv
        if recv == "self" and caller.cls:
            q = self._class_method(caller.cls, attr)
            if q:
                return [q]
        if recv == "":
            mi = self.modules.get(caller.module)
            if mi and attr in mi.defs:
                return list(mi.defs[attr])
            if mi and attr in mi.from_imports:
                target = mi.from_imports[attr].split(".")[-1]
                cands = self.by_name.get(target, [])
                if len(cands) == 1:
                    return list(cands)
            cands = self.by_name.get(attr, [])
            if len(cands) == 1:
                return list(cands)
            return []
        hint = self._hint_class(recv)
        if hint is not None:
            q = self._class_method(hint, attr)
            if q:
                return [q]
        if attr in COMMON_NAMES:
            return []
        cands = self.by_name.get(attr, [])
        if 1 <= len(cands) <= MAX_NAME_CANDIDATES:
            return list(cands)
        return []

    def _class_method(self, cls: str, name: str) -> Optional[str]:
        seen = set()
        stack = [cls]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            entry = self.classes.get(c)
            if not entry:
                continue
            q = entry["methods"].get(name)
            if q:
                return q
            stack.extend(b.split(".")[-1] for b in entry["bases"])
        return None

    # -- propagation helpers -------------------------------------------------

    def propagate(self, direct: dict) -> dict:
        """Generic transitive closure over the call graph.

        ``direct``: qualname → dict payload {token: witness} where witness
        is ``(line, via_qualname_or_None)``.  Returns the fixed point:
        each function's payload merged with every callee's, the witness
        recording WHICH call site imported the token (for path
        reconstruction in messages)."""
        out = {q: dict(d) for q, d in direct.items()}
        for q in self.functions:
            out.setdefault(q, {})
        changed = True
        while changed:
            changed = False
            for q, info in self.functions.items():
                mine = out[q]
                for site in info.calls:
                    for callee in self.resolve_call(site, info):
                        if callee == q:
                            continue
                        for token in out.get(callee, ()):
                            if token not in mine:
                                mine[token] = (site.line, callee)
                                changed = True
        return out

    def witness_path(self, closure: dict, qualname: str, token, limit: int = 8) -> str:
        """Human-readable call chain from ``qualname`` to the function
        that directly carries ``token``."""
        parts = [qualname]
        cur = qualname
        for _ in range(limit):
            wit = closure.get(cur, {}).get(token)
            if wit is None or wit[1] is None:
                break
            cur = wit[1]
            parts.append(cur)
        return " → ".join(p.split("::")[-1] for p in parts)
