"""Baseline mechanism: explicit grandfathering of pre-existing findings.

The baseline file (``tools/analysis_baseline.json``) is a list of
entries, each carrying the finding's stable ``key`` and a WRITTEN
justification:

    {"entries": [
        {"key": "lockdep-blocking::defrag/__init__.py::...",
         "justification": "planner lock exists to serialize rounds; ..."}
    ]}

Semantics (all three outcomes fail the gate):

- a finding whose key is NOT in the baseline is **new** → fail;
- a baseline entry matching NO current finding is **stale** → fail (the
  violation was fixed: delete the entry, or the key drifted: re-anchor
  it) — this is what makes suppression reversible instead of rot;
- an entry with an empty/missing justification is **invalid** → fail
  (grandfathering without a reason is just silence).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass
class BaselineDiff:
    new: list = field(default_factory=list)        # [Finding]
    suppressed: list = field(default_factory=list) # [Finding]
    stale: list = field(default_factory=list)      # [key]
    invalid: list = field(default_factory=list)    # [reason]

    @property
    def ok(self) -> bool:
        return not (self.new or self.stale or self.invalid)


def load_baseline(path: str) -> dict:
    """key → justification.  Raises ValueError on malformed entries."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return {}
    entries = data.get("entries", data if isinstance(data, list) else [])
    out = {}
    for i, e in enumerate(entries):
        key = e.get("key", "")
        just = (e.get("justification") or "").strip()
        if not key:
            raise ValueError(f"baseline entry {i}: missing key")
        if key in out:
            raise ValueError(f"baseline entry {i}: duplicate key {key!r}")
        out[key] = just
    return out


def diff_baseline(findings: list, baseline: dict) -> BaselineDiff:
    d = BaselineDiff()
    for key, just in baseline.items():
        if not just:
            d.invalid.append(
                f"baseline entry {key!r} has no justification — "
                "grandfathering without a reason is just silence"
            )
    matched = set()
    for f in findings:
        if f.key in baseline:
            matched.add(f.key)
            d.suppressed.append(f)
        else:
            d.new.append(f)
    d.stale = sorted(set(baseline) - matched)
    return d


def write_baseline(path: str, findings: list, justification: str = "TODO: justify") -> None:
    """Emit a baseline covering the current findings (the bootstrap /
    re-anchor workflow; every generated entry still needs a real
    justification before the gate passes)."""
    entries = []
    seen = set()
    for f in sorted(findings, key=lambda f: f.key):
        if f.key in seen:
            continue  # keys are line-free; two sites can share one
        seen.add(f.key)
        entries.append({
            "key": f.key, "justification": justification,
            "finding": f"{f.file}:{f.line}: {f.message}",
        })
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"entries": entries}, fh, indent=1)
        fh.write("\n")
