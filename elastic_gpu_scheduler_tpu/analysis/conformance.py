"""Conformance lints: the operational contracts OPERATIONS.md promises.

- ``conformance-metric-name``        — a registered metric whose name does
  not follow the ``tpu_*`` scheme (every exported series shares the
  prefix so fleet dashboards can glob one namespace).
- ``conformance-metric-undocumented``— a registered metric name absent
  from OPERATIONS.md (an operator paging through the runbook must be
  able to find every series /metrics can emit).
- ``conformance-debug-index``        — a ``/debug/*`` route dispatched by
  the HTTP server but missing from the ``/debug/`` index page (the index
  is the discovery surface; an unlisted endpoint is invisible).
- ``conformance-offlock-mutation``   — a module-level mutable container
  mutated outside any lock and outside the documented GIL-atomic
  allowlist.  Plain-list appends/slice-dels ARE GIL-atomic in CPython,
  but each such site is a load-bearing concurrency argument that must be
  listed (with its pairing reader) in ``AnalysisConfig.gil_atomic_allowlist``,
  not discovered in a post-mortem.
"""

from __future__ import annotations

import ast
import re

from . import Finding
from .callgraph import PackageIndex, _dotted

METRIC_CTORS = ("Counter", "Gauge", "Histogram", "LazyGauge")
MUTATING_METHODS = (
    "append", "extend", "insert", "pop", "remove", "clear", "update",
    "setdefault", "add", "discard", "popitem", "appendleft", "popleft",
)


def check_conformance(index: PackageIndex, cfg) -> list:
    findings: list[Finding] = []
    findings.extend(_check_metrics(index, cfg))
    findings.extend(_check_debug_index(index, cfg))
    findings.extend(_check_offlock_globals(index, cfg))
    return findings


# -- metrics ----------------------------------------------------------------


def _check_metrics(index: PackageIndex, cfg) -> list:
    out = []
    for rel, mi in index.modules.items():
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is None or dotted.split(".")[-1] not in METRIC_CTORS:
                continue
            # only REGISTERED metrics (REGISTRY.register(Ctor(...)) or a
            # module-level CTOR assignment in a metrics module) are export
            # surface; ad-hoc local Histograms in tests/tools are not
            if not _is_registered(mi, node):
                continue
            if not node.args or not (
                isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            name = node.args[0].value
            if not name.startswith("tpu_"):
                out.append(Finding(
                    rule="conformance-metric-name",
                    file=rel, line=node.lineno,
                    key=f"conformance-metric-name::{name}",
                    message=(
                        f"registered metric {name!r} does not follow the "
                        "tpu_* naming scheme"
                    ),
                ))
            if cfg.ops_text and name not in cfg.ops_text:
                out.append(Finding(
                    rule="conformance-metric-undocumented",
                    file=rel, line=node.lineno,
                    key=f"conformance-metric-undocumented::{name}",
                    message=(
                        f"registered metric {name!r} is not mentioned in "
                        "OPERATIONS.md — document every exported series"
                    ),
                ))
    return out


def _is_registered(mi, ctor_call: ast.Call) -> bool:
    """True when the ctor call is the argument of REGISTRY.register(...)."""
    for node in ast.walk(mi.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted is None or not dotted.endswith("register"):
            continue
        for a in node.args:
            if a is ctor_call:
                return True
    return False


# -- /debug index -----------------------------------------------------------

INDEX_EXEMPT = ("/debug", "/debug/", "/debug/pprof", "/debug/pprof/")


def _check_debug_index(index: PackageIndex, cfg) -> list:
    out = []
    for rel, mi in index.modules.items():
        if not rel.endswith("routes.py"):
            continue
        index_text = ""
        for node in ast.walk(mi.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and "<html>" in node.value
                and "/debug/" in node.value
            ):
                index_text += node.value
        if not index_text:
            continue
        endpoints = {}
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Constant):
                continue
            v = node.value
            if not (isinstance(v, str) and v.startswith("/debug/") and len(v) > 7):
                continue
            if v in INDEX_EXEMPT:
                continue
            endpoints.setdefault(v.rstrip("/"), node.lineno)
        for ep, line in sorted(endpoints.items()):
            if ep in INDEX_EXEMPT:
                continue
            # boundary match, not substring: "/debug/frag" must not pass
            # because the index lists "/debug/fragmentation"
            if not re.search(re.escape(ep) + r"(?![\w-])", index_text):
                out.append(Finding(
                    rule="conformance-debug-index",
                    file=rel, line=line,
                    key=f"conformance-debug-index::{ep}",
                    message=(
                        f"debug endpoint {ep!r} is served but absent from "
                        "the /debug/ index page — unlisted endpoints are "
                        "invisible to operators"
                    ),
                ))
    return out


# -- off-lock global mutations ----------------------------------------------


def _check_offlock_globals(index: PackageIndex, cfg) -> list:
    out = []
    allow = set(cfg.gil_atomic_allowlist)
    for q, info in index.functions.items():
        mi = index.modules.get(info.module)
        if mi is None or not mi.mutable_globals:
            continue
        for node, held in _walk_with_held(index, info):
            name = _mutated_global(node, mi.mutable_globals)
            if name is None:
                continue
            if held:
                continue  # under some lock: the lock is the argument
            if (info.module, name) in allow or any(
                info.module.endswith(m) and n == name for m, n in allow
            ):
                continue
            out.append(Finding(
                rule="conformance-offlock-mutation",
                file=info.module,
                line=node.lineno,
                key=(
                    f"conformance-offlock-mutation::{info.module}::"
                    f"{q.split('::')[-1]}::{name}"
                ),
                message=(
                    f"module-level container {name!r} mutated outside any "
                    "lock — GIL-atomicity-dependent patterns must be listed "
                    "in the documented allowlist (analysis.AnalysisConfig."
                    "gil_atomic_allowlist) with their pairing reader"
                ),
            ))
    return out


def _walk_with_held(index, info):
    """Yield (node, held_locks) for every statement-level node in the
    function, tracking with-lock context."""
    import ast as _ast

    def visit(node, held):
        if isinstance(node, (_ast.With, _ast.AsyncWith)):
            # ANY with-context (even one the resolver can't type) counts
            # as "locked": this lint is about mutations with no
            # synchronization in sight
            ctx = held + [object()]
            for stmt in node.body:
                yield from visit(stmt, ctx)
        elif isinstance(node, (_ast.FunctionDef, _ast.AsyncFunctionDef,
                               _ast.Lambda, _ast.ClassDef)):
            return
        else:
            yield (node, held)
            for child in _ast.iter_child_nodes(node):
                yield from visit(child, held)

    for stmt in info.node.body:
        yield from visit(stmt, [])


def _mutated_global(node, mutable_globals) -> str:
    import ast as _ast

    if isinstance(node, _ast.Call) and isinstance(node.func, _ast.Attribute):
        if node.func.attr in MUTATING_METHODS and isinstance(
            node.func.value, _ast.Name
        ):
            name = node.func.value.id
            if name in mutable_globals:
                return name
    if isinstance(node, _ast.Delete):
        for t in node.targets:
            if isinstance(t, _ast.Subscript) and isinstance(t.value, _ast.Name):
                if t.value.id in mutable_globals:
                    return t.value.id
    if isinstance(node, (_ast.Assign, _ast.AugAssign)):
        targets = node.targets if isinstance(node, _ast.Assign) else [node.target]
        for t in targets:
            if isinstance(t, _ast.Subscript) and isinstance(t.value, _ast.Name):
                if t.value.id in mutable_globals:
                    return t.value.id
    return None
