"""CLI: run the invariant analysis over the package and diff against the
checked-in baseline.

    python -m elastic_gpu_scheduler_tpu.analysis [--baseline PATH]
        [--root DIR] [--write-baseline] [--json]

Exit 0 = clean (possibly with explicitly-baselined findings), 1 = new
findings / stale baseline entries / invalid baseline.  ``make
check-analysis`` wraps this plus an injection self-test
(tools/check_analysis.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import AnalysisConfig, default_ops_text, package_root, run_all
from .baseline import diff_baseline, load_baseline, write_baseline


def default_baseline_path() -> str:
    repo = os.path.dirname(package_root())
    return os.path.join(repo, "tools", "analysis_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="elastic_gpu_scheduler_tpu.analysis")
    ap.add_argument("--root", default=package_root(),
                    help="package directory to analyze")
    ap.add_argument("--baseline", default=default_baseline_path())
    ap.add_argument("--write-baseline", action="store_true",
                    help="overwrite the baseline with the current findings "
                         "(each entry still needs a written justification)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    cfg = AnalysisConfig(ops_text=default_ops_text())
    findings = run_all(args.root, cfg)

    if args.write_baseline:
        write_baseline(args.baseline, findings)
        print(f"wrote {len(findings)} entries to {args.baseline}")
        return 0

    try:
        baseline = load_baseline(args.baseline)
    except ValueError as e:
        print(f"INVALID BASELINE: {e}", file=sys.stderr)
        return 1
    diff = diff_baseline(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "new": [f.__dict__ for f in diff.new],
            "suppressed": [f.key for f in diff.suppressed],
            "stale": diff.stale,
            "invalid": diff.invalid,
        }, indent=1))
        return 0 if diff.ok else 1

    if diff.suppressed:
        print(f"{len(diff.suppressed)} finding(s) suppressed by baseline "
              f"({os.path.relpath(args.baseline)})")
    for f in diff.new:
        print(f"NEW: {f.render()}")
    for k in diff.stale:
        print(f"STALE BASELINE ENTRY (violation gone — delete it): {k}")
    for msg in diff.invalid:
        print(f"INVALID BASELINE: {msg}")
    if diff.ok:
        print(f"analysis clean: {len(findings)} finding(s), all baselined "
              "with justification")
        return 0
    print(
        f"\nanalysis FAILED: {len(diff.new)} new, {len(diff.stale)} stale, "
        f"{len(diff.invalid)} invalid baseline entr(ies).\n"
        "How to read a finding: OPERATIONS.md §'Static analysis & "
        "sanitizers'.  Fix the violation, or baseline it WITH a written "
        "justification in tools/analysis_baseline.json."
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
