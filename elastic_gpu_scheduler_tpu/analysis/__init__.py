"""Invariant analysis plane: mechanical checkers for the conventions prose
used to carry.

The codebase's correctness story lives in conventions — ranked TimedLocks
(gang 10 → resize 14 → defrag 15 → scheduler 20 → node 30), journal records
emitted only at commit choke points, "finalizers may take no locks",
GIL-atomic off-lock mutation patterns, and a native kernel that must stay
bit-identical to its Python fallback.  The runtime checkers (the rank guard
in ``metrics.TimedLock``, the replay invariant audit) only fire on paths
that EXECUTE, and the GIL hides most interleavings from the test suite.
This package checks the contracts statically, over every path the AST can
see:

- ``lockdep``    — static lock-order analysis over a heuristic call graph:
                   rank inversions on never-executed paths, locks taken
                   from GC finalizers, blocking calls (HTTP / fsync /
                   subprocess / jax dispatch) reachable while a
                   control-plane rank (≤ 20) is held.
- ``journalcheck`` — journal discipline: every emitted record type has a
                   replay handler (and a conscious ``what_if`` stance),
                   ``ChipSet._set_slot`` stays confined to its choke
                   modules, live allocator mutations stay inside the
                   journaling perimeter.
- ``conformance`` — registered metric names follow the ``tpu_*`` scheme
                   and appear in OPERATIONS.md, every ``/debug/*`` route
                   is listed in the ``/debug/`` index, off-lock mutations
                   of module-level containers match the documented
                   GIL-atomic allowlist.

Findings are diffed against a checked-in baseline
(``tools/analysis_baseline.json``): pre-existing findings are
grandfathered EXPLICITLY (each entry carries a written justification) and
any NEW finding fails CI (``make check-analysis``).  The analysis is
deliberately heuristic — name-based call resolution, receiver-name type
hints — and errs toward reporting; the baseline is the pressure valve,
never silence.

Entry points: ``python -m elastic_gpu_scheduler_tpu.analysis`` (CLI),
``run_all(root)`` (programmatic; the fixture tests drive it directly).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class Finding:
    """One violation.  ``key`` is the stable identity the baseline matches
    on — rule + file + enclosing symbol + salient detail, NO line numbers,
    so unrelated edits shifting lines don't churn the baseline.  ``line``
    is for humans."""

    rule: str
    file: str
    line: int
    key: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}\n    key: {self.key}"


@dataclass
class AnalysisConfig:
    """Knobs the passes read.  Defaults describe THIS repository; the
    fixture tests override them to aim the passes at synthetic trees."""

    # text of OPERATIONS.md (metric-documentation lint); empty string
    # disables the documentation check, not the naming check
    ops_text: str = ""
    # module basename (relative path suffix) holding the replay dispatch
    replay_module: str = "journal/replay.py"
    # relative-path suffixes allowed to call ChipSet._set_slot/_set_total
    setslot_modules: tuple = ("core/allocator.py", "core/chip.py")
    # modules exempt from the journaling-perimeter rule (they mutate
    # rebuilt/offline state, not the live allocator)
    journal_exempt_modules: tuple = ("journal/replay.py", "journal/__main__.py")
    # (module-relpath, global-name) pairs allowed to mutate module-level
    # containers without holding a lock — the documented GIL-atomic
    # patterns (ADVICE r5 #1 and the LOCK_WAIT drain design): appends and
    # slice/del pairs on plain lists are single bytecodes under CPython's
    # GIL, and each listed site pairs a hot-path append with a reader-side
    # drain that tolerates concurrent tails.
    gil_atomic_allowlist: tuple = (
        # dying TimedLocks park their wait buffers from a GC finalizer
        # that may run inside any metric lock — it MUST NOT lock
        ("metrics/__init__.py", "_ORPHAN_WAITS"),
        ("metrics/__init__.py", "_ORPHAN_DROPPED"),
    )
    # record types replay may handle without any live emission site
    # (forward-compat handlers); populated from the baseline workflow
    dead_handler_allow: tuple = ()


def iter_py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames
            if d not in ("__pycache__", "_native_build") and not d.startswith(".")
        ]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def run_all(root: str, config: Optional[AnalysisConfig] = None) -> list:
    """Parse every module under ``root`` and run all three passes.
    Returns findings sorted by (file, line)."""
    from .callgraph import PackageIndex
    from .conformance import check_conformance
    from .journalcheck import check_journal
    from .lockdep import check_lockdep

    cfg = config or AnalysisConfig()
    index = PackageIndex.load(root)
    findings: list[Finding] = []
    findings.extend(check_lockdep(index, cfg))
    findings.extend(check_journal(index, cfg))
    findings.extend(check_conformance(index, cfg))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def default_ops_text() -> str:
    """OPERATIONS.md of this repository (metric-doc lint input)."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(repo, "OPERATIONS.md")
    try:
        with open(path, encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return ""


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
