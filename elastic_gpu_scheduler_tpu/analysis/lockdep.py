"""Static lockdep: the rank rules of ``metrics.TimedLock``, checked over
every path the call graph can see instead of only the paths tests run.

Rules:

- ``lockdep-inversion``        — a function acquires rank r while the
  lexical with-context already holds rank ≥ r (direct inversion), or a
  call made under held rank R can transitively reach an indefinite
  blocking acquire of rank ≤ R.  Same-lock reentrant re-acquires are
  exempt (the runtime owner check allows them); try-locks and
  timeout-bounded acquires were already dropped at scan time.
- ``lockdep-finalizer``        — a GC finalizer root (``weakref.finalize``
  callback, ``__del__``) can reach ANY lock acquisition, ranked or plain.
  A finalizer runs on whatever thread triggers collection — possibly one
  already inside that very lock.
- ``lockdep-blocking``         — a blocking primitive (HTTP, fsync,
  subprocess, sleep, socket connect, jax compile/dispatch) is reachable
  while a control-plane rank ≤ 20 is held (gang/resize/defrag/engine:
  the locks every verb queues on).  Node locks (rank 30) are leaf locks
  around pure chip math and are exempt by the rule's definition.
"""

from __future__ import annotations

from . import Finding
from .callgraph import PackageIndex

ENGINE_RANK_CEILING = 20


def _acq_token(acq):
    return ("acq", acq.lock.key)


def check_lockdep(index: PackageIndex, cfg) -> list:
    findings: list[Finding] = []

    # direct payloads for propagation -------------------------------------
    direct_acquires = {}   # qualname → {("acq", key): (line, None)}
    direct_blocking = {}   # qualname → {("blk", label): (line, None)}
    for q, info in index.functions.items():
        acc = {}
        for acq in info.acquires:
            tok = _acq_token(acq)
            if tok not in acc:
                acc[tok] = (acq.line, None)
        if acc:
            direct_acquires[q] = acc
        blk = {}
        for label, line, _held in info.blocking:
            tok = ("blk", label)
            if tok not in blk:
                blk[tok] = (line, None)
        if blk:
            direct_blocking[q] = blk

    may_acquire = index.propagate(direct_acquires)
    may_block = index.propagate(direct_blocking)

    lock_by_key = {}
    for ld in list(index.class_locks.values()) + list(index.module_locks.values()):
        lock_by_key[ld.key] = ld

    # -- rule 1: inversions ------------------------------------------------
    # direct with-inside-with nesting within one function body
    for q, info in index.functions.items():
        _direct_nesting(index, info, findings, lock_by_key)

    # bare .acquire() inside a with-held lock in the same function —
    # the one direct shape the nesting walk (With items only) and the
    # call-path rule (other functions' acquires) both miss
    for q, info in index.functions.items():
        for acq in info.acquires:
            if not acq.bare or acq.lock.rank is None:
                continue
            for h in acq.held:
                if h.rank is None:
                    continue
                if h.key == acq.lock.key and acq.lock.reentrant:
                    continue
                if acq.lock.rank <= h.rank:
                    findings.append(Finding(
                        rule="lockdep-inversion",
                        file=info.module,
                        line=acq.line,
                        key=(
                            f"lockdep-inversion::{info.module}::"
                            f"{_sym(q)}::{h.key}->{acq.lock.key}"
                        ),
                        message=(
                            f"bare acquire of {acq.lock.lock_name!r} "
                            f"(rank {acq.lock.rank}) while holding "
                            f"{h.lock_name!r} (rank {h.rank}) — ranks "
                            "must strictly increase"
                        ),
                    ))

    # call-path inversions
    for q, info in index.functions.items():
        for site in info.calls:
            if not site.held:
                continue
            callees = index.resolve_call(site, info)
            for callee in callees:
                for tok, wit in may_acquire.get(callee, {}).items():
                    _, key = tok
                    tgt = lock_by_key.get(key)
                    if tgt is None or tgt.rank is None:
                        continue
                    for held in site.held:
                        if held.rank is None:
                            continue
                        if held.key == key and tgt.reentrant:
                            continue
                        if tgt.rank <= held.rank:
                            path = index.witness_path(may_acquire, callee, tok)
                            findings.append(Finding(
                                rule="lockdep-inversion",
                                file=info.module,
                                line=site.line,
                                key=(
                                    f"lockdep-inversion::{info.module}::"
                                    f"{_sym(q)}::{held.key}->{key}"
                                ),
                                message=(
                                    f"call to {site.attr}() while holding "
                                    f"{held.lock_name!r} (rank {held.rank}) can "
                                    f"acquire {tgt.lock_name!r} (rank {tgt.rank}) "
                                    f"via {path} — ranks must strictly increase"
                                ),
                            ))

    # -- rule 2: finalizers take no locks ---------------------------------
    seen_final = set()
    for q, via, line in index.finalizer_roots:
        if q in seen_final:
            continue
        seen_final.add(q)
        info = index.functions.get(q)
        if info is None:
            continue
        for tok, wit in may_acquire.get(q, {}).items():
            _, key = tok
            tgt = lock_by_key.get(key)
            path = index.witness_path(may_acquire, q, tok)
            findings.append(Finding(
                rule="lockdep-finalizer",
                file=info.module,
                line=wit[0] if wit[1] is None else info.line,
                key=f"lockdep-finalizer::{info.module}::{_sym(q)}::{key}",
                message=(
                    f"finalizer {info.name}() (registered via {via}) can "
                    f"acquire lock {tgt.lock_name if tgt else key!r} via "
                    f"{path} — finalizers may take no locks (they can run "
                    "on a thread already inside that lock)"
                ),
            ))

    # -- rule 3: no blocking call under a control-plane rank --------------
    for q, info in index.functions.items():
        # direct blocking primitive inside a with-block
        for label, line, held in info.blocking:
            worst = _worst_control_rank(held)
            if worst is not None:
                findings.append(Finding(
                    rule="lockdep-blocking",
                    file=info.module,
                    line=line,
                    key=(
                        f"lockdep-blocking::{info.module}::{_sym(q)}::"
                        f"{worst.key}::{label}"
                    ),
                    message=(
                        f"blocking {label} while holding {worst.lock_name!r} "
                        f"(rank {worst.rank}) — no blocking calls under a "
                        f"control-plane lock (rank ≤ {ENGINE_RANK_CEILING})"
                    ),
                ))
        for site in info.calls:
            worst = _worst_control_rank(site.held)
            if worst is None:
                continue
            for callee in index.resolve_call(site, info):
                for tok, wit in may_block.get(callee, {}).items():
                    _, label = tok
                    path = index.witness_path(may_block, callee, tok)
                    findings.append(Finding(
                        rule="lockdep-blocking",
                        file=info.module,
                        line=site.line,
                        key=(
                            f"lockdep-blocking::{info.module}::{_sym(q)}::"
                            f"{worst.key}::{label}::{_sym(callee)}"
                        ),
                        message=(
                            f"call to {site.attr}() while holding "
                            f"{worst.lock_name!r} (rank {worst.rank}) can reach "
                            f"blocking {label} via {path}"
                        ),
                    ))
    return findings


def _direct_nesting(index, info, findings, lock_by_key) -> None:
    """With-inside-with inversions within one function body."""
    import ast

    def visit(node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            resolved = []
            for item in node.items:
                ld = index.resolve_lock(
                    item.context_expr, info.module, info.cls
                )
                if ld is not None:
                    for h in held + resolved:
                        if h.rank is None or ld.rank is None:
                            continue
                        if h.key == ld.key and ld.reentrant:
                            continue
                        if ld.rank <= h.rank:
                            findings.append(Finding(
                                rule="lockdep-inversion",
                                file=info.module,
                                line=item.context_expr.lineno,
                                key=(
                                    f"lockdep-inversion::{info.module}::"
                                    f"{_sym(info.qualname)}::"
                                    f"{h.key}->{ld.key}"
                                ),
                                message=(
                                    f"acquires {ld.lock_name!r} (rank "
                                    f"{ld.rank}) while holding "
                                    f"{h.lock_name!r} (rank {h.rank}) — "
                                    "ranks must strictly increase"
                                ),
                            ))
                    resolved.append(ld)
            for stmt in node.body:
                visit(stmt, held + resolved)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda, ast.ClassDef)):
            return  # nested scopes analyzed separately
        else:
            for child in ast.iter_child_nodes(node):
                visit(child, held)

    for stmt in info.node.body:
        visit(stmt, [])


def _worst_control_rank(held):
    worst = None
    for ld in held:
        if ld.rank is None or ld.rank > ENGINE_RANK_CEILING:
            continue
        if worst is None or ld.rank > worst.rank:
            worst = ld
    return worst


def _sym(qualname: str) -> str:
    return qualname.split("::")[-1]
