"""Speculative decoding via prompt-lookup (n-gram) drafting.

Draft-model-free speculation: propose the tokens that followed the most
recent matching n-gram in the context, verify all K proposals in ONE
multi-token cached forward (``generate.forward_cached`` — a single wide
pass over the K+1 draft positions, so device time per accepted token is
the sequential-decode cost divided by the acceptance length, the actual
speculative-decoding win), and keep the longest prefix the model itself
would have produced — output is exactly greedy decoding.

The verify window has a FIXED width (k+1, short drafts padded), so the
verification pass compiles once.

Cache rollback is free by design: KVCache entries beyond ``length`` are
masked out (generate.cached_attention), so rejecting speculated tokens is
just rewinding the length counter — the rejected K/V rows are overwritten by
the next write at that position.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .generate import KVCache, decode_step, forward_cached
from .transformer import TransformerConfig


def propose_ngram(context: list[int], n: int, k: int) -> list[int]:
    """Last-match prompt lookup: find the trailing n-gram earlier in the
    context and propose the k tokens that followed it."""
    if len(context) < n + 1:
        return []
    tail = context[-n:]
    # scan right-to-left for the most recent earlier occurrence
    for start in range(len(context) - n - 1, -1, -1):
        if context[start : start + n] == tail:
            follow = context[start + n : start + n + k]
            return list(follow)
    return []


def speculative_generate(
    params: dict,
    prompt: jax.Array,  # (1, S) int32 — single sequence
    cfg: TransformerConfig,
    max_new_tokens: int,
    ngram: int = 3,
    k: int = 5,
    max_len: int = 0,
) -> tuple[jax.Array, dict]:
    """Greedy-equivalent speculative decoding.

    Returns (tokens (1, S+new), stats {"model_passes", "accepted_drafts"}).
    """
    assert prompt.shape[0] == 1, "speculative decoding is per-sequence"
    from .generate import prefill

    S = prompt.shape[1]
    need = S + max_new_tokens + k + 1
    max_len = max_len or need
    # the FIXED-width verify window writes up to k padded K/V rows past the
    # accepted prefix; a smaller max_len would make dynamic_update_slice
    # clamp the write start and silently corrupt confirmed cache rows
    assert max_len >= need, (
        f"max_len {max_len} < {need} (prompt + max_new_tokens + k + 1; the "
        "padded verify window needs the headroom)"
    )
    cache = KVCache.empty(cfg, 1, max_len)
    logits, cache = prefill(params, prompt, cache, cfg)

    step_fn = jax.jit(functools.partial(decode_step, cfg=cfg))
    # fixed-width verify window: [last_accepted, d1..dk] (drafts padded) so
    # the multi-token pass compiles exactly once
    verify_fn = jax.jit(functools.partial(forward_cached, cfg=cfg))
    context: list[int] = [int(t) for t in np.asarray(prompt[0])]
    produced: list[int] = []
    passes = 0
    accepted_total = 0

    next_token = int(jnp.argmax(logits, -1)[0])
    produced.append(next_token)
    context.append(next_token)

    while len(produced) < max_new_tokens:
        budget = max_new_tokens - len(produced)
        drafts = propose_ngram(context, ngram, min(k, budget - 1))
        if drafts:
            # ONE wide pass over [last_accepted, d1..dn] (+padding): the
            # logits at each position give the model's own choice to verify
            # the NEXT draft against
            feed = [context[-1]] + drafts + [0] * (k - len(drafts))
            confirmed_len = int(cache.length)
            toks = jnp.asarray(feed, jnp.int32)[None, :]  # (1, k+1)
            logits_seq, cache2 = verify_fn(params, toks, cache)
            passes += 1
            choices = np.asarray(jnp.argmax(logits_seq[0], -1))  # (k+1,)
            n_accept = 0
            for i, d in enumerate(drafts):
                if int(choices[i]) == d:
                    n_accept += 1
                else:
                    break
            accepted = drafts[:n_accept]
            # the model's own token after the last accepted draft
            own = int(choices[n_accept])
            produced.extend(accepted + [own])
            context.extend(accepted + [own])
            accepted_total += n_accept
            # rewind: confirmed prefix + accepted drafts + 1 own token fed
            keep = confirmed_len + n_accept + 1
            cache = KVCache(cache2.k, cache2.v, jnp.asarray(keep, jnp.int32))
        else:
            logits, cache = step_fn(
                params, jnp.asarray([context[-1]], jnp.int32), cache
            )
            passes += 1
            tok = int(jnp.argmax(logits, -1)[0])
            produced.append(tok)
            context.append(tok)

    produced = produced[:max_new_tokens]
    out = jnp.concatenate(
        [prompt, jnp.asarray(produced, jnp.int32)[None, :]], axis=1
    )
    return out, {"model_passes": passes, "accepted_drafts": accepted_total}
