"""Weight-only int8 quantization for inference.

Matmul weights are stored as int8 with per-output-channel fp32 scales; at
compute time ``wmat`` dequantizes with ``q.astype(bf16) * scale``, which XLA
fuses into the matmul's weight read — so HBM traffic for weights drops ~4x
(vs fp32) / ~2x (vs bf16) while the MXU still sees bf16 operands.  Norm
scales and small vectors stay fp32.

Usage:
    qparams = quantize_params(params)          # pytree with QTensor leaves
    logits  = forward(qparams, tokens, cfg)    # all matmul sites use wmat()
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def is_qtensor(x: Any) -> bool:
    return isinstance(x, dict) and "q8" in x and "scale" in x


def quantize_tensor(w: jax.Array) -> dict:
    """Per-output-channel symmetric int8 quantization.

    Only the contraction axis (-2: the input-feature dim of every matmul
    weight here, incl. layer-stacked (L, D, H) and expert-stacked
    (L, E, D, F) forms) is reduced; leading stack axes keep their extent so
    ``lax.scan`` over layers still sees matching leading dims."""
    absmax = jnp.max(jnp.abs(w), axis=-2, keepdims=True)
    scale = (absmax / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-12)), -127, 127).astype(
        jnp.int8
    )
    return {"q8": q, "scale": scale}


# matmul-weight leaves by name; norms/biases/router stay full precision
_QUANT_KEYS = (
    "embed", "unembed", "wq", "wk", "wv", "wo", "w_in", "w_gate", "w_out",
    "patch_embed", "head",
)


def quantize_params(params: Any) -> Any:
    """Quantize every matmul weight leaf; returns a mixed pytree."""

    def walk(tree, path=()):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        name = path[-1] if path else ""
        if name in _QUANT_KEYS and getattr(tree, "ndim", 0) >= 2:
            return quantize_tensor(tree)
        return tree

    return walk(params)


def wmat(w: Any, dtype) -> jax.Array:
    """Weight as a dense matrix in `dtype` — the universal matmul accessor.

    Dense leaves pass through ``astype``; QTensor leaves dequantize (XLA
    fuses the cast+multiply into the consuming matmul).
    """
    if is_qtensor(w):
        return w["q8"].astype(dtype) * w["scale"].astype(dtype)
    return w.astype(dtype)


def quantized_bytes(params: Any) -> int:
    """Total parameter bytes after quantization (for memory reporting)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
