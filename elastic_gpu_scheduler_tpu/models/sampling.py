"""Token sampling: temperature, top-k, and top-p (nucleus) filtering.

Two entry points for the two call shapes in this repo:

- ``sample_static``: per-call Python scalars (temperature/top_k/top_p are
  static under jit) — used by ``generate.decode_loop``/``generate.generate``
  where one sampling config applies to the whole batch.  Filters compile
  away entirely when disabled.
- ``sample_batched``: per-row device arrays — used by the serving engine's
  fused decode chunk, where every slot carries its own request's sampling
  params and recompiling per combination is not an option.

Conventions match the de-facto standard (HF ``generation``): temperature
scales logits first, then top-k keeps the k highest-probability tokens,
then top-p keeps the smallest prefix of the sorted distribution whose
cumulative mass reaches p (the top-1 token is always kept).  temperature 0
means greedy; top_k 0 and top_p >= 1 disable the respective filter.

All shapes are static and the math is branch-free, so everything lives
happily inside a ``lax.scan`` decode loop on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _topk_threshold_mask(scaled: jax.Array, k: int) -> jax.Array:
    """keep mask for static k>0: True where scaled >= k-th largest value."""
    kth = jax.lax.top_k(scaled, k)[0][..., -1:]  # (B,1)
    return scaled >= kth


def _topp_mask_from_sorted(
    sorted_scaled: jax.Array, top_p: jax.Array | float
) -> jax.Array:
    """keep mask IN SORTED ORDER: smallest prefix with cumulative mass
    reaching top_p; exclusive-cumsum comparison always keeps the top-1."""
    probs = jax.nn.softmax(sorted_scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    if not isinstance(top_p, jax.Array):
        top_p = jnp.asarray(top_p, probs.dtype)
    keep = (cum - probs) < jnp.reshape(top_p, (-1, 1) if jnp.ndim(top_p) else ())
    # the top-1 token survives even a degenerate top_p <= 0
    return keep.at[..., 0].set(True)


def sample_static(
    logits: jax.Array,
    key: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """(B, V) logits → (B,) tokens.  temperature/top_k/top_p are Python
    scalars, so disabled filters cost nothing after jit."""
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1)
    scaled = logits.astype(jnp.float32) / max(temperature, 1e-6)
    V = logits.shape[-1]
    if top_k > 0 and top_k < V:
        scaled = jnp.where(_topk_threshold_mask(scaled, top_k), scaled, -jnp.inf)
    if top_p < 1.0:
        sorted_scaled = -jnp.sort(-scaled, axis=-1)  # descending
        keep_sorted = _topp_mask_from_sorted(sorted_scaled, top_p)
        # threshold = smallest kept value; everything below is masked
        thresh = jnp.min(
            jnp.where(keep_sorted, sorted_scaled, jnp.inf), axis=-1, keepdims=True
        )
        scaled = jnp.where(scaled >= thresh, scaled, -jnp.inf)
    return jax.random.categorical(key, scaled, axis=-1)


def sample_batched(
    logits: jax.Array,
    key: jax.Array,
    temps: jax.Array,  # (B,) float32; 0 → greedy for that row
    top_ks: jax.Array,  # (B,) int32; 0 → no top-k for that row
    top_ps: jax.Array,  # (B,) float32; >= 1 → no top-p for that row
    row_keys: jax.Array = None,  # (B,) typed keys; overrides ``key``
) -> jax.Array:
    """(B, V) logits → (B,) tokens with PER-ROW sampling params.

    One descending argsort serves both filters: rank-based top-k and
    cumulative-mass top-p masks are built in sorted space and gathered back
    through the inverse permutation.

    ``row_keys`` (per-request seeding): each row draws with its own key
    instead of slicing one batch key — reproducible per request,
    independent of batch composition.
    """
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    order = jnp.argsort(-scaled, axis=-1)  # (B,V) descending
    inv = jnp.argsort(order, axis=-1)  # inverse permutation
    sorted_scaled = jnp.take_along_axis(scaled, order, axis=-1)

    ranks = inv  # rank of each vocab entry in the sorted order
    keep_k = (top_ks[:, None] <= 0) | (ranks < top_ks[:, None])
    # SEQUENTIAL semantics (same as sample_static / HF): top-p sees the
    # top-k-filtered, renormalized distribution — mask beyond-k positions
    # in sorted space (position IS rank there) before the mass cumsum
    pos = jnp.arange(V)[None, :]
    sorted_k = jnp.where(
        (top_ks[:, None] <= 0) | (pos < top_ks[:, None]), sorted_scaled, -jnp.inf
    )
    keep_sorted_p = _topp_mask_from_sorted(sorted_k, top_ps)
    keep_p = jnp.take_along_axis(keep_sorted_p, ranks, axis=-1)
    keep = keep_k & (keep_p | (top_ps[:, None] >= 1.0))

    masked = jnp.where(keep, scaled, -jnp.inf)
    if row_keys is not None:
        sampled = jax.vmap(
            lambda k, lg: jax.random.categorical(k, lg)
        )(row_keys, masked).astype(jnp.int32)
    else:
        sampled = jax.random.categorical(key, masked, axis=-1).astype(
            jnp.int32
        )
    return jnp.where(temps > 0, sampled, greedy)
