"""Continuous-batching inference engine: paged KV cache + fused decode.

Serving-side subsystem of the workload plane.  Requests join and leave a
fixed-shape batch *between* fused decode chunks, so the TPU always steps one
static (B_max, …) computation while work arrives and finishes asynchronously.
Two TPU-first design points (VERDICT r1 #4/#10):

- **Paged KV cache** (vLLM-style, XLA-friendly): one pool of P fixed-size
  pages shaped (L, P, page_size, Hkv, Dh) shared by all slots, plus a
  host-managed block table (B, max_pages) of page indices per slot.  Pages
  are allocated on demand as sequences grow and freed on completion, so
  total HBM is sized for the *actual* token load, not
  max_batch × max_len worst case — mixed-length traffic admits more
  concurrent requests than slot-contiguous allocation allows.  Page 0 is a
  reserved scratch page: inactive slots' table rows point at it, so the
  fixed-shape step can run without masking writes.
- **Fused decode**: each engine step runs ``fused_steps`` decode iterations
  in ONE jitted ``lax.scan`` with sampling inside (same recipe as
  models/generate.py:decode_loop), so the host→device dispatch cost is paid
  once per K tokens.  Prompt feeding happens on-device too: the scan picks
  the next prompt token while a slot is still prefilling, else the sampled
  token.

A slot whose next chunk cannot get pages simply *stalls* (stays inactive,
state intact) until completions free pages; if every slot is stalled the
pool is genuinely exhausted and the engine raises.

**Multi-LoRA serving**: the engine can hold a bank of named LoRA adapters
(``adapters=`` at construction; ``Request.adapter`` selects one, "" = base).
Adapters are stacked into per-family gather banks (``build_lora_bank``) and
every projection adds the slot's own low-rank delta inside the SAME fused
step — requests using different adapters batch together, nothing splits or
recompiles per adapter.  Prefix-cache keys are seeded with the adapter id,
since cached K/V depends on the wk/wv deltas.

No reference analogue (SURVEY §2 #19); this is the inference-serving
capability slot of a complete framework.
"""

from __future__ import annotations

import functools
import itertools
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.attention import NEG_INF
from ..tracing import TRACER
from ..utils import kvwire, prefixdigest
from .generate import cached_attention
from .quantize import wmat
from .transformer import TransformerConfig, _embed_lookup, rms_norm, rope

# Structured rejection sentinels: the HTTP layer maps THESE strings to
# retryable statuses (503 / 429) on every request shape; compare by
# constant, not prose.
DRAINING_ERROR = "server draining"
QUEUE_FULL_ERROR = "admission queue full"

log = logging.getLogger("tpu-scheduler")

SCRATCH_PAGE = 0  # reserved; inactive slots write here, nobody reads it


# -- paged KV pool (optionally int8-quantized) -------------------------------
#
# The pool is a pytree dict so every step/prefill function threads ONE
# argument regardless of storage format: {"k","v"} arrays of shape
# (L, P, page, Hkv, Dh), plus {"ks","vs"} per-row scales (L, P, page, Hkv)
# when int8.  int8-at-rest halves KV HBM bytes per token — double the
# servable context/concurrency per pool byte — with per-token-per-head
# symmetric scales (the weight-quantization recipe from models/quantize.py
# applied to the cache).


def make_kv_pool(cfg, n_pages: int, page_size: int, int8: bool) -> dict:
    shape = (cfg.n_layers, n_pages, page_size, cfg.kv_heads, cfg.head_dim)
    if int8:
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "ks": jnp.zeros(shape[:-1], jnp.float32),
            "vs": jnp.zeros(shape[:-1], jnp.float32),
        }
    dtype = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _quantize_rows(x):
    """(N, Hkv, Dh) → int8 rows + per-(token, head) scale."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / 127.0
    safe = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(xf / safe[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def _kv_write_rows(lkv: dict, pidx, off, k_rows, v_rows) -> dict:
    """Scatter new K/V rows into one LAYER's pool slice at (pidx, off)."""
    out = dict(lkv)
    if "ks" in lkv:
        qk, sk_ = _quantize_rows(k_rows)
        qv, sv_ = _quantize_rows(v_rows)
        out["k"] = lkv["k"].at[pidx, off].set(qk)
        out["v"] = lkv["v"].at[pidx, off].set(qv)
        out["ks"] = lkv["ks"].at[pidx, off].set(sk_)
        out["vs"] = lkv["vs"].at[pidx, off].set(sv_)
    else:
        out["k"] = lkv["k"].at[pidx, off].set(k_rows.astype(lkv["k"].dtype))
        out["v"] = lkv["v"].at[pidx, off].set(v_rows.astype(lkv["v"].dtype))
    return out


def _kv_gather(lkv: dict, tables, page_size: int, dtype):
    """One LAYER's pages → virtually-contiguous (B, M, Hkv, Dh) K and V
    (dequantized when the pool is int8)."""
    B, maxp = tables.shape
    Hkv, Dh = lkv["k"].shape[-2], lkv["k"].shape[-1]
    k = lkv["k"][tables].reshape(B, maxp * page_size, Hkv, Dh)
    v = lkv["v"][tables].reshape(B, maxp * page_size, Hkv, Dh)
    if "ks" in lkv:
        ks = lkv["ks"][tables].reshape(B, maxp * page_size, Hkv)
        vs = lkv["vs"][tables].reshape(B, maxp * page_size, Hkv)
        k = (k.astype(jnp.float32) * ks[..., None]).astype(dtype)
        v = (v.astype(jnp.float32) * vs[..., None]).astype(dtype)
    else:
        k = k.astype(dtype)
        v = v.astype(dtype)
    return k, v


@dataclass
class Request:
    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0  # 0 → disabled; per-request (see models/sampling.py)
    top_p: float = 1.0  # >= 1 → disabled
    adapter: str = ""  # "" → base model; else a name registered at init
    # generation stops when any of these ids is emitted (the stop token IS
    # included in output, HF-style); () → run to max_new_tokens
    stop_tokens: tuple = ()
    # streaming: called from the engine thread with each emitted token id,
    # in order, before done is signaled
    on_token: Optional[object] = None
    # >0 → return per-emitted-token logprobs: the chosen token's logprob
    # in ``token_logprobs`` and this many top alternatives (id, logprob)
    # in ``top_logprobs``.  Clamped to the engine's compiled logprobs_k.
    logprobs: int = 0
    # OpenAI-semantics repetition penalties: logits -= frequency_penalty
    # × count(token among GENERATED tokens so far) + presence_penalty ×
    # (count > 0).  Prompt tokens do NOT count (matching OpenAI/vLLM: the
    # first sampled token is never penalized); applied in every sampling
    # distribution (fused chunks via an in-scan count carry, the verify
    # pass via an in-window running count) with exact sequential
    # semantics.
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    # stop ids CANNOT be sampled until this many tokens have been
    # emitted (vLLM's min_tokens: their logits sit at -1e9 while the
    # emitted count is below the floor, in every sampling distribution —
    # fused chunks, verify pass, admission prefill — so clients never
    # see a stop id embedded mid-completion and the penalty counts never
    # include one); max_new_tokens still caps the total.
    min_tokens: int = 0
    # per-request sampling seed: draws key off fold_in(key(seed),
    # position) — reproducible across batch composition, slot placement,
    # restarts, AND engine modes (a seeded sampled request produces the
    # same tokens under speculative and sequential decoding, because
    # both key by the distribution's position).  None → engine stream.
    seed: Optional[int] = None
    # hard constraint: when non-empty, ONLY these token ids can ever be
    # sampled (everything else gets -1e9 — classification / multiple-
    # choice / tool-call-id decoding).  Implemented through the same
    # device-resident bias rows as logit_bias and composes with it.
    allowed_tokens: tuple = ()
    # admission priority / SLO class (higher = more important; 0 =
    # default, negative = batch/best-effort).  Admission pops the
    # highest-priority queued request first (FIFO within a class), and
    # under KV page pressure the engine SPILLS the lowest-priority slot
    # (frees its pages, requeues it for an exact resume) instead of
    # stalling everyone — the serving-plane mirror of the scheduler's
    # preemption verb (server/handlers.py Preemption).
    priority: int = 0
    # internal: times this request was evicted by the LAST-RESORT pool
    # preemption (all slots stalled, no lower class to spill).  The first
    # eviction requeues for an exact resume; a second means the request
    # genuinely cannot fit the pool and fails terminally.
    pool_spills: int = 0
    # tracing (tracing/__init__.py): the serving request's SpanContext,
    # set by the HTTP layer from the client's ``traceparent`` header.
    # The engine drops instant markers (queued/admitted/spilled) into the
    # trace from ITS thread via this context — no shared span mutation.
    trace_ctx: Optional[object] = None
    # token id → additive logit bias (OpenAI semantics): applied to every
    # sampling distribution for this request, in the fused chunks, the
    # speculative verify pass, and the admission prefill.  ±large values
    # ban/force tokens; reported logprobs are post-bias (they describe
    # the distribution actually sampled from).
    logit_bias: dict = field(default_factory=dict)
    done: threading.Event = field(default_factory=threading.Event)
    output: list[int] = field(default_factory=list)
    token_logprobs: list = field(default_factory=list)
    top_logprobs: list = field(default_factory=list)
    error: str = ""  # set (with done) when the request is rejected
    # Thread ownership: the ENGINE thread owns output/error/done and all
    # slot state; other threads may only read output after done, and may
    # request cancellation via cancel().  ``cancelled`` is a plain bool
    # flag (atomic under the GIL) the engine checks at every chunk
    # boundary — tokens already emitted stay in ``output``.
    cancelled: bool = False
    # SLO-plane queue-wait telemetry: monotonic stamps of FIRST enqueue
    # and FIRST slot admission (a spill-resume re-queues but the queue
    # wait a client perceived is the first one).  0.0 = not yet stamped;
    # queue wait = t_admit - t_submit.  Written by the enqueue/admit
    # paths, read by the HTTP layer after admission — GIL-atomic floats.
    t_submit: float = 0.0
    t_admit: float = 0.0

    def cancel(self) -> None:
        """Stop generation at the next chunk boundary (client timeout or
        disconnect).  Safe to call from any thread, idempotent; the engine
        frees the slot/pages and signals ``done``."""
        self.cancelled = True


def _shard_params_for_mesh(params, mesh):
    """Place weights under the training sharding rules fitted to this mesh
    (parallel/sharding.shard_params strict=False: mesh-absent axes drop,
    non-divisible dims replicate — arbitrary checkpoints must load)."""
    from ..parallel.sharding import shard_params

    if "tensor" not in mesh.axis_names:
        raise ValueError(
            f"serving mesh needs a 'tensor' axis, got {mesh.axis_names}"
        )
    return shard_params(params, mesh, strict=False)


def _shard_kv_for_mesh(kv, cfg, mesh):
    """Shard the paged pool's kv-head axis over ``tensor``: each rank owns
    its heads' pages whole, so page tables and host bookkeeping need no
    changes.  Falls back to replication when the head count doesn't divide
    (small GQA models) — correct, just memory-unsaving."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    t = mesh.shape["tensor"]
    heads_ok = cfg.kv_heads % t == 0
    spec5 = P(None, None, None, "tensor", None) if heads_ok else P()
    spec4 = P(None, None, None, "tensor") if heads_ok else P()
    out = {}
    for name, arr in kv.items():
        spec = spec5 if arr.ndim == 5 else spec4
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return out


def build_lora_bank(
    adapters: dict[str, dict], dtype, base_layers: Optional[dict] = None
) -> tuple[dict, dict[str, int]]:
    """Stack named LoRA adapters (models/lora.py ``lora_init`` trees) into
    a per-family gatherable bank for multi-LoRA serving:

        {family: {"a": (L, n_ids, d_in, r_max), "b": (L, n_ids, r_max,
        d_out)}}

    id 0 is the all-zero adapter (base model; "" requests), ids 1.. follow
    the dict order.  Ranks are zero-padded to the max (exact: padded rank
    dims contribute nothing) and the alpha/rank scale is folded into b,
    mirroring lora.inject_lora.  ``base_layers`` (the model's layer tree)
    enables shape validation at build time — an adapter trained against a
    different base fails HERE with a named error instead of deep inside
    the jitted serve chunk.  Returns (bank, name → id)."""

    def _base_shape(t):
        W = base_layers.get(t)
        if W is None:
            raise ValueError(f"adapter target {t!r} not in model layers")
        return W["q8"].shape if isinstance(W, dict) else W.shape
    index = {"": 0}
    targets: dict[str, tuple] = {}
    for name, lo in adapters.items():
        if name == "" or name in index:
            raise ValueError(f"bad/duplicate adapter name {name!r}")
        index[name] = len(index)
        for t, ab in lo["adapters"].items():
            L, d_in, r = ab["a"].shape
            d_out = ab["b"].shape[-1]
            if base_layers is not None and _base_shape(t) != (L, d_in, d_out):
                raise ValueError(
                    f"adapter {name!r} target {t!r} has dims "
                    f"(L={L}, d_in={d_in}, d_out={d_out}) but the model's "
                    f"{t!r} is {tuple(_base_shape(t))} — this adapter was "
                    "trained against a different base"
                )
            prev = targets.get(t)
            if prev is not None and prev[:3] != (L, d_in, d_out):
                raise ValueError(
                    f"adapter {name!r} target {t!r} has dims "
                    f"(L={L}, d_in={d_in}, d_out={d_out}) but another "
                    f"adapter uses (L={prev[0]}, d_in={prev[1]}, "
                    f"d_out={prev[2]}) — all adapters must share one base"
                )
            targets[t] = (L, d_in, d_out, max(r, prev[3] if prev else 0))
    n = len(index)
    bank: dict = {}
    for t, (L, d_in, d_out, rmax) in targets.items():
        a = np.zeros((L, n, d_in, rmax), np.float32)
        b = np.zeros((L, n, rmax, d_out), np.float32)
        for name, lo in adapters.items():
            ab = lo["adapters"].get(t)
            if ab is None:
                continue
            r = ab["a"].shape[-1]
            scale = lo["alpha"] / lo["rank"]
            a[:, index[name], :, :r] = np.asarray(ab["a"], np.float32)
            b[:, index[name], :r, :] = np.asarray(ab["b"], np.float32) * scale
        bank[t] = {
            "a": jnp.asarray(a, dtype), "b": jnp.asarray(b, dtype)
        }
    return bank, index


def _rope_rows(x, positions, theta):
    """rope with PER-ROW positions: x (B,T,H,Dh), positions (B,T)."""
    return jax.vmap(lambda xb, pb: rope(xb[None], pb, theta)[0])(x, positions)


def _sproj(x, p, name, dtype, ad, aids):
    """``x @ p[name]`` plus the PER-SLOT LoRA delta when the layer's bank
    slice carries this family (multi-LoRA serving: every slot applies its
    own request's adapter inside ONE fused step — the bank is gathered by
    adapter id, so the batch never splits by adapter).

    x: (B, T, d); ad[name] = {"a": (n_adapters, d, r), "b": (n_adapters,
    r, o)} with id 0 the all-zero base adapter; aids: (B,) int32."""
    y = x @ wmat(p[name], dtype)
    if ad and name in ad:
        a = ad[name]["a"][aids]  # (B, d, r)
        b = ad[name]["b"][aids]  # (B, r, o)
        t = jnp.einsum(
            "btd,bdr->btr", x, a, preferred_element_type=jnp.float32
        )
        y = y + jnp.einsum(
            "btr,bro->bto", t, b, preferred_element_type=jnp.float32
        ).astype(y.dtype)
    return y


def _moe_ffn_serve(h, p, dtype, ep=False):
    """Drop-free top-1 MoE FFN for the serving paths.

    Training's ``moe_ffn`` (models/moe.py) drops tokens past an expert's
    capacity — acceptable as a training-time regularizer, wrong at serving
    (a dropped token silently skips its FFN and the victim depends on
    which other requests share the batch).  Serving routes EXACTLY, and
    batch-composition independently: a token's output never depends on
    other slots' routing, so engine outputs match solo ``generate()`` runs.

    Three shapes of the same computation, chosen statically:
    - decode-sized (≤32 tokens AND ≤E tokens, single device): gather the
      chosen expert's weights per token — 3 (T, D, F) gathers, dense-FFN
      FLOPs.  Past E tokens the gather reads MORE weight bytes than the
      grouped matmul touches (T matrices vs ≤E), so ragged wins;
    - prefill-sized (single device / tensor-sharded): grouped matmul —
      sort tokens by expert, ``lax.ragged_dot`` per projection (XLA's
      TPU grouped GEMM), unsort.  Dense FLOPs per token; this retired
      the old E×-dense mask-dispatch prefill path (r3 debt);
    - expert-parallel mesh (``ep``): mask-dispatch to ALL experts
      (onehot-scaled inputs; SwiGLU maps zero to zero, so unrouted
      contributions vanish) — E× dense FLOPs, but each rank's experts
      stay local and GSPMD reduces the combine (ragged_dot's group dim
      has no partitioning rule).

    ``ep`` (expert-parallel serving mesh, expert axis > 1): force the
    mask-dispatch form even at decode size — per-token weight GATHERS over
    an expert-sharded (E, D, F) array would all-gather whole expert
    matrices across ranks every step, while mask-dispatch keeps each
    rank's experts local and GSPMD reduces the combine (the same
    dispatch/combine geometry as training's all-to-all, models/moe.py).
    """
    B, T, D = h.shape
    tokens = B * T
    xf = h.reshape(tokens, D)
    glog = (xf @ wmat(p["moe_gate"], h.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(glog, axis=-1)  # (T, E)
    idx = jnp.argmax(probs, axis=-1)  # (T,)
    prob = jnp.max(probs, axis=-1).astype(jnp.float32)  # (T,)
    if tokens <= min(32, glog.shape[-1]) and not ep:
        wg = wmat(p["w_gate"], dtype)[idx]  # (T, D, F)
        wi = wmat(p["w_in"], dtype)[idx]
        wo = wmat(p["w_out"], dtype)[idx]
        gate = jax.nn.silu(jnp.einsum("td,tdf->tf", xf, wg))
        up = jnp.einsum("td,tdf->tf", xf, wi)
        out = jnp.einsum(
            "tf,tfd->td", gate * up, wo, preferred_element_type=jnp.float32
        )
    elif ep:
        # expert-parallel mesh: mask-dispatch keeps each rank's experts
        # local and GSPMD reduces the combine (ragged_dot's group dim has
        # no GSPMD partitioning rule, so it would gather expert weights
        # cross-rank); E× dense FLOPs is the price of distribution here
        E = glog.shape[-1]
        onehot = jax.nn.one_hot(idx, E, dtype=xf.dtype)  # (T, E)
        expert_in = jnp.einsum("te,td->etd", onehot, xf)
        gate = jax.nn.silu(
            jnp.einsum("etd,edf->etf", expert_in, wmat(p["w_gate"], dtype))
        )
        up = jnp.einsum("etd,edf->etf", expert_in, wmat(p["w_in"], dtype))
        out = jnp.einsum(
            "etf,efd->td", gate * up, wmat(p["w_out"], dtype),
            preferred_element_type=jnp.float32,
        )
    else:
        # grouped matmul (lax.ragged_dot — XLA's TPU grouped-GEMM): sort
        # tokens by expert so each group is contiguous, run dense-FLOPs
        # GEMMs per expert, unsort.  This replaces the old mask-dispatch
        # E× dense-FLOPs prefill path (the in-code "awaiting a grouped
        # matmul" debt, VERDICT r3 weak #3).
        E = glog.shape[-1]
        order = jnp.argsort(idx)
        inv = jnp.argsort(order)
        xs = xf[order]
        counts = jnp.bincount(idx, length=E)
        wg, wi, wo = (
            wmat(p["w_gate"], dtype), wmat(p["w_in"], dtype),
            wmat(p["w_out"], dtype),
        )
        gate = jax.nn.silu(jax.lax.ragged_dot(xs, wg, counts))
        up = jax.lax.ragged_dot(xs, wi, counts)
        out = jax.lax.ragged_dot(
            gate * up, wo, counts, preferred_element_type=jnp.float32
        )[inv]
    out = out * prob[:, None]
    return out.astype(h.dtype).reshape(B, T, D)


def _paged_layer(x, p, lkv, positions, pidx, off, attn, cfg, dtype,
                 ad=None, aids=None, ep=False):
    """ONE transformer layer shared by every paged path (decode step,
    plain prefill, prefixed prefill) — the paths differ only in position
    arithmetic and the attention geometry, which arrive as ``positions``
    (B,T) / scatter targets (B·T,) / ``attn(q, k, v, lkv)`` → (B,T,Hn·Dh).

    ``ad``/``aids``: this layer's multi-LoRA bank slice + per-row adapter
    ids (empty dict / None → exactly the plain computation).
    """
    B, T, _ = x.shape
    Hn, Dh, Hkv = cfg.n_heads, cfg.head_dim, cfg.kv_heads
    h = rms_norm(x, p["attn_norm"])
    q = _sproj(h, p, "wq", dtype, ad, aids).reshape(B, T, Hn, Dh)
    k = _sproj(h, p, "wk", dtype, ad, aids).reshape(B, T, Hkv, Dh)
    v = _sproj(h, p, "wv", dtype, ad, aids).reshape(B, T, Hkv, Dh)
    q = _rope_rows(q, positions, cfg.rope_theta)
    k = _rope_rows(k, positions, cfg.rope_theta)
    # scatter the new rows (inactive/padding rows target the scratch page —
    # harmless garbage nobody attends to)
    lkv = _kv_write_rows(
        lkv, pidx, off, k.reshape(B * T, Hkv, Dh), v.reshape(B * T, Hkv, Dh)
    )
    o = attn(q, k, v, lkv)
    x = x + _sproj(o, p, "wo", dtype, ad, aids)
    h = rms_norm(x, p["mlp_norm"])
    if cfg.n_experts > 0:
        # expert FFN weights are expert-stacked (E, D, F) — LoRA targets
        # the dense projections only (build_lora_bank rejects adapters
        # against expert-stacked shapes at construction)
        x = x + _moe_ffn_serve(h, p, dtype, ep=ep)
    else:
        gate = jax.nn.silu(_sproj(h, p, "w_gate", dtype, ad, aids))
        up = _sproj(h, p, "w_in", dtype, ad, aids)
        x = x + _sproj(gate * up, p, "w_out", dtype, ad, aids)
    return x, lkv


def default_n_pages(max_batch: int, max_len: int, page_size: int) -> int:
    """Default pool size: capacity-equivalent to slot-contiguous layout
    plus the scratch page — shared by the engine constructor and the HBM
    estimator so the two cannot diverge."""
    return max_batch * (-(-max_len // page_size)) + 1


def estimate_hbm_bytes(
    cfg,
    max_batch: int,
    max_len: int,
    page_size: int,
    n_pages: int = 0,
    kv_int8: bool = False,
    draft_cfg=None,
    param_bytes_per: float = 2.0,
) -> dict:
    """Static HBM accounting for an engine configuration (no allocation).

    The draft model's dense (L, B, M+1, Hkv, Dh) cache scales with
    max_len·B — exactly the contiguous-allocation pressure the paged pool
    removes for the TARGET model (VERDICT r3 weak #4).  This estimator
    makes the trade auditable: tests/test_engine_soak.py pins a
    production-shape configuration inside the chip envelope, so a change
    that silently balloons any component fails loudly.

    ``param_bytes_per``: bytes/param for the target weights (2 = bf16,
    1 ≈ int8 weight-only with its fp32 scales amortized).  Returns a dict
    of byte counts plus ``total``."""
    n_pages = n_pages or default_n_pages(max_batch, max_len, page_size)
    page_elems = page_size * cfg.kv_heads * cfg.head_dim
    per_tensor = cfg.n_layers * n_pages * page_elems
    if kv_int8:
        pool = 2 * per_tensor  # int8 k + v
        pool += 2 * cfg.n_layers * n_pages * page_size * cfg.kv_heads * 4
    else:
        pool = 2 * per_tensor * jnp.dtype(cfg.dtype).itemsize
    target_params = _cfg_param_count(cfg)
    out = {
        "kv_pool_bytes": int(pool),
        "target_param_bytes": int(target_params * param_bytes_per),
    }
    if draft_cfg is not None:
        d = draft_cfg
        dcache = (
            2 * d.n_layers * max_batch * (max_len + 1) * d.kv_heads
            * d.head_dim * jnp.dtype(d.dtype).itemsize
        )
        out["draft_cache_bytes"] = int(dcache)
        out["draft_param_bytes"] = int(
            _cfg_param_count(d) * d.rest_dtype.itemsize  # at-rest weights
        )
    out["total"] = sum(out.values())
    return out


def _cfg_param_count(cfg) -> int:
    """Parameter count from config shapes alone (embed + per-layer attn/FFN
    + unembed; MoE experts included)."""
    D, F, L, V = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab_size
    H = cfg.n_heads * cfg.head_dim
    KV = cfg.kv_heads * cfg.head_dim
    attn = D * (H + 2 * KV) + H * D
    ffn = 3 * D * F
    if cfg.n_experts > 0:
        ffn = cfg.n_experts * ffn + D * cfg.n_experts  # experts + router
    per_layer = attn + ffn + 2 * D  # + the two norms
    return V * D + L * per_layer + D + D * V


def _mesh_ep(mesh) -> bool:
    """True when the serving mesh distributes experts (expert axis > 1)."""
    return mesh is not None and mesh.shape.get("expert", 1) > 1


def _paged_attn_call(q, lkv, tables, lengths, cfg, mesh, dtype):
    """Attend straight off one layer's page pool with the Pallas kernel
    (ops/paged_attention) — in-place page reads, int8 dequant in-kernel,
    sliding window, W-query verify windows.

    q: (B, Hn, Dh) decode or (B, W, Hn, Dh) verify.  Under a mesh the
    kernel is shard_mapped over the ``tensor`` axis on the head dims
    (tables/lengths replicated): each rank attends its own heads against
    its own shard of the pool — no collectives, the output stays
    head-sharded exactly like the gather path's einsums."""
    from ..ops.attention import _use_pallas
    from ..ops.paged_attention import paged_attention

    kw = dict(
        window=cfg.window_size, dtype=dtype, interpret=not _use_pallas()
    )
    sk, sv = lkv.get("ks"), lkv.get("vs")
    if mesh is None:
        return paged_attention(
            q, lkv["k"], lkv["v"], tables, lengths,
            scales_k=sk, scales_v=sv, **kw,
        )
    from jax.sharding import PartitionSpec as P

    from ..utils.jaxcompat import shard_map

    qspec = P(*([None] * (q.ndim - 2)), "tensor", None)
    pspec = P(None, None, "tensor", None)
    in_specs = [qspec, pspec, pspec, P(), P()]
    operands = [q, lkv["k"], lkv["v"], tables, lengths]
    if sk is not None:
        in_specs += [P(None, None, "tensor")] * 2
        operands += [sk, sv]

    def local(q_, k_, v_, tbl, ln, *scales):
        s = dict(zip(("scales_k", "scales_v"), scales))
        return paged_attention(q_, k_, v_, tbl, ln, **s, **kw)

    fn = shard_map(
        local, mesh=mesh, in_specs=tuple(in_specs), out_specs=qspec,
        check_rep=False,
    )
    return fn(*operands)


def _paged_decode_step(params, tokens, kv, tables, lengths, cfg, page_size,
                       bank=None, aids=None, paged_kernel=False, mesh=None):
    """One decode step for every slot at its own position, against the page
    pool.

    tokens: (B,) int32; kv: pool dict (make_kv_pool); tables:
    (B, max_pages) int32 page ids; lengths: (B,) int32 write positions;
    bank/aids: multi-LoRA adapter bank (leaves stacked over layers) +
    per-slot adapter ids; ``paged_kernel``: attend straight off the page
    pool with the Pallas kernel (ops/paged_attention) instead of
    gathering a contiguous copy.  Returns (logits (B, V), new kv).
    """
    dtype = jnp.dtype(cfg.dtype)
    B = tokens.shape[0]
    Hn, Dh = cfg.n_heads, cfg.head_dim
    x = _embed_lookup(params["embed"], tokens, dtype)[:, None, :]  # (B,1,D)
    bidx = jnp.arange(B)
    page_idx = tables[bidx, lengths // page_size]  # (B,)
    offset = lengths % page_size  # (B,)

    def attn(q, k, v, lkv):
        if paged_kernel:
            # in-place page reads: HBM traffic is the live pages once,
            # not a full gathered copy per step (ops/paged_attention)
            o = _paged_attn_call(
                q[:, 0], lkv, tables, lengths, cfg, mesh, dtype
            )
            return o.reshape(B, 1, Hn * Dh)
        # gather the slot's pages into a virtually-contiguous view; position
        # j of the view IS token position j (pages are table-ordered), so
        # the shared cached_attention position mask applies unchanged
        k_all, v_all = _kv_gather(lkv, tables, page_size, dtype)
        return cached_attention(
            q, k_all, v_all, lengths, window=cfg.window_size
        ).reshape(B, 1, Hn * Dh)

    def layer_step(x, scanned):
        p, lkv, ad = scanned  # this layer's pool + bank slices
        return _paged_layer(
            x, p, lkv, lengths[:, None], page_idx, offset, attn, cfg, dtype,
            ad, aids, ep=_mesh_ep(mesh),
        )

    x, new_kv = jax.lax.scan(
        layer_step, x, (params["layers"], kv, bank or {})
    )
    x = rms_norm(x, params["final_norm"])
    logits = (x @ wmat(params["unembed"], dtype))[:, 0, :]
    return logits.astype(jnp.float32), new_kv


def _paged_prefill(params, tokens, kv, pages, t_real, bank=None, aid=None,
                   *, cfg, page_size, mesh=None):
    """One-pass prompt ingestion for ONE slot (the paged analogue of
    ``generate.forward_cached`` with an empty prefix): self-attention over
    the whole prompt block, K/V scattered into the slot's pages.

    tokens: (1, Tpad) — prompt padded to a bucket size; pages: (max_pages,)
    the slot's table row; t_real: scalar count of real tokens (padding K/V
    is routed to the scratch page).  Returns (last-real-position logits
    (V,), caches) — only that row is ever consumed, so only it is
    unembedded.
    """
    from ..ops.attention import flash_attention

    dtype = jnp.dtype(cfg.dtype)
    Tpad = tokens.shape[1]
    Hn, Dh = cfg.n_heads, cfg.head_dim
    x = _embed_lookup(params["embed"], tokens, dtype)  # (1, Tpad, D)
    positions = jnp.arange(Tpad)
    pidx = jnp.where(
        positions < t_real, pages[positions // page_size], SCRATCH_PAGE
    )
    off = positions % page_size

    def attn(q, k, v, lkv):
        # the prompt is the entire valid prefix, so attention is plain
        # causal self-attention within the block — no page gather needed
        # (padding positions sit AFTER every real one; causal masking keeps
        # them out of real queries' windows)
        from .transformer import repeat_kv

        n_rep = Hn // cfg.kv_heads
        return flash_attention(
            q.transpose(0, 2, 1, 3),
            repeat_kv(k, n_rep).transpose(0, 2, 1, 3),
            repeat_kv(v, n_rep).transpose(0, 2, 1, 3),
            True, None, cfg.window_size,
        ).transpose(0, 2, 1, 3).reshape(1, Tpad, Hn * Dh)

    def layer_step(x, scanned):
        p, lkv, ad = scanned  # this layer's pool + bank slices
        return _paged_layer(
            x, p, lkv, positions[None, :], pidx, off, attn, cfg, dtype,
            ad, None if aid is None else aid[None], ep=_mesh_ep(mesh),
        )

    x, new_kv = jax.lax.scan(
        layer_step, x, (params["layers"], kv, bank or {})
    )
    x = jax.lax.dynamic_slice_in_dim(x, t_real - 1, 1, axis=1)  # (1,1,D)
    x = rms_norm(x, params["final_norm"])
    logits = (x @ wmat(params["unembed"], dtype))[0, 0]  # (V,)
    return logits.astype(jnp.float32), new_kv


def _paged_prefill_prefixed(
    params, tokens, kv, pages, t0, t_real, bank=None, aid=None,
    *, cfg, page_size, mesh=None
):
    """One-pass prompt ingestion BEHIND a shared cached prefix.

    Same contract as ``_paged_prefill`` except the slot's pages already
    hold K/V for positions < t0 (prefix-cache hit): the new tokens sit at
    global positions t0..t0+t_real-1, and attention gathers the slot's
    pages so queries see the cached prefix (generate.cached_attention_multi
    geometry).  Padding rows write to the scratch page and their outputs
    are never consumed.
    """
    from .generate import cached_attention_multi

    dtype = jnp.dtype(cfg.dtype)
    Tpad = tokens.shape[1]
    Hn, Dh = cfg.n_heads, cfg.head_dim
    x = _embed_lookup(params["embed"], tokens, dtype)  # (1, Tpad, D)
    rel = jnp.arange(Tpad)
    positions = t0 + rel
    pidx = jnp.where(
        rel < t_real, pages[positions // page_size], SCRATCH_PAGE
    )
    off = positions % page_size

    def attn(q, k, v, lkv):
        k_all, v_all = _kv_gather(lkv, pages[None, :], page_size, dtype)
        return cached_attention_multi(
            q, k_all, v_all, t0, window=cfg.window_size
        ).reshape(1, Tpad, Hn * Dh)

    def layer_step(x, scanned):
        p, lkv, ad = scanned
        return _paged_layer(
            x, p, lkv, positions[None, :], pidx, off, attn, cfg, dtype,
            ad, None if aid is None else aid[None], ep=_mesh_ep(mesh),
        )

    x, new_kv = jax.lax.scan(
        layer_step, x, (params["layers"], kv, bank or {})
    )
    x = jax.lax.dynamic_slice_in_dim(x, t_real - 1, 1, axis=1)  # (1,1,D)
    x = rms_norm(x, params["final_norm"])
    logits = (x @ wmat(params["unembed"], dtype))[0, 0]  # (V,)
    return logits.astype(jnp.float32), new_kv


def _bias_row(req: "Request", vocab_size: int) -> np.ndarray:
    """The additive logit row for a request's allowed_tokens +
    logit_bias — ONE construction shared by the admission prefill
    (host-side add) and the device-resident per-slot bias rows, so the
    two distributions cannot diverge."""
    row = np.zeros(vocab_size, np.float32)
    for t, b in req.logit_bias.items():
        row[t] += b
    if req.allowed_tokens:
        # the whitelist DOMINATES in both directions: non-allowed ids sit
        # at a flat -1e9 regardless of positive bias, and allowed ids'
        # bias is clamped ABOVE that floor so a huge negative bias on an
        # allowed token can't push it beneath the banned set — 'only
        # these ids can ever be sampled' is a hard guarantee
        allowed_idx = np.asarray(req.allowed_tokens, np.int64)
        row[allowed_idx] = np.maximum(row[allowed_idx], -1e8)
        banned = np.ones(vocab_size, bool)
        banned[allowed_idx] = False
        row[banned] = -1e9
    return row


def _stop_row(req: "Request", vocab_size: int) -> np.ndarray:
    """The min_tokens suppression row: -1e9 at the request's stop ids,
    added to every sampling distribution while the emitted count is
    below the floor (vLLM semantics — a stop id can never be generated
    pre-floor).  Out-of-range ids are skipped: they can never be sampled
    anyway, and the host-side ``_stops`` check still honors them."""
    row = np.zeros(vocab_size, np.float32)
    ids = [t for t in req.stop_tokens if 0 <= t < vocab_size]
    if ids:
        row[np.asarray(ids, np.int64)] = -1e9
    return row


def _row_sample_keys(seed_keys, seeded, positions, sub):
    """(B,) per-row sampling keys: seeded rows key off
    fold_in(key(seed), position) — deterministic per request and
    position, independent of batch composition and engine mode; unseeded
    rows key off the engine stream (split per row)."""
    B = positions.shape[0]
    pos_keys = jax.vmap(jax.random.fold_in)(seed_keys, positions)
    stream_keys = jax.random.split(sub, B)
    kd = jnp.where(
        seeded[:, None],
        jax.random.key_data(pos_keys),
        jax.random.key_data(stream_keys),
    )
    return jax.random.wrap_key_data(kd)


def _logprob_rows(logits, chosen, k):
    """(chosen_lp, top_ids, top_lps) for one step's logits.

    logits: (..., V) f32; chosen: (...) int32.  Log-softmax via one
    logsumexp; top-k alternatives share the same normalizer."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    chosen_lp = (
        jnp.take_along_axis(logits, chosen[..., None], axis=-1)[..., 0] - lse
    )
    top_lg, top_ids = jax.lax.top_k(logits, k)
    return chosen_lp, top_ids, top_lg - lse[..., None]


def _fused_serve_chunk(
    params, kv, tables, tokens, lengths, active,
    prompts, prompt_lens, temps, top_ks, top_ps, key,
    bank=None, aids=None, bias=None, fpens=None, ppens=None, counts=None,
    seed_keys=None, seeded=None, stop_rows=None, min_toks=None,
    *, cfg, page_size, n_steps, use_filters, paged_kernel=False, mesh=None,
    logprobs_k=0, use_pen=False, use_seed=False, use_min=False,
):
    """``n_steps`` decode iterations in one scan; sampling AND prompt
    feeding happen on-device.  Returns (sampled (B, n_steps), new caches,
    next_tokens (B,), new_lengths (B,)) — the final carry rides out so
    the NEXT chunk can be dispatched straight off device state without
    a host round trip (the overlapped pipeline threads these futures
    from chunk to chunk); with ``logprobs_k`` > 0 (a separately-compiled
    variant, chosen only when some active request asked) the first
    element becomes (sampled, chosen_lp (B, n_steps), top_ids
    (B, n_steps, k), top_lps (B, n_steps, k)).

    Step s feeds the token at position lengths+s and samples from its
    logits; the host decides afterwards which sampled entries are real
    emissions (position ≥ prompt_len-1) — the device only needs to know
    which NEXT token to feed (prompt token while prefilling, else the
    sample).

    ``use_filters`` is static: the engine picks the filtered variant (one
    argsort per step for per-slot top-k/top-p) only for chunks where some
    active request asks for it, so default sampling never pays for it."""
    from .sampling import sample_batched

    def body(carry, _):
        if use_pen:
            tokens, lengths, key, kv, cnt = carry
        else:
            tokens, lengths, key, kv = carry
            cnt = None
        logits, kv = _paged_decode_step(
            params, tokens, kv, tables, lengths, cfg, page_size, bank, aids,
            paged_kernel=paged_kernel, mesh=mesh,
        )
        if bias is not None:
            # per-slot additive logit bias (zero rows are a bitwise
            # no-op, so non-biased slots/batches are unaffected)
            logits = logits + bias
        if use_min:
            # min_tokens (vLLM): this step samples the token at global
            # position lengths+1, whose emitted index is
            # lengths+1-prompt_lens; while that index is below the
            # slot's floor, stop ids sit at -1e9.  Exact mid-chunk: the
            # gate is per scan step, so a chunk spanning the floor
            # suppresses only its pre-floor positions.
            pre = (lengths + 1 - prompt_lens) < min_toks
            logits = logits + jnp.where(pre[:, None], stop_rows, 0.0)
        if use_pen:
            # count the token FED this step iff it is a GENERATED one
            # (position `lengths` ≥ prompt length — prompt tokens never
            # count, so the first sampled token is never penalized),
            # then penalize this step's distribution
            B = tokens.shape[0]
            gen = jnp.logical_and(active, lengths >= prompt_lens)
            cnt = cnt.at[jnp.arange(B), tokens].add(gen.astype(cnt.dtype))
            logits = logits - fpens[:, None] * cnt - ppens[:, None] * (
                cnt > 0
            )
        key, sub = jax.random.split(key)
        row_keys = (
            _row_sample_keys(seed_keys, seeded, lengths, sub)
            if use_seed else None
        )
        if use_filters:
            sampled = sample_batched(
                logits, sub, temps, top_ks, top_ps, row_keys=row_keys
            )
        else:
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)
            scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
            if use_seed:
                temped = jax.vmap(
                    lambda k, lg: jax.random.categorical(k, lg)
                )(row_keys, scaled).astype(jnp.int32)
            else:
                temped = jax.random.categorical(
                    sub, scaled, axis=-1
                ).astype(jnp.int32)
            sampled = jnp.where(temps > 0, temped, greedy)
        new_len = lengths + active.astype(jnp.int32)
        in_prompt = new_len < prompt_lens
        nxt = jnp.minimum(new_len, prompts.shape[1] - 1)
        prompt_next = jnp.take_along_axis(prompts, nxt[:, None], axis=1)[:, 0]
        next_tok = jnp.where(in_prompt, prompt_next, sampled)
        tokens = jnp.where(active, next_tok, tokens)
        if logprobs_k > 0:
            out = (sampled, *_logprob_rows(logits, sampled, logprobs_k))
        else:
            out = sampled
        carry = (
            (tokens, new_len, key, kv, cnt) if use_pen
            else (tokens, new_len, key, kv)
        )
        return carry, out

    init = (
        (tokens, lengths, key, kv, counts.astype(jnp.float32))
        if use_pen else (tokens, lengths, key, kv)
    )
    carry, outs = jax.lax.scan(body, init, None, length=n_steps)
    kv = carry[3]
    if logprobs_k > 0:
        sampled, chosen_lp, top_ids, top_lps = outs
        return (
            sampled.T, chosen_lp.T,
            jnp.moveaxis(top_ids, 0, 1), jnp.moveaxis(top_lps, 0, 1),
        ), kv, carry[0], carry[1]
    return outs.T, kv, carry[0], carry[1]  # (B, n_steps), kv, feed, len


def _cached_attention_rows(q, cache_k, cache_v, starts, window=0):
    """W-position attention against gathered pages with PER-ROW start
    positions (the batched form of generate.cached_attention_multi).

    q: (B, W, Hn, Dh) — row b's queries sit at global positions
    starts[b]..starts[b]+W-1; cache: (B, M, Hkv, Dh) with the W new K/V
    rows already written at those positions.  Causal: query t of row b
    sees key m iff m <= starts[b] + t; ``window`` > 0 adds sliding-window
    masking.  GQA via the grouped einsum (no cache expansion)."""
    B, W, Hn, Dh = q.shape
    M = cache_k.shape[1]
    Hkv = cache_k.shape[2]
    n_rep = Hn // Hkv
    scale = Dh**-0.5
    qg = (
        q.reshape(B, W, Hkv, n_rep, Dh)
        .transpose(0, 2, 3, 1, 4)
        .astype(jnp.float32)
    )  # (B, Hkv, n_rep, W, Dh)
    kT = cache_k.transpose(0, 2, 1, 3).astype(jnp.float32)  # (B,Hkv,M,Dh)
    vT = cache_v.transpose(0, 2, 1, 3).astype(jnp.float32)
    s = jnp.einsum("bgrtd,bgkd->bgrtk", qg, kT) * scale
    qpos = starts[:, None] + jnp.arange(W)  # (B, W)
    kpos = jnp.arange(M)  # (M,)
    keep = kpos[None, None, :] <= qpos[:, :, None]  # (B, W, M)
    if window > 0:
        keep = keep & ((qpos[:, :, None] - kpos[None, None, :]) < window)
    s = jnp.where(keep[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgrtk,bgkd->bgrtd", p, vT)  # (B,Hkv,n_rep,W,Dh)
    return (
        o.transpose(0, 3, 1, 2, 4).reshape(B, W, Hn, Dh).astype(q.dtype)
    )


def _fused_verify_chunk(
    params, kv, tables, feed, lengths, active,
    temps, top_ks, top_ps, key,
    bank=None, aids=None, bias=None, fpens=None, ppens=None, counts=None,
    plens=None, seed_keys=None, seeded=None, stop_rows=None, min_toks=None,
    *, cfg, page_size, use_filters, paged_kernel=False, mesh=None,
    logprobs_k=0, use_pen=False, use_seed=False, use_min=False,
):
    """ONE wide pass over every slot's verify window (speculative decoding
    inside the paged engine — VERDICT r2 #2).

    feed: (B, W) — row b holds the tokens at global positions
    lengths[b]..lengths[b]+W-1: the confirmed next token at slot 0, then
    prompt tokens (while prefilling incrementally) and/or host-proposed
    drafts (prompt-lookup).  The pass writes all W K/V rows per slot and
    returns ``picked`` (B, W): position j's greedy argmax (or sample, for
    temps>0 rows) over the logits AT fed position j — i.e. the model's own
    choice for global position lengths+j+1.  The host accepts the longest
    fed prefix the model itself would have produced; rejected rows are
    overwritten by the next pass before any query can attend to them, so
    rollback is free (same masking argument as models/speculative.py).

    Positions past max_len route to the scratch page (their outputs are
    never consumed — the host caps acceptance), so slots near the end of
    their allocation stay safe under the fixed-shape window.
    """
    from .sampling import sample_batched

    dtype = jnp.dtype(cfg.dtype)
    B, W = feed.shape
    Hn, Dh = cfg.n_heads, cfg.head_dim
    max_len = tables.shape[1] * page_size
    x = _embed_lookup(params["embed"], feed, dtype)  # (B, W, D)
    positions = lengths[:, None] + jnp.arange(W)  # (B, W)
    in_range = (positions < max_len) & active[:, None]
    page_of = jnp.clip(positions // page_size, 0, tables.shape[1] - 1)
    pidx = jnp.where(
        in_range,
        jnp.take_along_axis(tables, page_of, axis=1),
        SCRATCH_PAGE,
    ).reshape(B * W)
    off = (positions % page_size).reshape(B * W)

    def attn(q, k, v, lkv):
        if paged_kernel:
            # the W-query kernel variant: verify attends through the SAME
            # kernel as plain decode, so a mixed greedy batch never mixes
            # two differently-rounded attention implementations
            o = _paged_attn_call(q, lkv, tables, lengths, cfg, mesh, dtype)
            return o.reshape(B, W, Hn * Dh)
        k_all, v_all = _kv_gather(lkv, tables, page_size, dtype)
        return _cached_attention_rows(
            q, k_all, v_all, lengths, window=cfg.window_size
        ).reshape(B, W, Hn * Dh)

    def layer_step(x, scanned):
        p, lkv, ad = scanned
        return _paged_layer(
            x, p, lkv, positions, pidx, off, attn, cfg, dtype, ad, aids,
            ep=_mesh_ep(mesh),
        )

    x, new_kv = jax.lax.scan(
        layer_step, x, (params["layers"], kv, bank or {})
    )
    x = rms_norm(x, params["final_norm"])
    logits = (x @ wmat(params["unembed"], dtype)).astype(jnp.float32)
    if bias is not None:
        logits = logits + bias[:, None, :]  # per-slot additive logit bias
    if use_min:
        # min_tokens (vLLM): window position j's pick is the token for
        # global position lengths+j+1, emitted index positions+1-plens;
        # suppress stop ids wherever that index is below the floor
        # (``plens`` is passed whenever use_min, independent of use_pen)
        pre = (positions + 1 - plens[:, None]) < min_toks[:, None]
        logits = logits + jnp.where(
            pre[..., None], stop_rows[:, None, :], 0.0
        )
    if use_pen:
        # window position j's generated-so-far counts = ``counts``
        # (generated tokens at positions < lengths) plus the GENERATED
        # fed tokens among fed[0..j].  A W-length scan carries one (B, V)
        # running count (no dense (B, W, V) one-hot/cumsum — W is tiny).
        # Exact for every ACCEPTED position (the fed prefix equals what
        # sequential decoding would have fed); rejected positions'
        # outputs are discarded by the host's acceptance cap.
        Bdim = feed.shape[0]
        gen = positions >= plens[:, None]  # fed token j is generated?

        def pen_step(cnt, inp):
            fj, lj, gj = inp  # (B,), (B, V), (B,)
            cnt = cnt.at[jnp.arange(Bdim), fj].add(gj.astype(cnt.dtype))
            pl = lj - fpens[:, None] * cnt - ppens[:, None] * (cnt > 0)
            return cnt, pl

        _, pen_logits = jax.lax.scan(
            pen_step,
            counts.astype(jnp.float32),
            (feed.T, jnp.moveaxis(logits, 1, 0), gen.T),
        )
        logits = jnp.moveaxis(pen_logits, 0, 1)
    greedy = jnp.argmax(logits, -1).astype(jnp.int32)  # (B, W)
    subs = jax.random.split(key, W)
    if use_seed:
        # per-(row, position) keys: a seeded row samples position p with
        # fold_in(key(seed), p) — exactly the sequential chunk's key for
        # the same position, so seeded sampled requests produce the SAME
        # tokens under speculative and sequential decoding
        def sample_j(lg, k, pos):
            rk = _row_sample_keys(seed_keys, seeded, pos, k)
            if use_filters:
                return sample_batched(
                    lg, k, temps, top_ks, top_ps, row_keys=rk
                )
            return jax.vmap(
                lambda kk, l: jax.random.categorical(kk, l)
            )(rk, lg / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.int32)

        sampled = jax.vmap(sample_j, in_axes=(1, 0, 1), out_axes=1)(
            logits, subs, positions
        )
    elif use_filters:
        sampled = jax.vmap(
            lambda lg, k: sample_batched(lg, k, temps, top_ks, top_ps),
            in_axes=(1, 0), out_axes=1,
        )(logits, subs)
    else:
        sampled = jax.vmap(
            lambda lg, k: jax.random.categorical(
                k, lg / jnp.maximum(temps, 1e-6)[:, None], axis=-1
            ).astype(jnp.int32),
            in_axes=(1, 0), out_axes=1,
        )(logits, subs)
    picked = jnp.where((temps > 0)[:, None], sampled, greedy)
    if logprobs_k > 0:
        # logits[:, j] is the distribution at fed position j — the one
        # the accepted token at window position j+1 (== picked[:, j])
        # was drawn from; the host indexes these by window position
        return (picked, *_logprob_rows(logits, picked, logprobs_k)), new_kv
    return picked, new_kv


def _draft_forward(dparams, dkv, feed, starts, *, dcfg):
    """Contiguous-cache forward for the DRAFT model (draft-model
    speculation): W tokens per row at PER-ROW start positions against a
    dense (L, B, M, Hkv, Dh) cache — the draft is small, so it skips the
    paged pool entirely and with it all page bookkeeping.  Rollback is
    free by the same argument as the big engine's verify window: rows past
    a row's valid count hold garbage only at positions a later call
    rewrites before they can become valid.  Returns (logits (B, W, V),
    dkv')."""
    dtype = jnp.dtype(dcfg.dtype)
    B, W = feed.shape
    Hn, Dh, Hkv = dcfg.n_heads, dcfg.head_dim, dcfg.kv_heads
    M = dkv["k"].shape[2]  # max_len + 1: index M-1 is the overflow scratch
    x = _embed_lookup(dparams["embed"], feed, dtype)  # (B, W, D)
    positions = starts[:, None] + jnp.arange(W)  # (B, W)
    pos_w = jnp.minimum(positions, M - 1)  # overflow → scratch row
    rows = jnp.arange(B)[:, None]

    def layer_step(x, scanned):
        p, lk, lv = scanned
        h = rms_norm(x, p["attn_norm"])
        q = (h @ wmat(p["wq"], dtype)).reshape(B, W, Hn, Dh)
        k = (h @ wmat(p["wk"], dtype)).reshape(B, W, Hkv, Dh)
        v = (h @ wmat(p["wv"], dtype)).reshape(B, W, Hkv, Dh)
        q = _rope_rows(q, positions, dcfg.rope_theta)
        k = _rope_rows(k, positions, dcfg.rope_theta)
        lk = lk.at[rows, pos_w].set(k.astype(lk.dtype))
        lv = lv.at[rows, pos_w].set(v.astype(lv.dtype))
        o = _cached_attention_rows(
            q, lk, lv, starts, window=dcfg.window_size
        ).reshape(B, W, Hn * Dh)
        x = x + (o @ wmat(p["wo"], dtype))
        h2 = rms_norm(x, p["mlp_norm"])
        gate = jax.nn.silu(h2 @ wmat(p["w_gate"], dtype))
        up = h2 @ wmat(p["w_in"], dtype)
        x = x + ((gate * up) @ wmat(p["w_out"], dtype))
        return x, (lk, lv)

    x, (nk, nv) = jax.lax.scan(
        layer_step, x, (dparams["layers"], dkv["k"], dkv["v"])
    )
    x = rms_norm(x, dparams["final_norm"])
    logits = (x @ wmat(dparams["unembed"], dtype)).astype(jnp.float32)
    return logits, {"k": nk, "v": nv}


def _draft_ingest_propose(dparams, dkv, feed, starts, counts, *, dcfg, k):
    """One fused draft pass: ingest each row's ``counts`` new context
    tokens (window-padded), then greedily roll the draft model ``k`` steps
    from the last real position — the draft-model replacement for
    prompt-lookup proposing.  Returns (drafts (B, k), dkv')."""
    logits, dkv = _draft_forward(dparams, dkv, feed, starts, dcfg=dcfg)
    idx = jnp.maximum(counts - 1, 0)[:, None, None]
    last = jnp.take_along_axis(logits, idx, axis=1)[:, 0]  # (B, V)
    tok0 = jnp.argmax(last, -1).astype(jnp.int32)

    def step(carry, _):
        tok, pos, dkv = carry
        lg, dkv = _draft_forward(dparams, dkv, tok[:, None], pos, dcfg=dcfg)
        nxt = jnp.argmax(lg[:, 0], -1).astype(jnp.int32)
        return (nxt, pos + 1, dkv), tok

    (_, _, dkv), toks = jax.lax.scan(
        step, (tok0, starts + counts, dkv), None, length=k
    )
    return jnp.moveaxis(toks, 0, 1), dkv  # (B, k)


class _DeviceBatchState:
    """Per-field device mirrors of the host batch-state arrays.

    The fused chunks consume ~10 per-slot arrays (temps, top_ks, tables
    view, active mask, ...) that change only when admission, release, or
    page growth actually touches the batch — yet the seed engine rebuilt
    every one of them with ``jnp.asarray`` on EVERY dispatch.  This cache
    keeps one persistent device array per field and refreshes it only
    when the host copy has actually changed.

    Dirtiness is detected by content (``np.array_equal`` against the
    snapshot the device copy was built from) rather than by flags at
    every mutation site: a missed flag would silently serve stale state,
    while a comparison is self-correcting and costs nanoseconds on the
    (B,)-sized arrays involved.  Big arrays (``prompts``) use an explicit
    version counter instead (``get_versioned``), bumped at their single
    mutation site.  ``uploads`` counts actual host→device refreshes —
    the transfer probe tests/test_serve_overlap.py asserts it stays flat
    across steady-state decode steps."""

    def __init__(self):
        self._dev: dict = {}
        self._src: dict = {}
        self._ver: dict = {}
        self.uploads = 0  # host→device refreshes (transfer-count probe)

    def get(self, name: str, host_arr: np.ndarray):
        """Device array for ``host_arr``, re-uploaded only on change."""
        src = self._src.get(name)
        if (
            src is None
            or src.shape != host_arr.shape
            or not np.array_equal(src, host_arr)
        ):
            self._dev[name] = jnp.asarray(host_arr)
            self._src[name] = host_arr.copy()
            self.uploads += 1
        return self._dev[name]

    def get_versioned(self, name: str, host_arr: np.ndarray, version: int):
        """Like ``get`` but keyed by an explicit version counter — for
        arrays too big to compare per dispatch (the (B, max_len) prompt
        buffer, mutated only at admission)."""
        if self._ver.get(name) != version:
            self._dev[name] = jnp.asarray(host_arr)
            self._ver[name] = version
            self.uploads += 1
        return self._dev[name]


def _prefix_page_key(prev: bytes, toks: np.ndarray) -> bytes:
    """One link of the prefix-cache key chain: a 16-byte BLAKE2b digest
    over (previous link, this page's token bytes).  Replaces the seed's
    nested-tuple hash chain — that built and hashed an O(page) tuple per
    page per ADMISSION (O(prompt) total, on the host path the overlapped
    pipeline is trying to empty); this is one incremental digest over the
    raw int32 bytes.  Content-addressing is preserved exactly: equal
    token prefixes (under the same adapter seed) produce equal digests,
    and 128-bit digests make accidental collisions (which would alias
    cached K/V) negligible.

    The chain definition is SHARED with the fleet router
    (utils/prefixdigest.py): the router computes the same digests over
    incoming prompts to route a session to the replica already holding
    its prefix — a drift between the two would silently turn affinity
    routing into noise."""
    return prefixdigest.prefix_page_key(prev, toks.tobytes())


def _prefix_seed(adapter_id: int) -> bytes:
    """Chain seed: K/V content depends on the adapter (wk/wv deltas), so
    pages cached under one adapter must never match another's prompts."""
    return prefixdigest.prefix_seed(adapter_id)


def _bias_row_cached(req: "Request", vocab_size: int) -> np.ndarray:
    """``_bias_row`` memoized on the request: admission needs the row
    twice (device-resident slot row + the host-side prefill add) and a
    spilled request re-admits with identical bias — one O(vocab) build
    instead of up to four."""
    row = getattr(req, "_bias_row_memo", None)
    if row is None or row.shape[0] != vocab_size:
        row = _bias_row(req, vocab_size)
        req._bias_row_memo = row
    return row


def _stop_row_cached(req: "Request", vocab_size: int) -> np.ndarray:
    """``_stop_row`` memoized on the request (same double-use as the
    bias row)."""
    row = getattr(req, "_stop_row_memo", None)
    if row is None or row.shape[0] != vocab_size:
        row = _stop_row(req, vocab_size)
        req._stop_row_memo = row
    return row


@dataclass
class _PendingChunk:
    """An in-flight fused decode chunk: the device output futures plus
    the host-side snapshot needed to drain it later.  ``pairs`` pins the
    (slot, request) identity at dispatch time — a slot released or
    re-tenanted before the drain (stop discovered late, spill, cancel)
    is skipped, which is what makes the overlapped pipeline's bounded
    one-chunk overshoot safe to discard."""

    out: object  # device arrays: sampled (+ logprob triplet when want_lp)
    want_lp: bool
    n_steps: int
    pos0: np.ndarray  # per-slot lengths BEFORE the chunk ran
    pairs: list  # [(slot index, Request at dispatch time), ...]


class InferenceEngine:
    """Paged-cache continuous batching with fused K-step decode chunks."""

    def __init__(
        self,
        params,
        cfg: TransformerConfig,
        max_batch: int = 8,
        max_len: int = 512,
        page_size: int = 16,
        n_pages: int = 0,
        fused_steps: int = 8,
        kv_int8: bool = False,
        prefix_cache: bool = False,
        adapters: Optional[dict[str, dict]] = None,
        spec_k: int = 0,
        spec_ngram: int = 3,
        draft: Optional[tuple] = None,
        mesh=None,
        paged_kernel: bool = False,
        logprobs_k: int = 5,
        prefill_chunk: int = 0,
        max_queue: int = 0,
        overlap: bool = True,
        compile_cache=None,
    ):
        """``spec_k`` > 0 enables speculative decoding inside the engine:
        steps where some greedy slot is generating run a fused VERIFY
        chunk (one wide pass over a spec_k+1 window per slot, prompt-lookup
        drafts, per-slot variable acceptance) instead of spec_k+1
        sequential decode steps — device time per accepted token divides
        by the acceptance length, and greedy outputs are EXACTLY those of
        the non-speculative engine.  Sampled (temperature>0) slots advance
        one token per verify pass (their window still fast-feeds prompt
        tokens); steps where only sampled slots are generating fall back
        to the sequential fused chunk automatically.  ``spec_ngram`` is
        the prompt-lookup match length (models/speculative.propose_ngram).

        ``draft``: (draft_params, draft_cfg) — drafts come from a small
        DRAFT MODEL instead of prompt lookup (requires ``spec_k`` > 0 and
        a matching vocab; dense draft only).  The draft keeps its own
        dense per-slot KV cache and per-slot ingested-length counter; each
        verify pass first catches the draft up on newly-confirmed context
        (one fused pass, chunked for long prompts) and rolls it spec_k
        greedy steps.  The SAME verify/accept machinery runs either way —
        greedy outputs stay token-identical to the non-speculative engine;
        only the acceptance RATE changes (a trained draft beats n-gram
        lookup on non-repetitive text).

        ``paged_kernel``: decode attention reads the page pool IN PLACE
        via the Pallas kernel (ops/paged_attention) instead of gathering
        a contiguous copy per step — the long-context HBM-bandwidth win.
        Composes with kv_int8 (in-kernel dequant), sliding windows,
        spec_k/draft speculation (the W-query verify-window kernel), and
        a mesh (shard_map over the tensor axis); the only hard
        requirement is head counts divisible by the tensor axis when
        both paged_kernel and mesh are on.  Opt-in (default off) until
        an on-chip run validates the Mosaic lowering
        (bench --tpu-section=pagedattn).

        ``logprobs_k``: compiled top-k width for per-token logprobs.
        Requests opt in per-request (``Request.logprobs`` ≤ this cap);
        the logprob-emitting chunk variants compile lazily and only
        batches containing an asking request pay the device top-k.

        ``mesh``: serve TENSOR-PARALLEL over a `jax.sharding.Mesh` with a
        ``tensor`` axis — for checkpoints too big for one chip's HBM.
        Weights take the training sharding rules (parallel/sharding.py)
        restricted to the mesh's axes; the paged KV pool shards its
        kv-head axis over ``tensor`` (each rank holds its own heads'
        pages — pages stay whole per rank, so the host-side page/table
        machinery is untouched); activations/collectives are GSPMD's from
        there, exactly as in training.  Host-side state (tables, lengths,
        prompts, prefix cache) is unsharded — the engine logic is
        identical single-chip and multi-chip.

        ``overlap``: double-buffered chunk dispatch — chunk N+1 is
        dispatched off device-resident state immediately after chunk N,
        and N's sampled tokens drain (device→host) while N+1 runs, so
        the accelerator never idles on host bookkeeping.  Host-side
        stop/cancel/max-token detection lags one chunk; the engine
        over-runs a finishing slot by at most ONE chunk and discards
        those tokens at drain time.  Greedy and seeded-sampled outputs
        are bit-identical to ``overlap=False`` (the correctness mode:
        ``--serve-overlap=off``); unseeded sampled requests may diverge
        after another request's completion because overshoot chunks
        advance the engine RNG stream.  Batches with frequency/presence
        penalties fall back to the non-overlapped loop automatically
        (their cross-chunk counts are host-rebuilt).
        """
        self.mesh = mesh
        self.params = (
            params if mesh is None else _shard_params_for_mesh(params, mesh)
        )
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len
        self.page_size = page_size
        self.max_pages_per_slot = -(-max_len // page_size)
        # default pool: capacity-equivalent to slot-contiguous (+ scratch);
        # pass a smaller n_pages to exploit paging's memory win
        self.n_pages = n_pages or default_n_pages(
            max_batch, max_len, page_size
        )
        assert self.n_pages >= 2, "need at least scratch + one real page"
        self.fused_steps = max(1, fused_steps)
        self.kv_int8 = kv_int8
        self.paged_kernel = paged_kernel
        # round 4 (VERDICT r3 #2): the kernel composes with kv_int8
        # (in-kernel dequant through the compute dtype — bit-identical to
        # _kv_gather), sliding windows (dead pages skipped, DMA included),
        # spec_k (a W-query verify-window kernel variant — decode and
        # verify share one attention implementation, so determinism
        # holds), and a mesh (shard_map over the tensor axis on the head
        # dims).  The only remaining constraint is structural: head
        # sharding requires the head counts to divide the tensor axis.
        if paged_kernel and mesh is not None:
            t = mesh.shape.get("tensor", 1)
            if cfg.n_heads % t or cfg.kv_heads % t:
                raise ValueError(
                    f"paged_kernel over a tensor={t} mesh needs n_heads "
                    f"({cfg.n_heads}) and kv_heads ({cfg.kv_heads}) "
                    "divisible by the tensor axis"
                )
        self.kv = make_kv_pool(cfg, self.n_pages, page_size, kv_int8)
        if mesh is not None:
            self.kv = _shard_kv_for_mesh(self.kv, cfg, mesh)
        self.free_pages = list(range(self.n_pages - 1, SCRATCH_PAGE, -1))
        self.tables = np.zeros(
            (max_batch, self.max_pages_per_slot), np.int32
        )  # all → scratch
        self.slot_pages: list[list[int]] = [[] for _ in range(max_batch)]
        self.lengths = np.zeros(max_batch, np.int32)
        self.slots: list[Optional[Request]] = [None] * max_batch
        self.prompts = np.zeros((max_batch, max_len), np.int32)
        self.prompt_lens = np.zeros(max_batch, np.int32)
        self.temps = np.zeros(max_batch, np.float32)
        self.top_ks = np.zeros(max_batch, np.int32)
        self.top_ps = np.ones(max_batch, np.float32)
        # multi-LoRA: stacked adapter bank + per-slot adapter ids (0 = base)
        if adapters:
            self.lora_bank, self.adapter_index = build_lora_bank(
                adapters, jnp.dtype(cfg.dtype), base_layers=params["layers"]
            )
        else:
            self.lora_bank, self.adapter_index = {}, {"": 0}
        self.adapter_ids = np.zeros(max_batch, np.int32)
        # per-slot additive logit-bias rows, DEVICE-resident so the fused
        # chunks pay no per-dispatch transfer; zero rows are a bitwise
        # no-op on the logits.  _bias_set tracks which rows need clearing
        # at release (so bias-free serving never dispatches the updates).
        self._bias_dev = jnp.zeros(
            (max_batch, cfg.vocab_size), jnp.float32
        )
        self._bias_set = np.zeros(max_batch, bool)
        # min_tokens stop suppression: per-slot -1e9 rows at stop ids,
        # device-resident like the bias rows; the use_min chunk variant
        # gates them per scan position so the floor is exact even when a
        # chunk spans it.  Both the variant's compile AND the (B, V)
        # buffer are lazy — a deployment that never combines stop_tokens
        # with min_tokens > 0 pays neither the compile nor the HBM.
        self._stop_dev = None
        self._stop_set = np.zeros(max_batch, bool)
        self.min_toks = np.zeros(max_batch, np.int32)
        self.freq_pens = np.zeros(max_batch, np.float32)
        self.pres_pens = np.zeros(max_batch, np.float32)
        # per-request sampling seeds: typed key per slot + a host-side
        # flag; unseeded slots keep drawing from the engine stream
        self._seed_keys = jax.vmap(jax.random.key)(
            jnp.zeros(max_batch, jnp.uint32)
        )
        self._seeded = np.zeros(max_batch, bool)
        # chunked prefill (>0): long prompts ingest at most this many
        # tokens per engine-loop iteration instead of one monolithic
        # pass, so decoding slots keep emitting between chunks (no
        # head-of-line blocking behind a 7k-token admission)
        self.prefill_chunk = max(0, prefill_chunk)
        self.prefilling = np.zeros(max_batch, bool)
        self.next_token = np.zeros(max_batch, np.int32)
        self.emitted = np.zeros(max_batch, np.int32)
        self.stalled = np.zeros(max_batch, bool)  # couldn't get pages
        # generated tokens already in the FED prompt (non-zero only for a
        # spilled-and-resumed request, whose fed prompt = prompt + output
        # so far); every output-by-position index shifts by this
        self.gen_before = np.zeros(max_batch, np.int32)
        self.priorities = np.zeros(max_batch, np.int32)  # per-slot class
        # priority admission: highest class first, FIFO within a class
        self.queue: "queue.PriorityQueue" = queue.PriorityQueue()
        self._submit_seq = itertools.count()
        self.spills = 0  # low-priority slots spilled under page pressure
        # bounded admission (0 = unbounded): when the queue holds this
        # many requests, submit() rejects with QUEUE_FULL_ERROR (HTTP
        # 429) instead of growing tail latency without bound.  Spill
        # requeues bypass the cap — they are in-flight work, not new
        # admissions.
        self.max_queue = max(0, max_queue)
        self._cap_lock = threading.Lock()  # atomic cap-check + enqueue
        # graceful drain (k8s SIGTERM contract): True → submit() rejects
        # new requests while in-flight ones run to completion; the HTTP
        # front end turns this into 503s + a not-ready /healthz so the
        # Service stops routing here before the pod exits
        self.draining = False
        # work signal: set whenever a request is enqueued so an idle
        # EngineLoop can park on it instead of busy-polling every 2 ms
        # (server/inference.py; stop/drain set it too, to wake the loop)
        self._work = threading.Event()
        # -- overlapped decode pipeline state --------------------------------
        self.overlap = overlap
        # device-resident batch state: persistent device mirrors of the
        # per-slot host arrays, refreshed only when the batch changes
        self._ds = _DeviceBatchState()
        self._prompts_version = 0  # bumped by _admit (prompts row writes)
        # in-flight carry: (next_tokens, lengths) device futures returned
        # by the last fused chunk — the next chunk dispatches straight
        # off them (no host round trip).  ``_carry_dirty`` lists slots
        # whose host lengths/next_token were mutated outside the chunk
        # (admission, prefill); those rows are patched device-side at the
        # next dispatch.  None → rebuild from host (mode switch, verify).
        self._carry = None
        self._carry_dirty: set[int] = set()
        self._pending: Optional[_PendingChunk] = None  # undrained chunk
        # host-gap telemetry: the host-imposed device-idle window between
        # consecutive decode chunks — from the previous chunk's results
        # landing on the host (drain transfer done) to the next dispatch
        # call.  When the next chunk was dispatched BEFORE the previous
        # one drained (the overlapped pipeline's steady state) the device
        # had queued work the whole time and the gap is zero by
        # construction.  Reset by prefill/verify dispatches so only
        # back-to-back decode chunks are measured.
        self.host_gap_ns = 0
        self.host_gap_chunks = 0
        self.last_host_gap_ms = 0.0
        self._last_drain_done: Optional[int] = None
        # per-chunk host-gap samples (ms), buffered for the scrape path:
        # the ENGINE thread appends (GIL-atomic), /metrics drains into
        # the tpu_serve_host_gap_ms histogram so operators get p50/p99
        # instead of whichever chunk scraped last.  Bounded: with nothing
        # scraping, keep the newest half (same stance as the TimedLock
        # wait buffers).
        self._gap_buf: list[float] = []
        self._gap_buf_cap = 8192
        # monotonic count of tokens delivered to clients (the profile
        # observatory's throughput numerator — a host-side int add per
        # token, read by the engine loop off the device path)
        self.tokens_emitted = 0
        # in-flight chunks discarded because their slot was released or
        # re-tenanted between dispatch and drain (stop/cancel discovered
        # late under overlap, spill, drain-for-migration).  THE observable
        # behind the fleet/defrag "at most one lost in-flight chunk per
        # moved pod" contract — tests and bench assert on its delta.
        self.chunks_discarded = 0
        # two chunk variants: plain sampling, and per-slot top-k/top-p
        # filtering (compiled lazily, only if a request ever asks for it)
        self.logprobs_k = max(0, logprobs_k)
        # warm-start compilation plane (compilecache/): when a cache is
        # attached, every fused-kernel dispatch below routes through AOT
        # executables keyed by (static fingerprint, input shapes) — a
        # shape pre-lowered at warm-up (or persisted by a previous
        # process) never compiles on the admission path.  ``None`` (the
        # default) keeps the exact historical jit dispatch.
        self.compile_cache = compile_cache
        _devs = jax.devices()
        self._aot_fp = (
            repr(cfg), max_batch, max_len, page_size, self.fused_steps,
            kv_int8, paged_kernel, self.logprobs_k,
            tuple(sorted(self.adapter_index)),
            tuple(sorted(mesh.shape.items())) if mesh is not None else None,
            jax.__version__, jax.default_backend(), jax.device_count(),
            # device KIND, not just backend: a fleet-shared cache dir
            # (PVC) serves mixed v5e/v5p/v6e replicas — without the kind
            # in the key two generations would perpetually quarantine
            # each other's entries under the same digest
            getattr(_devs[0], "device_kind", "") if _devs else "",
        )

        def _aot(jitfn, tag):
            from ..compilecache.aot import wrap as _aot_wrap

            return _aot_wrap(jitfn, compile_cache, self._aot_fp, tag)

        self._chunks = {
            (use_filters, want_lp, use_pen, use_seed, use_min): _aot(
                jax.jit(
                    functools.partial(
                        _fused_serve_chunk,
                        cfg=cfg,
                        page_size=page_size,
                        n_steps=self.fused_steps,
                        use_filters=use_filters,
                        paged_kernel=self.paged_kernel,
                        mesh=mesh,
                        logprobs_k=self.logprobs_k if want_lp else 0,
                        use_pen=use_pen,
                        use_seed=use_seed,
                        use_min=use_min,
                    ),
                    donate_argnums=(1,),  # the kv pool pytree
                ),
                f"serve_chunk:{int(use_filters)}{int(want_lp)}{int(use_pen)}"
                f"{int(use_seed)}{int(use_min)}",
            )
            for use_filters in (False, True)
            for want_lp in (False, True)
            for use_pen in (False, True)
            for use_seed in (False, True)
            for use_min in (False, True)
        }
        self.spec_k = max(0, spec_k)
        self.spec_ngram = spec_ngram
        self.steps_run = 0  # decode/verify steps (device dispatches)
        self.prefills_run = 0  # prompt-ingest dispatches
        self.spec_passes = 0  # verify passes run
        self.spec_accepted = 0  # accepted draft tokens (beyond the bonus)
        self.draft = draft
        if draft is not None:
            dparams, dcfg = draft
            if self.spec_k <= 0:
                raise ValueError("draft model needs spec_k > 0")
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {dcfg.vocab_size} != target {cfg.vocab_size}"
                )
            if dcfg.n_experts > 0:
                raise ValueError("draft model must be dense (n_experts=0)")
            self.draft_cfg = dcfg
            if mesh is not None:
                # the draft is small: replicate it (and its cache, below)
                # across the mesh — host-committed draft weights against a
                # device-resident dkv would otherwise mix placements at
                # the first verify pass
                from jax.sharding import NamedSharding, PartitionSpec

                rep = NamedSharding(mesh, PartitionSpec())
                dparams = jax.tree.map(
                    lambda x: jax.device_put(x, rep), dparams
                )
            self.draft_params = dparams
            # max_len + 1: the LAST index is a scratch row — rollout
            # positions past max_len write there instead of clamping onto
            # the real final position's K/V (the paged pool solves the
            # same overflow with its scratch page)
            dshape = (
                dcfg.n_layers, max_batch, max_len + 1, dcfg.kv_heads,
                dcfg.head_dim,
            )
            ddtype = jnp.dtype(dcfg.dtype)
            self.dkv = {
                "k": jnp.zeros(dshape, ddtype),
                "v": jnp.zeros(dshape, ddtype),
            }
            if mesh is not None:
                self.dkv = {
                    k: jax.device_put(v, rep) for k, v in self.dkv.items()
                }
            self.draft_len = np.zeros(max_batch, np.int32)
            self._draft_chunk = 64  # pre-ingest width for long prompts
            self._draft_ip = jax.jit(
                functools.partial(
                    _draft_ingest_propose, dcfg=dcfg, k=self.spec_k
                ),
                donate_argnums=(1,),
            )
            self._draft_ingest = jax.jit(
                functools.partial(_draft_forward, dcfg=dcfg),
                donate_argnums=(1,),
            )
        self._verify_chunks = {
            (use_filters, want_lp, use_pen, use_seed, use_min): _aot(
                jax.jit(
                    functools.partial(
                        _fused_verify_chunk,
                        cfg=cfg,
                        page_size=page_size,
                        use_filters=use_filters,
                        paged_kernel=self.paged_kernel,
                        mesh=mesh,
                        logprobs_k=self.logprobs_k if want_lp else 0,
                        use_pen=use_pen,
                        use_seed=use_seed,
                        use_min=use_min,
                    ),
                    donate_argnums=(1,),  # the kv pool pytree
                ),
                f"verify_chunk:{self.spec_k}:{int(use_filters)}"
                f"{int(want_lp)}{int(use_pen)}{int(use_seed)}{int(use_min)}",
            )
            for use_filters in (False, True)
            for want_lp in (False, True)
            for use_pen in (False, True)
            for use_seed in (False, True)
            for use_min in (False, True)
        }
        self._prefill = _aot(
            jax.jit(
                functools.partial(
                    _paged_prefill, cfg=cfg, page_size=page_size, mesh=mesh
                ),
                donate_argnums=(2,),  # the kv pool pytree
            ),
            "prefill",
        )
        self._prefill_prefixed = _aot(
            jax.jit(
                functools.partial(
                    _paged_prefill_prefixed, cfg=cfg, page_size=page_size,
                    mesh=mesh,
                ),
                donate_argnums=(2,),
            ),
            "prefill_prefixed",
        )
        self._key = jax.random.key(0)
        # -- automatic prefix caching (vLLM-style, opt-in) -------------------
        # Full pages of a finished request's prompt stay in the pool under a
        # hash-chain key (prev_key, page_tokens); a new request's prompt is
        # matched page-by-page and shared pages are attached read-only (its
        # first write position is page-aligned past the match, so shared
        # content is never overwritten).  Zero-reference cached pages are
        # evicted LRU when the free list runs dry.
        self.prefix_cache = prefix_cache
        self.page_ref = np.zeros(self.n_pages, np.int32)
        self.prefix_entries: dict = {}  # key → page id
        self.page_key: dict[int, object] = {}  # page id → key (for eviction)
        self.page_lru: dict[int, int] = {}
        self._lru_clock = 0
        self.prefix_hit_tokens = 0
        # -- disaggregated serving data plane (fleet/, utils/kvwire) ---------
        # Cross-thread engine tasks: HTTP handlers may not touch slot /
        # page / pool state (the engine thread is its sole owner), so
        # KV export/import and migration run as queued thunks the engine
        # thread drains at the top of every _admit (run_task parks the
        # caller until its thunk ran).  The queue is also part of the
        # EngineLoop's idle re-check, so a task can never be lost
        # between the loop's _work.clear() and its park.
        self._tasks: "queue.Queue" = queue.Queue()
        # shipping + adoption counters (/v1/stats "kv" section and the
        # scrape-time tpu_kv_* gauges — host-side int adds; a refused
        # migrate-out handoff rolls its bumps back so fleet-wide
        # sum(migrated_out) == sum(migrated_in) holds)
        self.kv_pages_exported = 0
        self.kv_pages_imported = 0
        self.kv_exports = 0  # export bundles served
        self.kv_imports = 0  # import bundles applied
        self.sessions_migrated_out = 0
        self.sessions_migrated_in = 0
        # admission-level prefix-cache outcome counters (hit = at least
        # one full page attached at admission)
        self.prefix_lookups = 0
        self.prefix_admission_hits = 0
        # tokens each live slot got from the prefix cache at admission —
        # a ``kv`` policy-verb input: a slot with a large cached/adopted
        # prefix is the cheapest eviction (re-admission re-matches it)
        self.matched_toks = np.zeros(max_batch, np.int32)

    # -- public API ----------------------------------------------------------

    def _invalid_reason(self, req: Request) -> Optional[str]:
        """Shared request validation + normalization (seed domain,
        logprobs clamp) for BOTH admission doors — local ``submit`` and
        migrated-session ``resume_session``.  One rule set, two error
        deliveries (req.error vs raise): a migrated session must never
        be accepted with parameters local submission would reject.
        Mutates req (seed normalization, logprobs clamp) — call once."""
        if len(req.prompt) + req.max_new_tokens > self.max_len:
            return (
                f"prompt {len(req.prompt)} + max_new_tokens "
                f"{req.max_new_tokens} exceeds max_len {self.max_len}"
            )
        if req.adapter not in self.adapter_index:
            return (
                f"unknown adapter {req.adapter!r} "
                f"(registered: {sorted(self.adapter_index)})"
            )
        if req.seed is not None:
            if isinstance(req.seed, bool) or not isinstance(req.seed, int):
                return "seed must be an integer"
            if req.temperature <= 0:
                req.seed = None  # greedy ignores draws; don't pay the
                # seeded chunk variant's compile for a no-op
            else:
                req.seed &= 0xFFFFFFFF  # uint32 domain (np.uint32 of an
                # out-of-range int raises OverflowError under NumPy 2)
        for pen in (req.frequency_penalty, req.presence_penalty):
            if not np.isfinite(pen):
                return "penalties must be finite"
        if req.allowed_tokens and not all(
            isinstance(k, int) and not isinstance(k, bool)
            and 0 <= k < self.cfg.vocab_size
            for k in req.allowed_tokens
        ):
            return (
                f"allowed_tokens must be token ids in "
                f"[0, {self.cfg.vocab_size})"
            )
        if req.logit_bias and not all(
            isinstance(k, int) and not isinstance(k, bool)
            and 0 <= k < self.cfg.vocab_size
            and isinstance(v, (int, float)) and np.isfinite(v)
            for k, v in req.logit_bias.items()
        ):
            return (
                f"logit_bias keys must be token ids in "
                f"[0, {self.cfg.vocab_size}) with finite values"
            )
        if req.logprobs > 0 and self.logprobs_k <= 0:
            # a silent drop would be indistinguishable from a bug to the
            # caller; fail the request like any other invalid ask
            return "engine built with logprobs_k=0 (logprobs off)"
        if isinstance(req.priority, bool) or not isinstance(
            req.priority, int
        ):
            return "priority must be an integer"
        # the top-k width is compiled into the chunk (engine logprobs_k);
        # a wider ask gets the compiled width
        req.logprobs = min(max(0, req.logprobs), self.logprobs_k)
        return None

    def submit(self, req: Request) -> Request:
        """Validate and enqueue; invalid requests are failed immediately
        (req.error set, done signaled) rather than poisoning the loop."""
        if self.draining:
            req.error = DRAINING_ERROR
            req.done.set()
            return req
        if len(req.prompt) < 1:
            req.error = "empty prompt"
            req.done.set()
            return req
        err = self._invalid_reason(req)
        if err is not None:
            req.error = err
            req.done.set()
            return req
        if req.max_new_tokens <= 0:
            req.done.set()  # nothing to generate
            return req
        if self.max_queue:
            # cap-check + enqueue must be atomic across handler threads
            # (ThreadingHTTPServer), else a burst overshoots the bound;
            # entries whose clients already cancelled (timeout 504s) are
            # purged first so dead requests can't 429 live traffic
            with self._cap_lock:
                if self.queue.qsize() >= self.max_queue:
                    self._purge_cancelled_queued()
                    if self.queue.qsize() >= self.max_queue:
                        req.error = QUEUE_FULL_ERROR
                        req.done.set()
                        return req
                self._enqueue(req)
            return req
        self._enqueue(req)
        return req

    def _purge_cancelled_queued(self) -> None:
        """Drop queued entries whose requests were cancelled while
        waiting (client timeout/disconnect) — normally reaped lazily by
        _admit, but the admission cap must not count them against live
        traffic.  Safe against the engine thread: all list surgery is
        under the queue's own mutex."""
        import heapq

        with self.queue.mutex:
            q = self.queue.queue
            dead = [e for e in q if e[2].cancelled]
            for e in dead:
                q.remove(e)
            if dead:
                heapq.heapify(q)
        for e in dead:
            e[2].done.set()

    def _enqueue(self, req: Request) -> None:
        """Priority-ordered admission queue entry (also the spill-requeue
        path): highest class first, FIFO within a class."""
        if req.trace_ctx is not None:
            TRACER.point(
                "engine.queued", parent=req.trace_ctx,
                priority=req.priority, resumed=bool(req.output),
            )
        if req.t_submit == 0.0:
            req.t_submit = time.monotonic()
        self.queue.put((-req.priority, next(self._submit_seq), req))
        self._work.set()  # wake a parked EngineLoop

    def queue_depths(self) -> dict[int, int]:
        """Queued requests per priority class (metrics/stats)."""
        with self.queue.mutex:
            snapshot = [item[2] for item in self.queue.queue]
        out: dict[int, int] = {}
        for r in snapshot:
            out[r.priority] = out.get(r.priority, 0) + 1
        return out

    @property
    def device_uploads(self) -> int:
        """Total host→device refreshes of batch state (mirror uploads +
        carry rebuilds/patches) — the transfer-count probe.  Flat across
        steady-state decode steps: unchanged state is never re-sent."""
        return self._ds.uploads

    def _gap_sample(self, gap_ms: float) -> None:
        """Buffer one per-chunk host-gap sample for the scrape path (one
        append on the engine thread; trim keeps the NEWEST samples when
        nothing scrapes)."""
        buf = self._gap_buf
        buf.append(gap_ms)
        if len(buf) > self._gap_buf_cap:
            del buf[: self._gap_buf_cap // 2]

    def drain_host_gaps(self) -> list[float]:
        """Move the buffered per-chunk host-gap samples out (scrape path:
        /metrics folds them into the tpu_serve_host_gap_ms histogram).
        Slice-then-del is safe against the engine thread's concurrent
        appends landing at the tail."""
        buf = self._gap_buf
        n = len(buf)
        vals = buf[:n]
        del buf[:n]
        return vals

    def host_gap_stats(self) -> dict:
        """Host-gap telemetry: wall time between consecutive fused decode
        chunk dispatches (dispatch-return → next dispatch-call).  That
        window is when the device can starve on host bookkeeping; the
        overlapped pipeline exists to shrink it.  ``mean_ms`` is the
        running mean since engine start."""
        n = self.host_gap_chunks
        return {
            "chunks": n,
            "mean_ms": (self.host_gap_ns / 1e6 / n) if n else 0.0,
            "last_ms": self.last_host_gap_ms,
            "overlap": self.overlap,
        }

    def run_until_idle(self, max_steps: int = 10_000) -> None:
        """Drive fused chunks until no request is active or queued."""
        for _ in range(max_steps):
            self._admit()
            if not any(s is not None for s in self.slots):
                if self.queue.empty():
                    return
                continue
            self.step()
        raise RuntimeError("run_until_idle: step budget exhausted")

    # -- warm-start compilation plane (compilecache/) ------------------------

    @staticmethod
    def _pow2_lattice(start: int, cap: int) -> list[int]:
        """The power-of-two bucket values the dispatch paths round up to,
        clamped at ``cap`` — exactly the widths the pad/bucket recipes in
        ``_prefill_dispatch`` / ``_prepare_step`` can produce."""
        out, w = [], start
        while True:
            out.append(min(w, cap))
            if w >= cap:
                break
            w *= 2
        return sorted(set(out))

    def aot_signatures(self, variants: str = "minimal") -> list:
        """The engine's (batch, length)-bucket shape lattice as concrete
        dispatch signatures: ``[(label, fn, args), ...]`` where ``fn``
        is the AOT-wrapped dispatch callable and ``args`` mirror — shape
        for shape, dtype for dtype — what the live paths pass.  The
        warm-up driver lowers each BEFORE the pod reports Ready, so
        serving admission never eats an XLA compile on a lattice shape.

        ``variants``: ``minimal`` pre-lowers the chunk variants default
        traffic hits (plain + top-k/p filtered sampling); ``full`` walks
        all 32 flag combinations (logprobs / penalties / seeds /
        min-token suppression too).

        Args intentionally reuse live engine state (params / kv /
        lora_bank / bias rows) so the signatures cannot drift from the
        real dispatches; zero-filled host arrays stand in for the
        per-slot state.  Nothing here executes — the warm-up path only
        ever calls ``fn.build(*args)`` (lower + compile)."""
        B, V = self.max_batch, self.cfg.vocab_size
        key = jax.random.key(0)
        if variants == "full":
            import itertools

            vtuples = list(itertools.product((False, True), repeat=5))
        else:
            vtuples = [
                (False, False, False, False, False),
                (True, False, False, False, False),
            ]
        z32 = lambda *s: np.zeros(s, np.int32)  # noqa: E731
        zf = lambda *s: np.zeros(s, np.float32)  # noqa: E731
        zb = lambda *s: np.zeros(s, bool)  # noqa: E731
        stop_dummy = zf(B, V)
        sigs: list = []
        # prefill lattice: padded length buckets × the page-table widths
        # those lengths need at admission (t0=0).  The prefixed variant
        # only runs for chunked prefill / prefix-cache hits — lower it
        # only when the deployment can reach it — and there the pad
        # bucket follows the CHUNK remainder n while the table width
        # follows t0+n, so small tpads legitimately pair with EVERY
        # width ≥ their own need (a 4k prompt ingesting 512-token
        # chunks walks tpad=512 against pbucket 64→128→256): the
        # prefixed lattice is the full (tpad, width ≥ need) grid, not
        # the diagonal.
        pb_all = self._pow2_lattice(1, self.max_pages_per_slot)
        for tpad in self._pow2_lattice(8, self.max_len):
            need = -(-tpad // self.page_size)
            pbucket = min(
                next((w for w in pb_all if w >= need), pb_all[-1]),
                self.max_pages_per_slot,
            )
            args = (
                self.params, z32(1, tpad), self.kv, z32(pbucket),
                np.int32(tpad), self.lora_bank, np.int32(0),
            )
            sigs.append((f"prefill:t{tpad}:p{pbucket}", self._prefill, args))
            if self.prefill_chunk > 0 or self.prefix_cache:
                for pw in pb_all:
                    if pw < need:
                        continue
                    pargs = (
                        self.params, z32(1, tpad), self.kv, z32(pw),
                        np.int32(0), np.int32(tpad), self.lora_bank,
                        np.int32(0),
                    )
                    sigs.append((
                        f"prefill_prefixed:t{tpad}:p{pw}",
                        self._prefill_prefixed, pargs,
                    ))
        # decode chunks: one signature per page-table width bucket ×
        # variant; every other array is (B,)-fixed
        for pbucket in self._pow2_lattice(1, self.max_pages_per_slot):
            for vt in vtuples:
                use_filters, want_lp, use_pen, use_seed, use_min = vt
                args = (
                    self.params, self.kv, z32(B, pbucket), z32(B), z32(B),
                    zb(B), z32(B, self.max_len), z32(B), zf(B), z32(B),
                    np.ones(B, np.float32), key, self.lora_bank, z32(B),
                    self._bias_dev,
                    zf(B) if use_pen else None,
                    zf(B) if use_pen else None,
                    z32(B, V) if use_pen else None,
                    self._seed_keys if use_seed else None,
                    zb(B) if use_seed else None,
                    stop_dummy if use_min else None,
                    z32(B) if use_min else None,
                )
                sigs.append((
                    f"serve_chunk:{''.join(str(int(x)) for x in vt)}"
                    f":p{pbucket}",
                    self._chunks[vt], args,
                ))
                if self.spec_k > 0:
                    W = self.spec_k + 1
                    vargs = (
                        self.params, self.kv, z32(B, pbucket), z32(B, W),
                        z32(B), zb(B), zf(B), z32(B),
                        np.ones(B, np.float32), key, self.lora_bank,
                        z32(B), self._bias_dev,
                        zf(B) if use_pen else None,
                        zf(B) if use_pen else None,
                        z32(B, V) if use_pen else None,
                        z32(B) if (use_pen or use_min) else None,
                        self._seed_keys if use_seed else None,
                        zb(B) if use_seed else None,
                        stop_dummy if use_min else None,
                        z32(B) if use_min else None,
                    )
                    sigs.append((
                        f"verify_chunk:"
                        f"{''.join(str(int(x)) for x in vt)}:p{pbucket}",
                        self._verify_chunks[vt], vargs,
                    ))
        return sigs

    # -- engine internals ----------------------------------------------------

    def _stops(self, i: int, req: Request, tok: int) -> bool:
        """Stop-token check honoring min_tokens (emitted counter already
        includes ``tok`` at every call site)."""
        return tok in req.stop_tokens and self.emitted[i] >= req.min_tokens

    def _emit(self, req: Request, tok: int, lp=None, top=None) -> None:
        """Deliver one streamed token.  A raising user callback must never
        unwind into the engine loop — the donated KV pool has already
        advanced when emissions run, so an escaping exception would leave
        lengths/next_token stale and corrupt every other in-flight slot.
        Policy: log, disable THAT request's streaming, keep generating.

        ``lp``/``top``: the token's logprob and [(id, logprob), ...]
        alternatives — appended in lockstep with ``output`` so the three
        lists always align."""
        self.tokens_emitted += 1
        req.output.append(tok)
        if req.logprobs > 0:
            req.token_logprobs.append(None if lp is None else float(lp))
            req.top_logprobs.append([] if top is None else top)
        if req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception:
                log.warning(
                    "on_token callback raised; streaming disabled for this "
                    "request", exc_info=True,
                )
                req.on_token = None

    def _admit(self) -> None:
        # cross-thread engine tasks first (KV export/import, session
        # migration): the engine thread is the sole owner of slot/page/
        # pool state, so the HTTP layer's disagg verbs run here
        self._run_tasks()
        # anti-thrash: while a stalled slot outranks the queue's best,
        # admitting lower classes would immediately re-trigger the spill
        # they were evicted by — leave them queued until pressure clears
        stalled_pris = [
            int(self.priorities[i])
            for i in range(self.max_batch)
            if self.slots[i] is not None and self.stalled[i]
        ]
        stall_floor = max(stalled_pris) if stalled_pris else None
        for i in range(self.max_batch):
            if self.slots[i] is not None:
                continue
            # pop-or-putback under the cap lock: without it, a submit
            # between this pop and the stall-floor put-back would see a
            # transiently short queue and overshoot max_queue by one
            with self._cap_lock:
                try:
                    neg, seq, req = self.queue.get_nowait()
                except queue.Empty:
                    return
                if stall_floor is not None and req.priority < stall_floor:
                    self.queue.put((neg, seq, req))  # keeps FIFO position
                    return  # everything below is lower-priority still
            if req.cancelled:  # cancelled while still queued
                req.done.set()
                continue
            # fed prompt: the original prompt, plus — for a spilled-and-
            # resumed request — everything already generated, so the
            # resume re-prefills exactly the sequence it was at.  Global
            # token positions are unchanged, which keeps seeded sampling
            # (position-keyed) bit-identical across a spill.
            fed = list(req.prompt) + list(req.output)
            if req.trace_ctx is not None:
                TRACER.point(
                    "engine.admitted", parent=req.trace_ctx, slot=i,
                    prefill_tokens=len(fed),
                )
            if req.t_admit == 0.0:
                req.t_admit = time.monotonic()
            self.slots[i] = req
            # gap metric: only back-to-back decode chunks count.  Most
            # admissions reset via _prefill_dispatch, but a plen-1 or
            # fully-prefix-matched prompt skips the prefill dispatch —
            # without this reset, engine idle time before the admission
            # (minutes on a quiet pod) would land in host_gap_ns
            self._last_drain_done = None
            self.prompts[i, : len(fed)] = fed
            self._prompts_version += 1  # device prompt mirror refresh
            self.prompt_lens[i] = len(fed)
            self.next_token[i] = fed[0]
            self._carry_dirty.add(i)  # host rewrote this slot's feed row
            self.gen_before[i] = len(req.output)
            self.priorities[i] = req.priority
            self.temps[i] = req.temperature
            self.top_ks[i] = req.top_k
            self.top_ps[i] = req.top_p
            self.adapter_ids[i] = self.adapter_index[req.adapter]
            self.freq_pens[i] = req.frequency_penalty
            self.pres_pens[i] = req.presence_penalty
            if req.seed is not None:
                self._seed_keys = self._seed_keys.at[i].set(
                    jax.random.key(np.uint32(req.seed))
                )
                self._seeded[i] = True
            if req.logit_bias or req.allowed_tokens:
                self._bias_dev = self._bias_dev.at[i].set(
                    _bias_row_cached(req, self.cfg.vocab_size)
                )
                self._bias_set[i] = True
            # remaining floor: tokens generated before a spill count
            floor = max(0, req.min_tokens - int(self.gen_before[i]))
            self.min_toks[i] = floor
            if floor > 0 and req.stop_tokens:
                if self._stop_dev is None:
                    self._stop_dev = jnp.zeros(
                        (self.max_batch, self.cfg.vocab_size), jnp.float32
                    )
                self._stop_dev = self._stop_dev.at[i].set(
                    _stop_row_cached(req, self.cfg.vocab_size)
                )
                self._stop_set[i] = True
            self.emitted[i] = int(self.gen_before[i])
            self.stalled[i] = False
            # no page zeroing needed: the position mask only exposes
            # positions <= length, all of which the new tenant rewrites
            matched = self._match_prefix(i, req) if self.prefix_cache else 0
            if self.prefix_cache:
                self.prefix_lookups += 1
                if matched:
                    self.prefix_admission_hits += 1
            self.matched_toks[i] = matched
            self.lengths[i] = matched
            if matched:
                self.next_token[i] = int(self.prompts[i, matched])
            self._try_prefill(i, req)

    def _match_prefix(self, i: int, req: Request) -> int:
        """Attach cached pages matching the prompt's leading full pages
        (capped at plen-1 so at least one prompt token always runs through
        the model to produce the first logits).  Returns tokens matched."""
        ps = self.page_size
        plen = int(self.prompt_lens[i])  # the FED prompt (incl. resumed
        # output for a spilled request — cached pages match by content)
        # K/V content depends on the adapter (wk/wv deltas): pages cached
        # under one adapter must never match a request using another, so
        # the digest chain is seeded with the adapter id (the rolling
        # BLAKE2b chain replaced the seed's O(prompt) nested-tuple hash;
        # same content-addressing, one incremental digest per page)
        key = _prefix_seed(int(self.adapter_ids[i]))
        row = self.prompts[i]
        matched_pages = 0
        for j in range(self.max_pages_per_slot):
            end = (j + 1) * ps
            if end > plen - 1:
                break
            key = _prefix_page_key(key, row[j * ps:end])
            pg = self.prefix_entries.get(key)
            if pg is None:
                break
            self.tables[i, j] = pg
            self.slot_pages[i].append(pg)
            self.page_ref[pg] += 1
            self._touch(pg)
            matched_pages += 1
        self.prefix_hit_tokens += matched_pages * ps
        return matched_pages * ps

    def _touch(self, pg: int) -> None:
        self._lru_clock += 1
        self.page_lru[pg] = self._lru_clock

    def _register_prompt_pages(self, i: int, req: Request) -> None:
        """On release: publish the slot's pages fully covered by the prompt
        into the prefix cache (content-addressed by the token hash chain).
        Duplicates of already-cached content stay unregistered and are
        freed normally.

        Coverage is capped at the WRITTEN length, not just the prompt
        length: a request cancelled mid-prompt-feed (client timeout or
        disconnect during incremental feeding) releases pages whose K/V
        rows were never produced — publishing those under the prompt's
        content hash would hand garbage pages to every later request
        sharing the prefix."""
        ps = self.page_size
        plen = min(len(req.prompt), int(self.lengths[i]))
        key = _prefix_seed(int(self.adapter_ids[i]))  # as in _match_prefix
        # digest over the SAME int32 byte layout _match_prefix hashes (the
        # prompts buffer is int32), so registration and match keys agree
        ptoks = np.asarray(req.prompt[:plen], np.int32)
        for j, pg in enumerate(self.slot_pages[i]):
            end = (j + 1) * ps
            if end > plen:
                break
            key = _prefix_page_key(key, ptoks[j * ps:end])
            existing = self.prefix_entries.get(key)
            if existing is None:
                self.prefix_entries[key] = pg
                self.page_key[pg] = key
                self._touch(pg)
            elif existing == pg:
                self._touch(pg)  # shared page we matched at admission

    def _prefill_dispatch(self, i: int, req: Request, t0: int, n: int):
        """One prefill pass over prompt[t0:t0+n] (pages must already cover
        position t0+n).  Shared by the emitting final pass and the
        logit-discarding chunked-ingest passes — one copy of the
        pad/bucket/dispatch recipe.  Returns the last-real-position
        logits (V,).

        Pad length buckets to a power of two so the prefill jit compiles
        per bucket.  The table width buckets too: the prefixed path
        gathers every page it is handed, so its attention cost must
        follow the LIVE prompt length, not max_len (same trick as
        step()'s table view).  Padding positions index past the slice
        and clamp — then route to scratch."""
        tpad = 8
        while tpad < n:
            tpad *= 2
        tpad = min(tpad, self.max_len)
        need_pages = -(-(t0 + n) // self.page_size)
        pbucket = 1
        while pbucket < need_pages:
            pbucket *= 2
        pbucket = min(pbucket, self.max_pages_per_slot)
        row = jnp.asarray(self.tables[i, :pbucket])
        toks = np.zeros((1, tpad), np.int32)
        toks[0, :n] = self.prompts[i, t0:t0 + n]  # the FED prompt
        aid = jnp.asarray(self.adapter_ids[i], jnp.int32)
        if t0 == 0:
            logits, self.kv = self._prefill(
                self.params, jnp.asarray(toks), self.kv, row,
                jnp.asarray(n, jnp.int32), self.lora_bank, aid,
            )
        else:
            logits, self.kv = self._prefill_prefixed(
                self.params, jnp.asarray(toks), self.kv, row,
                jnp.asarray(t0, jnp.int32), jnp.asarray(n, jnp.int32),
                self.lora_bank, aid,
            )
        self.prefills_run += 1
        self._last_drain_done = None  # gap metric: decode chunks only
        return logits

    def _try_prefill(self, i: int, req: Request) -> None:
        """Ingest the (rest of the) prompt in one pass when pages are
        available; otherwise leave the slot in the incremental
        prompt-feeding path (the fused chunks consume the prompt at decode
        speed — slower but always correct).  A prefix-cache hit skips the
        matched tokens entirely: only the remainder runs through the model,
        attending to the shared pages."""
        plen = int(self.prompt_lens[i])  # the FED prompt
        t0 = int(self.lengths[i])  # prefix-cache hit length (0 without)
        rem = plen - t0
        C = self.prefill_chunk
        if C > 0 and rem - 1 > C:
            # chunked: ingest the next C tokens only, no emission — the
            # engine loop interleaves other slots' decode chunks between
            # these passes (_continue_prefills), and pages are claimed
            # incrementally so admission doesn't grab plen pages upfront
            self.prefilling[i] = True
            if not self._ensure_pages(i, t0 + C):
                return  # pool pressure: retried next loop iteration
            self._prefill_dispatch(i, req, t0, C)  # logits discarded
            self.lengths[i] = t0 + C
            self._carry_dirty.add(i)
            return
        if rem < 2 or not self._ensure_pages(i, plen):
            return
        self.prefilling[i] = False  # final (or only) pass emits below
        logits = self._prefill_dispatch(i, req, t0, rem)
        if req.logit_bias or req.allowed_tokens:
            # the SAME row the fused chunks add, applied host-side
            logits = jnp.asarray(
                np.asarray(logits, np.float32)
                + _bias_row_cached(req, self.cfg.vocab_size)
            )
        if self.min_toks[i] > 0 and req.stop_tokens:
            # this emission's index is gen_before < the remaining floor,
            # so the suppression applies (same row the fused chunks gate
            # per position; min_toks holds the REMAINING floor, already 0
            # for a resumed request that passed it before its spill)
            logits = jnp.asarray(
                np.asarray(logits, np.float32)
                + _stop_row_cached(req, self.cfg.vocab_size)
            )
        # penalties: counts cover GENERATED tokens only — none exist at a
        # fresh admission, but a spilled-and-resumed request re-enters
        # with its prior output, which the next distribution must count
        if (
            (req.frequency_penalty or req.presence_penalty)
            and self.gen_before[i] > 0
        ):
            cnt = np.zeros(self.cfg.vocab_size, np.float32)
            np.add.at(cnt, np.asarray(req.output, np.int64), 1.0)
            logits = jnp.asarray(
                np.asarray(logits, np.float32)
                - req.frequency_penalty * cnt
                - req.presence_penalty * (cnt > 0)
            )
        if req.temperature > 0:
            # same key stream + recipe as the fused chunks' device sampling
            from .sampling import sample_static

            if req.seed is not None:
                # position-keyed, like the chunks: the distribution sits
                # at the prompt's last position
                sub = jax.random.fold_in(
                    jax.random.key(np.uint32(req.seed)), plen - 1
                )
                self._key, _ = jax.random.split(self._key)
            else:
                self._key, sub = jax.random.split(self._key)
            tok = int(
                sample_static(
                    jnp.reshape(logits, (1, -1)), sub,
                    temperature=req.temperature,
                    top_k=req.top_k, top_p=req.top_p,
                )[0]
            )
        else:
            tok = int(jnp.argmax(logits))
        if req.logprobs > 0:
            # first emission comes from the prefill's (V,) logits row —
            # host-side numpy log-softmax, no extra device dispatch
            lg = np.asarray(logits, np.float32)
            lse = float(np.logaddexp.reduce(lg))
            n = req.logprobs
            top = np.argpartition(-lg, n - 1)[:n]
            top = top[np.argsort(-lg[top])]
            self._emit(
                req, tok, lg[tok] - lse,
                [(int(t), float(lg[t] - lse)) for t in top],
            )
        else:
            self._emit(req, tok)
        self.emitted[i] = int(self.gen_before[i]) + 1
        self.lengths[i] = plen
        self.next_token[i] = tok
        self._carry_dirty.add(i)
        if (
            self._stops(i, req, tok)
            or self.emitted[i] >= req.max_new_tokens
            or req.cancelled
        ):
            req.done.set()
            self._release_slot(i)

    def _alloc_page(self) -> Optional[int]:
        if self.free_pages:
            return self.free_pages.pop()
        if self.prefix_cache:
            # evict the least-recently-used cached page nobody references
            candidates = [
                pg for pg in self.page_key if self.page_ref[pg] == 0
            ]
            if candidates:
                pg = min(candidates, key=lambda p: self.page_lru.get(p, 0))
                key = self.page_key.pop(pg)
                self.prefix_entries.pop(key, None)
                self.page_lru.pop(pg, None)
                return pg
        return None

    def _ensure_pages(self, i: int, upto: int) -> bool:
        """Grow slot i's page list to cover token positions < upto.
        Returns False (and leaves partial growth in place) on pool
        exhaustion — the slot stalls for this chunk."""
        upto = min(upto, self.max_len)
        need = -(-upto // self.page_size)
        while len(self.slot_pages[i]) < need:
            pg = self._alloc_page()
            if pg is None:
                return False
            self.tables[i, len(self.slot_pages[i])] = pg
            self.slot_pages[i].append(pg)
            self.page_ref[pg] += 1
        return True

    def _force_drop_slot(self, i: int) -> None:
        """Last-resort slot teardown for the serving loop's failure path:
        free the slot's pages WITHOUT prefix-cache registration and never
        raise — if ``_release_slot`` itself failed, a bare ``slots[i] =
        None`` would leave the dead tenant's page list attached, and the
        next request admitted into the slot would write K/V over pages
        still referenced (possibly shared via the prefix cache) by other
        live requests."""
        try:
            for pg in reversed(self.slot_pages[i]):
                self.page_ref[pg] -= 1
                if self.page_ref[pg] <= 0 and pg not in self.page_key:
                    self.free_pages.append(pg)
        except Exception:
            log.exception("page cleanup for slot %d failed; pages leak", i)
        self.slot_pages[i] = []
        self.tables[i, :] = SCRATCH_PAGE
        self.slots[i] = None
        self.stalled[i] = False
        self.prefilling[i] = False
        self.gen_before[i] = 0
        self.priorities[i] = 0
        self.matched_toks[i] = 0
        self._seeded[i] = False
        self._clear_bias(i)
        self._clear_stop(i)
        if self.draft is not None:
            self.draft_len[i] = 0

    def _release_slot(self, i: int) -> None:
        req = self.slots[i]
        if self.prefix_cache and req is not None and not req.error:
            self._register_prompt_pages(i, req)
        for pg in reversed(self.slot_pages[i]):
            self.page_ref[pg] -= 1
            if self.page_ref[pg] <= 0 and pg not in self.page_key:
                self.free_pages.append(pg)
        self.slot_pages[i] = []
        self.tables[i, :] = SCRATCH_PAGE
        self.slots[i] = None
        self.stalled[i] = False
        self.prefilling[i] = False
        self.gen_before[i] = 0
        self.priorities[i] = 0
        self.matched_toks[i] = 0
        self._seeded[i] = False
        self._clear_bias(i)
        self._clear_stop(i)
        if self.draft is not None:
            self.draft_len[i] = 0  # rows rewrite lazily; no device work

    def evict_slot(self, i: int, requeue: bool = True) -> None:
        """Evict a live slot for a migration/resize pause (defrag hooks,
        fleet/resize.py): free its pages and requeue the request for an
        exact resume.  Unlike the in-step spill (``_maybe_spill``, which
        runs between a chunk's dispatch and drain), an EXTERNAL eviction
        can race an overlapped in-flight chunk — so this slot's stake in
        the pending chunk is discarded FIRST.  Without that, a resumed
        request re-admitted into the same slot index would receive the
        stale chunk's tokens on top of its re-prefilled stream (the
        (slot, request) identity pin cannot tell the two tenancies
        apart).  The discarded chunk is the contract's bounded loss: at
        most one per evicted slot, counted in ``chunks_discarded``."""
        req = self.slots[i]
        if req is None:
            return
        if self._pending is not None:
            kept = [(s, r) for (s, r) in self._pending.pairs if s != i]
            if len(kept) != len(self._pending.pairs):
                self.chunks_discarded += 1
                self._pending.pairs = kept
        self._release_slot(i)
        if requeue and not req.done.is_set():
            self._enqueue(req)

    # -- disaggregated serving data plane (utils/kvwire, fleet/) -------------
    #
    # Prefill/decode split, replica-to-replica KV-page shipping and live
    # session migration all reduce to four engine-thread primitives:
    # export cached prefix pages as a wire bundle, import a bundle's
    # pages into the local pool + prefix cache, detach a live slot into
    # a session bundle (evict→export), and resume a shipped session
    # (enqueue→prefix-match the imported pages → token-identical
    # continuation, the same exactness contract as the local spill).
    # HTTP handlers reach them through run_task — the engine thread is
    # the sole owner of slot/page/pool state.

    def run_task(self, fn, timeout: float = 30.0,
                 abandon_on_timeout: bool = True):
        """Execute ``fn()`` on the engine thread (drained at the top of
        every ``_admit``) and return its result, re-raising whatever it
        raised.  Callers must be driving the engine from another thread
        (the EngineLoop case); with no loop running this times out.

        A timeout ABANDONS the thunk: the engine thread skips it if it
        hasn't started yet, so a timed-out caller can safely treat the
        task as never-ran (the migrate-in path relies on this — a late
        import would resurrect the session on a second replica).  A
        thunk already mid-execution when the caller gives up cannot be
        recalled; that window is one lock-free flag check wide.
        ``abandon_on_timeout=False`` keeps the thunk runnable after a
        timeout — for callers whose thunk MUST eventually happen (the
        migrate-out local re-enqueue: losing it loses the session)."""
        done = threading.Event()
        box: dict = {"abandoned": False}

        def thunk():
            if box["abandoned"]:  # caller timed out before we started
                done.set()
                return
            try:
                box["result"] = fn()
            except BaseException as e:  # re-raised on the caller thread
                box["error"] = e
            finally:
                done.set()

        self._tasks.put(thunk)
        self._work.set()  # wake a parked EngineLoop
        if not done.wait(timeout):
            box["abandoned"] = abandon_on_timeout
            raise TimeoutError("engine task timed out (no engine loop?)")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _run_tasks(self) -> None:
        while True:
            try:
                thunk = self._tasks.get_nowait()
            except queue.Empty:
                return
            thunk()  # thunk() never raises (errors park in its box)

    def _chain_seed(self, adapter: str) -> bytes:
        if adapter not in self.adapter_index:
            raise ValueError(
                f"unknown adapter {adapter!r} "
                f"(registered: {sorted(self.adapter_index)})"
            )
        return _prefix_seed(int(self.adapter_index[adapter]))

    def _pool_keys(self) -> tuple:
        return ("k", "v", "ks", "vs") if self.kv_int8 else ("k", "v")

    def _wire_header(self, adapter: str, kind: str) -> dict:
        """Geometry fields the importer verifies before any page lands —
        two engines can only exchange pages when their pools are laid
        out identically (fleet replicas of one deployment are)."""
        return {
            "kind": kind,
            "page_size": self.page_size,
            "n_layers": self.cfg.n_layers,
            "kv_heads": self.cfg.kv_heads,
            "head_dim": self.cfg.head_dim,
            "dtype": str(np.dtype(self.kv["k"].dtype)),
            "kv_int8": self.kv_int8,
            "adapter": adapter,
        }

    def cached_prefix_pages(self, tokens, adapter: str = "") -> list[int]:
        """Page ids for the longest locally-cached run of ``tokens``'
        leading full pages, capped at len-1 (mirroring ``_match_prefix``:
        a page the destination's admission can never attach is not worth
        shipping).  Read-only — no refs taken, no LRU touch."""
        ps = self.page_size
        toks = np.asarray(list(tokens), np.int32)
        key = self._chain_seed(adapter)
        out: list[int] = []
        for j in range((max(0, len(toks) - 1)) // ps):
            key = _prefix_page_key(key, toks[j * ps:(j + 1) * ps])
            pg = self.prefix_entries.get(key)
            if pg is None:
                break
            out.append(pg)
        return out

    def _page_payloads(self, pgs: list[int]) -> list[bytes]:
        """Serialize pool pages ``pgs`` → raw per-page payload bytes
        (concatenated pool keys, layer-major).  ONE device→host gather
        per pool key, not one per page; reading the current ``self.kv``
        blocks until any in-flight chunk lands, and the chunk only
        scatters at positions past what we export, so the bytes are the
        confirmed values."""
        idx = np.asarray(pgs, np.int32)
        per_key = {
            k: np.ascontiguousarray(np.asarray(self.kv[k][:, idx]))
            for k in self._pool_keys()
        }
        return [
            b"".join(
                np.ascontiguousarray(per_key[k][:, j]).tobytes()
                for k in self._pool_keys()
            )
            for j in range(len(pgs))
        ]

    def export_prefix_pages(
        self, tokens, adapter: str = "", max_pages: int = 0
    ) -> Optional[bytes]:
        """Wire bundle of the cached pages covering ``tokens``' leading
        full pages, or None when nothing is cached.  The receiving
        replica re-derives registration keys from the shipped token
        content with ITS adapter seed, so bank-index skew between
        replicas cannot alias pages."""
        toks = [int(t) for t in tokens]
        pgs = self.cached_prefix_pages(toks, adapter)
        if max_pages > 0:
            pgs = pgs[:max_pages]
        if not pgs:
            return None
        ps = self.page_size
        payloads = self._page_payloads(pgs)
        pages = [
            (toks[j * ps:(j + 1) * ps], payloads[j])
            for j in range(len(pgs))
        ]
        for pg in pgs:
            self._touch(pg)  # shipped = used: keep under LRU pressure
        self.kv_exports += 1
        self.kv_pages_exported += len(pgs)
        return kvwire.encode_bundle(
            self._wire_header(adapter, "prefix"), pages,
            self._chain_seed(adapter),
        )

    def import_pages(self, header: dict, pages: list) -> dict:
        """Land a decoded bundle's pages in the local pool and register
        them in the prefix cache (content-addressed under THIS engine's
        chain).  Geometry mismatch raises before anything lands; pool
        pressure stops the import cleanly (later pages are useless
        without their predecessors — ``_match_prefix`` walks in order).
        Returns {"imported", "already", "tokens", "stopped"}."""
        if not self.prefix_cache:
            raise ValueError("prefix cache disabled (--prefix-cache)")
        mine = self._wire_header(str(header.get("adapter", "")), "")
        for f in ("page_size", "n_layers", "kv_heads", "head_dim",
                  "dtype", "kv_int8"):
            if header.get(f) != mine[f]:
                raise ValueError(
                    f"incompatible KV geometry: {f} "
                    f"{header.get(f)!r} != {mine[f]!r}"
                )
        adapter = str(header.get("adapter", ""))
        key = self._chain_seed(adapter)  # raises on unknown adapter
        ps = self.page_size
        kdt = np.dtype(self.kv["k"].dtype)
        L, hkv, hd = self.cfg.n_layers, self.cfg.kv_heads, self.cfg.head_dim
        sizes = {
            k: (L * ps * hkv * (hd if k in ("k", "v") else 1))
            * (kdt.itemsize if k in ("k", "v") else 4)
            for k in self._pool_keys()
        }
        shapes = {
            k: (L, ps, hkv, hd) if k in ("k", "v") else (L, ps, hkv)
            for k in self._pool_keys()
        }
        # validate EVERY page's frame against the geometry BEFORE any
        # allocation or registration: a raise below this loop would
        # otherwise leave earlier pages registered in prefix_entries
        # with never-written pool content (the garbage-page hazard
        # _register_prompt_pages documents) — the method's contract is
        # that a rejection lands NOTHING
        payload_size = sum(sizes.values())
        for toks, payload in pages:
            if len(toks) != ps:
                raise ValueError("partial page in bundle")
            if len(payload) != payload_size:
                raise ValueError("payload size does not match geometry")
        staged: list[tuple[int, dict]] = []
        pinned: list[int] = []  # ref-bumped for the import's duration
        imported = already = covered = 0
        stopped = None
        try:
            for toks, payload in pages:
                key = _prefix_page_key(key, np.asarray(toks, np.int32))
                existing = self.prefix_entries.get(key)
                if existing is not None:
                    already += 1
                    covered += ps
                    self._touch(existing)
                    # pin: a later page's allocation must not LRU-evict
                    # an earlier link of the SAME chain (match walks in
                    # order)
                    self.page_ref[existing] += 1
                    pinned.append(existing)
                    continue
                pg = self._alloc_page()
                if pg is None:
                    stopped = "page pool exhausted"
                    break
                parsed, off = {}, 0
                for k in self._pool_keys():
                    dt = kdt if k in ("k", "v") else np.dtype(np.float32)
                    parsed[k] = np.frombuffer(
                        payload[off:off + sizes[k]], dt
                    ).reshape(shapes[k])
                    off += sizes[k]
                # pinned while the import runs so a later page's
                # allocation cannot cannibalize this one; released to
                # ref 0 (cached, LRU-evictable) below
                self.page_ref[pg] = 1
                pinned.append(pg)
                self.prefix_entries[key] = pg
                self.page_key[pg] = key
                self._touch(pg)
                staged.append((pg, parsed))
                imported += 1
                covered += ps
        finally:
            for pg in pinned:
                self.page_ref[pg] -= 1
        if staged:
            idx = jnp.asarray(
                np.asarray([pg for pg, _ in staged], np.int32)
            )
            for k in self._pool_keys():
                stack = np.stack([p[k] for _, p in staged], axis=1)
                self.kv[k] = self.kv[k].at[:, idx].set(jnp.asarray(stack))
            self.kv_imports += 1
            self.kv_pages_imported += imported
        return {
            "imported": imported,
            "already": already,
            "tokens": covered,
            "stopped": stopped,
        }

    def migrate_out_bundle(self, slot: int) -> Optional[bytes]:
        """Detach live slot ``slot`` into a ``kind="session"`` bundle:
        request state + the K/V pages covering its confirmed sequence,
        then evict WITHOUT a local requeue (the caller owns the request
        from here — it re-enqueues locally only if the destination
        refuses).  The eviction discards at most the one in-flight
        overlapped chunk (``evict_slot``'s contract); everything the
        bundle carries is confirmed state, so the destination resumes
        token-identically."""
        req = self.slots[slot]
        if req is None or req.done.is_set():
            return None
        seq = list(req.prompt) + list(req.output)
        ps = self.page_size
        # confirmed written positions only: lengths may be eagerly
        # advanced for an undrained chunk, but positions < len(seq)-1
        # are always written and match seq's content
        end = min(int(self.lengths[slot]), len(seq) - 1)
        n = max(0, min(end // ps, len(self.slot_pages[slot])))
        pages = []
        if n > 0:
            payloads = self._page_payloads(self.slot_pages[slot][:n])
            pages = [
                (seq[j * ps:(j + 1) * ps], payloads[j]) for j in range(n)
            ]
        header = self._wire_header(req.adapter, "session")
        header["request"] = {
            "prompt": [int(t) for t in req.prompt],
            "output": [int(t) for t in req.output],
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "top_p": float(req.top_p),
            "adapter": req.adapter,
            "stop_tokens": [int(t) for t in req.stop_tokens],
            "logprobs": int(req.logprobs),
            "token_logprobs": list(req.token_logprobs),
            "top_logprobs": [
                [[int(t), float(lp)] for t, lp in top]
                for top in req.top_logprobs
            ],
            "logit_bias": {
                str(k): float(v) for k, v in req.logit_bias.items()
            },
            "frequency_penalty": float(req.frequency_penalty),
            "presence_penalty": float(req.presence_penalty),
            "min_tokens": int(req.min_tokens),
            "priority": int(req.priority),
            "seed": req.seed,
            "allowed_tokens": [int(t) for t in req.allowed_tokens],
            "pool_spills": int(req.pool_spills),
        }
        data = kvwire.encode_bundle(
            header, pages, self._chain_seed(req.adapter)
        )
        self.sessions_migrated_out += 1
        self.kv_pages_exported += n
        self.evict_slot(slot, requeue=False)
        return data

    def resume_session(self, state: dict, on_token=None) -> Request:
        """Re-create a migrated session's Request and enqueue it for the
        engine's spill-resume machinery (``_admit`` feeds prompt+output
        and prefix-matches the imported pages, so the re-prefill covers
        only the unshipped tail).  Bypasses the admission cap — a
        migrated session is in-flight work, not new traffic (the spill
        requeue's stance).  Raises on invalid state; returns the live
        Request (done/output/error owned by this engine from here)."""
        if self.draining:
            raise RuntimeError(DRAINING_ERROR)
        prompt = [int(t) for t in (state.get("prompt") or [])]
        if not prompt:
            raise ValueError("session has an empty prompt")
        req = Request(
            prompt=prompt,
            max_new_tokens=int(state.get("max_new_tokens", 16)),
            temperature=float(state.get("temperature", 0.0)),
            top_k=int(state.get("top_k", 0)),
            top_p=float(state.get("top_p", 1.0)),
            adapter=str(state.get("adapter", "")),
            stop_tokens=tuple(
                int(t) for t in (state.get("stop_tokens") or ())
            ),
            logprobs=int(state.get("logprobs", 0)),
            logit_bias={
                int(k): float(v)
                for k, v in (state.get("logit_bias") or {}).items()
            },
            frequency_penalty=float(state.get("frequency_penalty", 0.0)),
            presence_penalty=float(state.get("presence_penalty", 0.0)),
            min_tokens=int(state.get("min_tokens", 0)),
            priority=int(state.get("priority", 0)),
            seed=state.get("seed"),
            allowed_tokens=tuple(
                int(t) for t in (state.get("allowed_tokens") or ())
            ),
        )
        err = self._invalid_reason(req)  # submit()'s exact rule set
        if err is not None:
            raise ValueError(err)
        req.output = [int(t) for t in (state.get("output") or [])]
        req.token_logprobs = [
            None if lp is None else float(lp)
            for lp in (state.get("token_logprobs") or [])
        ]
        req.top_logprobs = [
            [(int(t), float(lp)) for t, lp in top]
            for top in (state.get("top_logprobs") or [])
        ]
        req.pool_spills = int(state.get("pool_spills", 0))
        req.on_token = on_token
        self.sessions_migrated_in += 1
        if len(req.output) >= req.max_new_tokens:
            req.done.set()  # arrived complete: nothing left to generate
            return req
        self._enqueue(req)
        return req

    def _prepare_step(self, lookahead: int):
        """Host-side slot scan shared by BOTH step flavors (sequential
        chunk and speculative verify): release cancelled slots (before the
        pages check, so a cancelled stalled slot frees pages that may
        unstall others), grow each live slot's pages to cover
        ``lookahead`` more positions, raise when every live slot is
        stalled, and build the scratch-masked power-of-two table view
        (attention cost follows the LIVE context length, and inactive
        rows must point at scratch — a stalled slot whose write position
        lies beyond the bucket would otherwise clamp into its own last
        visible page and corrupt confirmed K/V).

        Returns (active, view) or None when no slot is runnable.

        Priority (VERDICT r4 #8): when a stalled slot outranks a live
        lower-priority slot, the low one is SPILLED — pages freed, request
        requeued for an exact resume — instead of the high one waiting
        out a blanket stall.  One spill per rescan, re-checked until no
        eligible victim remains (bounded by max_batch)."""
        B = self.max_batch
        while True:
            active = np.zeros(B, bool)
            for i, req in enumerate(self.slots):
                if req is None:
                    continue
                if req.cancelled:
                    req.done.set()
                    self._release_slot(i)
                    continue
                if self.prefilling[i]:
                    # mid-chunked-prefill: fed by _continue_prefills (it
                    # retries regardless of the stalled flag; the flag
                    # only feeds the exhaustion check and spill
                    # accounting).  A pool-pressure stall it recorded may
                    # be stale after a spill freed pages — clear it iff
                    # the FULL next-pass target is grantable (the same
                    # t0+C / plen branch _try_prefill takes; a partial
                    # probe would be satisfied by leftover partial growth
                    # and mask a real stall), and never by grabbing pages
                    # a higher-priority stalled slot is waiting for.
                    if self.stalled[i]:
                        hp = max(
                            (
                                int(self.priorities[j])
                                for j, r in enumerate(self.slots)
                                if r is not None and self.stalled[j]
                                and j != i
                            ),
                            default=None,
                        )
                        if hp is not None and hp > int(self.priorities[i]):
                            continue  # yield the freed pages upward
                        t0 = int(self.lengths[i])
                        plen = int(self.prompt_lens[i])
                        C = self.prefill_chunk
                        target = (
                            t0 + C if C > 0 and (plen - t0) - 1 > C
                            else plen
                        )
                        if self._ensure_pages(i, target):
                            self.stalled[i] = False
                    continue
                if self._ensure_pages(i, int(self.lengths[i]) + lookahead):
                    active[i] = True
                    self.stalled[i] = False
                else:
                    self.stalled[i] = True
            if self.stalled.any() and self._maybe_spill():
                continue  # freed a lower-priority slot's pages; rescan
            if not active.any():
                if self.stalled.any():
                    # genuine page pressure: SOME slot (decode or prefill)
                    # could not get pages and nothing is runnable — surface
                    # the overload so the serving loop can preempt a victim.
                    # Prefilling slots that are progressing don't stall, so a
                    # lone long admission never trips this.
                    raise RuntimeError(
                        f"page pool exhausted: {sum(self.stalled)} slots "
                        f"stalled, 0 runnable (pool {self.n_pages - 1} pages)"
                    )
                return None
            break
        need = max(len(self.slot_pages[i]) for i in range(B) if active[i])
        bucket = 1
        while bucket < need:
            bucket *= 2
        bucket = min(bucket, self.max_pages_per_slot)
        view = self.tables[:, :bucket].copy()
        view[~active] = SCRATCH_PAGE
        return active, view

    def _maybe_spill(self) -> bool:
        """Spill ONE lower-priority slot to unblock a stalled higher-
        priority one: free its pages and requeue its request with an
        exact-resume continuation (the fed prompt on readmission is
        prompt + output so far — greedy and seeded streams are
        bit-identical across the spill).  Victim = the lowest-priority
        slot strictly below the neediest stalled slot's class; ties go to
        the slot holding the most pages (maximal relief).  Returns True
        if a slot was spilled."""
        stalled_pri = [
            int(self.priorities[i])
            for i in range(self.max_batch)
            if self.stalled[i] and self.slots[i] is not None
        ]
        if not stalled_pri:
            return False
        need = max(stalled_pri)
        victims = [
            i for i, req in enumerate(self.slots)
            if req is not None and int(self.priorities[i]) < need
        ]
        # a STALLED lower-priority slot is still a victim: when both
        # classes are page-starved, the lower one yields (the strict <
        # comparison already keeps the needer from victimizing itself)
        if not victims:
            return False
        v = min(
            victims,
            key=lambda i: (int(self.priorities[i]), -len(self.slot_pages[i])),
        )
        req = self.slots[v]
        log.info(
            "page pressure: spilling priority-%d slot %d (%d pages, %d "
            "tokens generated) for a priority-%d request",
            int(self.priorities[v]), v, len(self.slot_pages[v]),
            len(req.output), need,
        )
        self.spills += 1
        # _release_slot (not teardown): prefix-cache registration keeps
        # the spilled prompt's pages warm, so the resume's re-prefill is
        # mostly cache hits when the pages survive the pressure window
        self._release_slot(v)
        self._enqueue(req)
        return True

    def _filters_requested(self, active) -> bool:
        return bool(
            (self.top_ks[active] > 0).any()
            or (self.top_ps[active] < 1.0).any()
        )

    def _pens_requested(self, active) -> bool:
        return bool(
            (self.freq_pens[active] != 0).any()
            or (self.pres_pens[active] != 0).any()
        )

    def _host_counts(self) -> np.ndarray:
        """(B, V) counts of every GENERATED token at positions <
        lengths[i] — the authoritative penalty state, rebuilt per
        dispatch from host output lists so no device/host sync
        bookkeeping can drift.  Cost is O(tokens generated) per slot
        (bounded by max_new_tokens, never the full context) plus the
        (B, V) buffer — paid only by batches with a penalized request."""
        out = np.zeros((self.max_batch, self.cfg.vocab_size), np.int32)
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            n_gen = (
                int(self.lengths[i]) - int(self.prompt_lens[i])
                + int(self.gen_before[i])
            )  # output holds pre-spill tokens too; all of them count
            if n_gen > 0:
                np.add.at(
                    out[i], np.asarray(req.output[:n_gen], np.int64), 1
                )
        return out

    def _seeds_requested(self, active) -> bool:
        return bool(self._seeded[active].any())

    def _logprobs_requested(self, active) -> bool:
        """Pick the logprob-emitting chunk variant only when some active
        request asked — the default path never pays the top-k."""
        return any(
            req is not None and active[i] and req.logprobs > 0
            for i, req in enumerate(self.slots)
        )

    def _clear_bias(self, i: int) -> None:
        """Zero a released slot's bias row — only if it was ever set, so
        bias-free serving never dispatches the update."""
        if self._bias_set[i]:
            self._bias_dev = self._bias_dev.at[i].set(0.0)
            self._bias_set[i] = False

    def _clear_stop(self, i: int) -> None:
        """Zero a released slot's min_tokens suppression row (same
        only-if-set discipline as the bias rows)."""
        self.min_toks[i] = 0
        if self._stop_set[i]:
            self._stop_dev = self._stop_dev.at[i].set(0.0)
            self._stop_set[i] = False

    def _min_requested(self, active) -> bool:
        """Pick the stop-suppressing chunk variant only while some active
        request with stop tokens is still below its min_tokens floor —
        once every floor is passed the engine reverts to the cheaper
        variant on its own."""
        return any(
            req is not None and active[i] and req.stop_tokens
            and self.emitted[i] < req.min_tokens
            for i, req in enumerate(self.slots)
        )

    @staticmethod
    def _top_list(ids_row, lps_row, n) -> list:
        """[(token_id, logprob), ...] for one emission, truncated to the
        request's asked-for width."""
        return [
            (int(t), float(l))
            for t, l in zip(ids_row[:n], lps_row[:n])
        ]

    def _spec_useful(self) -> bool:
        """The verify pass beats sequential chunks only when some slot can
        actually exploit the window: a slot still feeding its prompt
        (W tokens/pass vs 1/step) or a greedy slot generating (drafts).
        A purely sampled generation step takes the sequential chunk."""
        for i, req in enumerate(self.slots):
            if req is None or req.cancelled or self.prefilling[i]:
                # mid-chunked-prefill slots are excluded from the verify
                # batch (_prepare_step), so they can't justify it either
                continue
            if self.lengths[i] < self.prompt_lens[i] - 1:
                return True
            if self.temps[i] == 0:
                return True
        return False

    def step(self) -> None:
        """One engine step: pending chunked-prefill slots each ingest one
        chunk, then a fused decode chunk (or, speculative mode, a fused
        verify pass) runs for everyone else; page allocation, admission,
        and completion happen between steps on the host.

        With ``overlap`` on, the decode-chunk flavor is double-buffered:
        this call dispatches chunk N+1 off device-resident state FIRST
        and only then drains chunk N's tokens — host bookkeeping runs
        while the device computes.  The verify flavor and penalized
        batches drain first (their host state must be current before the
        next dispatch)."""
        self._continue_prefills()
        if self.spec_k > 0 and self._spec_useful():
            self._drain_pending()
            self._step_verify()
            # verify recomputes lengths/next_token host-side (acceptance
            # is data-dependent): the chunk carry is stale — rebuild from
            # host at the next decode dispatch
            self._carry = None
            return
        if self.overlap and not self._overlap_blocked():
            return self._step_chunk_overlapped()
        self._drain_pending()
        return self._step_chunk()

    def _overlap_blocked(self) -> bool:
        """Penalized requests need cross-chunk token counts rebuilt from
        host output lists (``_host_counts``) — with a chunk in flight
        those counts lag, so such batches take the exact sequential
        loop."""
        return any(
            req is not None
            and (req.frequency_penalty or req.presence_penalty)
            for req in self.slots
        )

    def _drain_pending(self) -> None:
        if self._pending is not None:
            pending, self._pending = self._pending, None
            self._drain_chunk(pending)

    def _step_chunk_overlapped(self) -> None:
        """Double-buffered decode step: dispatch the next chunk off the
        in-flight device carry, THEN drain the previous chunk's tokens
        while the new one runs.  A page-pool-exhaustion raise during
        dispatch first drains the pending chunk (its completions may
        free pages) and retries once before surfacing overload."""
        pending, self._pending = self._pending, None
        try:
            new = self._dispatch_chunk(pipelined=pending is not None)
        except RuntimeError:
            if pending is None:
                raise
            self._drain_chunk(pending)
            pending = None
            new = self._dispatch_chunk()  # a second raise is real overload
        if pending is not None:
            self._drain_chunk(pending)
        self._pending = new

    def _continue_prefills(self) -> bool:
        """Advance every mid-chunked-prefill slot by one chunk.  Returns
        True if any slot made progress (used to distinguish a stalled
        pool from a still-prefilling engine)."""
        progressed = False
        for i, req in enumerate(self.slots):
            if req is None or not self.prefilling[i]:
                continue
            if req.cancelled:
                req.done.set()
                self._release_slot(i)
                progressed = True
                continue
            before = int(self.lengths[i])
            self._try_prefill(i, req)
            if not self.prefilling[i] or int(self.lengths[i]) > before:
                progressed = True
                self.stalled[i] = False
            else:
                self.stalled[i] = True  # pool-pressure stall; retried
        return progressed

    def _step_verify(self) -> None:
        """Speculative engine step (VERDICT r2 #2): build each active
        slot's verify window host-side (confirmed token, then prompt
        tokens and/or prompt-lookup drafts), run ONE wide fused pass, and
        accept per-slot the longest fed prefix the model itself would have
        produced — plus the model's own "bonus" token after it.  Greedy
        slots emit 1..W tokens per pass, token-identical to the
        sequential engine; sampled slots emit exactly one."""
        from .speculative import propose_ngram

        W = self.spec_k + 1
        B = self.max_batch
        prepared = self._prepare_step(W)
        if prepared is None:
            return
        self.steps_run += 1  # a real dispatch follows (bench: ms/step)
        active, view = prepared
        draft_rows = (
            self._propose_draft_model(active) if self.draft is not None
            else None
        )
        feed = np.zeros((B, W), np.int32)
        for i, req in enumerate(self.slots):
            if req is None or not active[i]:
                continue
            p = int(self.lengths[i])
            plen = int(self.prompt_lens[i])
            feed[i, 0] = self.next_token[i]
            j = 1
            while j < W and p + j < plen:  # prompt feeding: always valid
                feed[i, j] = self.prompts[i, p + j]
                j += 1
            if j < W and self.temps[i] == 0:
                if draft_rows is not None:
                    # the draft model's continuation starts right after
                    # the last KNOWN position q_end = max(p, plen-1); the
                    # first unfilled window position p+j is q_end+1 by
                    # construction, so drafts index from 0
                    drafts = [int(t) for t in draft_rows[i, : W - j]]
                else:
                    # prompt + output is exactly the tokens at positions
                    # 0..p, so the proposer's continuation lands at the
                    # window's first generated position
                    drafts = propose_ngram(
                        list(req.prompt) + req.output, self.spec_ngram, W - j
                    )
                for d in drafts:
                    feed[i, j] = d
                    j += 1
        self._key, sub = jax.random.split(self._key)
        use_filters = self._filters_requested(active)
        want_lp = self._logprobs_requested(active)
        use_pen = self._pens_requested(active)
        use_seed = self._seeds_requested(active)
        use_min = self._min_requested(active)
        ds = self._ds
        self._last_drain_done = None  # gap metric: decode chunks only
        out, self.kv = self._verify_chunks[
            (use_filters, want_lp, use_pen, use_seed, use_min)
        ](
            self.params,
            self.kv,
            ds.get("view", view),
            jnp.asarray(feed),
            ds.get("lengths", self.lengths),
            ds.get("active", active),
            ds.get("temps", self.temps),
            ds.get("top_ks", self.top_ks),
            ds.get("top_ps", self.top_ps),
            sub,
            self.lora_bank,
            ds.get("adapter_ids", self.adapter_ids),
            self._bias_dev,
            ds.get("freq_pens", self.freq_pens) if use_pen else None,
            ds.get("pres_pens", self.pres_pens) if use_pen else None,
            jnp.asarray(self._host_counts()) if use_pen else None,
            ds.get("prompt_lens", self.prompt_lens)
            if (use_pen or use_min) else None,
            self._seed_keys if use_seed else None,
            ds.get("seeded", self._seeded) if use_seed else None,
            self._stop_dev if use_min else None,
            ds.get("min_toks", self.min_toks) if use_min else None,
        )
        if want_lp:
            picked, chosen_lp, top_ids, top_lps = (
                np.asarray(a) for a in out
            )
        else:
            picked = np.asarray(out)  # (B, W)
        self.spec_passes += 1

        def emit_at(req, i, tok, w):
            """Emit with logprobs from window position w's distribution —
            the one the token at fed position w+1 was drawn from."""
            if want_lp and req.logprobs > 0:
                self._emit(
                    req, tok, chosen_lp[i, w],
                    self._top_list(top_ids[i, w], top_lps[i, w],
                                   req.logprobs),
                )
            else:
                self._emit(req, tok)
        for i, req in enumerate(self.slots):
            if req is None or not active[i]:
                continue
            p = int(self.lengths[i])
            plen = int(self.prompt_lens[i])
            greedy = self.temps[i] == 0
            # longest valid fed prefix: prompt positions are valid by
            # definition; a greedy draft is valid iff it equals the
            # model's own choice at the previous position (a "pad" zero
            # that matches is, by that very test, the correct token)
            A = 1
            while A < W:
                if p + A < plen:
                    A += 1
                elif greedy and feed[i, A] == picked[i, A - 1]:
                    A += 1
                else:
                    break
            stopped = False
            exhausted = False
            for j in range(1, A):
                if p + j < plen:
                    continue  # prompt position: nothing to emit
                tok = int(feed[i, j])
                # accepted ⇒ feed[i, j] == picked[i, j-1], so position
                # j-1's distribution is the one this token came from
                emit_at(req, i, tok, j - 1)
                self.emitted[i] += 1
                self.spec_accepted += 1
                if self._stops(i, req, tok):
                    stopped = True
                    A = j + 1  # confirmed rows end at the stop token
                    break
                if self.emitted[i] >= req.max_new_tokens:
                    exhausted = True
                    A = j + 1
                    break
            if not stopped and not exhausted and p + A >= plen:
                # the model's own token after the last valid fed position
                tok = int(picked[i, A - 1])
                emit_at(req, i, tok, A - 1)
                self.emitted[i] += 1
                if self._stops(i, req, tok):
                    stopped = True
            # rows p..p+A-1 hold confirmed K/V; the bonus token (position
            # p+A) is fed — and its row written — by the next pass
            self.lengths[i] = p + A
            if (
                stopped
                or self.emitted[i] >= req.max_new_tokens
                or req.cancelled
            ):
                req.done.set()
                self._release_slot(i)
            else:
                self.next_token[i] = (
                    self.prompts[i, p + A]
                    if p + A < plen
                    else int(picked[i, A - 1])
                )

    def _propose_draft_model(self, active) -> np.ndarray:
        """Catch the draft cache up on newly-confirmed context, then roll
        the draft model spec_k greedy steps — returns drafts (B, spec_k).

        Context for slot i is positions 0..q_end where q_end =
        max(lengths, plen-1): everything already CONFIRMED (prompt tokens
        are known before the big model ever sees them, so the draft may
        read ahead of the paged cache).  Long prompts pre-ingest in
        ``_draft_chunk``-wide fused passes; the steady-state pass ingests
        at most W new tokens and proposes in the same dispatch."""
        B, W = self.max_batch, self.spec_k + 1
        # a pass with no draft CONSUMER (every greedy row's window still
        # inside its prompt, or only sampled rows) skips ALL draft work —
        # pending context just accumulates and the next consuming pass
        # catches up (chunked below).  Returning zeros is safe: no row
        # reads drafts on such a pass.
        consumer = any(
            req is not None and active[i] and self.temps[i] == 0
            and int(self.lengths[i]) + W > int(self.prompt_lens[i])
            for i, req in enumerate(self.slots)
        )
        if not consumer:
            return np.zeros((B, self.spec_k), np.int32)
        pend: list[list[int]] = [[] for _ in range(B)]
        for i, req in enumerate(self.slots):
            if req is None or not active[i]:
                continue
            p = int(self.lengths[i])
            plen = int(self.prompt_lens[i])
            q_end = max(p, plen - 1)
            toks = []
            for q in range(int(self.draft_len[i]), q_end + 1):
                toks.append(
                    int(self.prompts[i, q]) if q < plen
                    else req.output[int(self.gen_before[i]) + q - plen]
                )
            pend[i] = toks
        CH = self._draft_chunk
        while max((len(t) for t in pend), default=0) > W:
            feed = np.zeros((B, CH), np.int32)
            counts = np.zeros(B, np.int32)
            for i, toks in enumerate(pend):
                if len(toks) <= W:
                    continue  # small backlogs wait for the propose pass:
                    # draining them here would leave counts=0 there and
                    # the rollout would start from pad-token logits
                take = toks[:CH]
                feed[i, : len(take)] = take
                counts[i] = len(take)
                pend[i] = toks[CH:]
            _, self.dkv = self._draft_ingest(
                self.draft_params, self.dkv,
                jnp.asarray(feed), jnp.asarray(self.draft_len),
            )
            self.draft_len += counts
        feed = np.zeros((B, W), np.int32)
        counts = np.zeros(B, np.int32)
        starts = self.draft_len.copy()
        advance = np.zeros(B, np.int32)
        for i, toks in enumerate(pend):
            if not toks and self.draft_len[i] > 0 and active[i]:
                # fully caught up (e.g. everything ingested in a prior
                # pass): re-feed the LAST context token one position back
                # so the rollout starts from real logits, not a pad's.
                # Rewriting that position's K/V is idempotent.  Active
                # rows only — a stalled row with prior ingestion would
                # otherwise re-ingest its last token every verify pass
                # (wasted dispatch width; counts=0 is correct for it).
                q = int(self.draft_len[i]) - 1
                plen = int(self.prompt_lens[i])
                req = self.slots[i]
                tok = (
                    int(self.prompts[i, q]) if q < plen
                    else req.output[int(self.gen_before[i]) + q - plen]
                    if req is not None else 0
                )
                feed[i, 0] = tok
                counts[i] = 1
                starts[i] = q
            else:
                feed[i, : len(toks)] = toks
                counts[i] = len(toks)
                advance[i] = len(toks)
        drafts, self.dkv = self._draft_ip(
            self.draft_params, self.dkv, jnp.asarray(feed),
            jnp.asarray(starts), jnp.asarray(counts),
        )
        self.draft_len += advance
        return np.asarray(drafts)

    def _step_chunk(self) -> None:
        """One fused chunk (``fused_steps`` decode iterations) across all
        slots — dispatch then immediately drain (the exact sequential
        loop; the overlapped pipeline splits the two across steps)."""
        pending = self._dispatch_chunk()
        if pending is not None:
            self._drain_chunk(pending)

    def _carry_feed(self):
        """(next_tokens, lengths) device arrays for the next chunk: the
        previous chunk's carry futures when available (zero host→device
        transfer), with host-mutated slots patched in; a full host
        upload only after a mode switch (engine start, verify pass)."""
        if self._carry is None:
            self._carry_dirty.clear()
            self._ds.uploads += 2
            self._carry = (
                jnp.asarray(self.next_token), jnp.asarray(self.lengths)
            )
            return self._carry
        if self._carry_dirty:
            sl = sorted(self._carry_dirty)
            self._carry_dirty.clear()
            idx = jnp.asarray(np.asarray(sl, np.int32))
            tok, ln = self._carry
            tok = tok.at[idx].set(jnp.asarray(self.next_token[sl]))
            ln = ln.at[idx].set(jnp.asarray(self.lengths[sl]))
            self._ds.uploads += 1
            self._carry = (tok, ln)
        return self._carry

    def _dispatch_chunk(
        self, pipelined: bool = False
    ) -> Optional[_PendingChunk]:
        """Prepare and dispatch one fused decode chunk; returns the
        pending record to drain (or None when nothing is runnable).  All
        batch state rides device-resident mirrors (``_ds``) and the
        chunk-to-chunk carry, so a steady-state dispatch performs ZERO
        host→device uploads of unchanged state.  Host ``lengths`` is
        advanced eagerly (+K for active slots — data-independent), so
        page growth and admission logic stay accurate while the sampled
        tokens are still in flight.

        ``pipelined``: this dispatch happened while the previous chunk
        was still undrained — the device had queued work the whole time,
        so the host-gap sample is zero."""
        K = self.fused_steps
        prepared = self._prepare_step(K)
        if prepared is None:
            return None
        self.steps_run += 1  # a real dispatch follows (bench: ms/step)
        active, view = prepared
        self._key, sub = jax.random.split(self._key)
        use_filters = self._filters_requested(active)
        want_lp = self._logprobs_requested(active)
        use_pen = self._pens_requested(active)
        use_seed = self._seeds_requested(active)
        use_min = self._min_requested(active)
        ds = self._ds
        counts = (
            jnp.asarray(self._host_counts()) if use_pen else None
        )  # before the eager lengths advance below
        tok_dev, len_dev = self._carry_feed()
        if pipelined:
            # previous chunk still in flight when this one queued: the
            # device never idled between them
            self.host_gap_chunks += 1
            self.last_host_gap_ms = 0.0
            self._gap_sample(0.0)
        elif self._last_drain_done is not None:
            gap = time.perf_counter_ns() - self._last_drain_done
            self.host_gap_ns += gap
            self.host_gap_chunks += 1
            self.last_host_gap_ms = gap / 1e6
            self._gap_sample(self.last_host_gap_ms)
        out, self.kv, new_toks, new_lens = self._chunks[
            (use_filters, want_lp, use_pen, use_seed, use_min)
        ](
            self.params,
            self.kv,
            ds.get("view", view),
            tok_dev,
            len_dev,
            ds.get("active", active),
            ds.get_versioned("prompts", self.prompts, self._prompts_version),
            ds.get("prompt_lens", self.prompt_lens),
            ds.get("temps", self.temps),
            ds.get("top_ks", self.top_ks),
            ds.get("top_ps", self.top_ps),
            sub,
            self.lora_bank,
            ds.get("adapter_ids", self.adapter_ids),
            self._bias_dev,
            ds.get("freq_pens", self.freq_pens) if use_pen else None,
            ds.get("pres_pens", self.pres_pens) if use_pen else None,
            counts,
            self._seed_keys if use_seed else None,
            ds.get("seeded", self._seeded) if use_seed else None,
            self._stop_dev if use_min else None,
            ds.get("min_toks", self.min_toks) if use_min else None,
        )
        # adopt the carry futures: the next dispatch chains off them
        self._carry = (new_toks, new_lens)
        pos0 = self.lengths.copy()
        idx = np.nonzero(active)[0]
        self.lengths[idx] += K  # eager, data-independent advance
        pairs = [(int(i), self.slots[int(i)]) for i in idx]
        return _PendingChunk(
            out=out, want_lp=want_lp, n_steps=K, pos0=pos0, pairs=pairs
        )

    def _drain_chunk(self, pending: _PendingChunk) -> None:
        """Transfer a dispatched chunk's sampled tokens to the host and
        emit them.  Slots released or re-tenanted since the dispatch
        (stop/cancel discovered late under overlap, spill, engine-failure
        cleanup) are skipped — their in-flight tokens are the bounded
        overshoot and are discarded."""
        out, want_lp, K = pending.out, pending.want_lp, pending.n_steps
        if want_lp:
            sampled, chosen_lp, top_ids, top_lps = (
                np.asarray(a) for a in out
            )
        else:
            sampled = np.asarray(out)  # (B, K)
        # results are on host: from here until the next dispatch the
        # device is idle (unless a later chunk is already queued) — the
        # window the host-gap metric measures
        self._last_drain_done = time.perf_counter_ns()
        for i, req in pending.pairs:
            if self.slots[i] is not req or req.done.is_set():
                self.chunks_discarded += 1
                continue  # released/re-tenanted since dispatch: discard
            pos = int(pending.pos0[i])
            plen = int(self.prompt_lens[i])
            stopped = False
            for s in range(K):
                # step s sampled from logits at position pos+s; that is a
                # real emission iff it is at or past the last prompt token
                if pos + s >= plen - 1 and self.emitted[i] < req.max_new_tokens:
                    tok = int(sampled[i, s])
                    if want_lp and req.logprobs > 0:
                        self._emit(
                            req, tok, chosen_lp[i, s],
                            self._top_list(
                                top_ids[i, s], top_lps[i, s], req.logprobs
                            ),
                        )
                    else:
                        self._emit(req, tok)
                    self.emitted[i] += 1
                    if self._stops(i, req, tok):
                        # stop token emitted (and kept, HF-style); tokens
                        # the device sampled past it this chunk are dropped
                        stopped = True
                        break
            # host next_token mirror: identical to the device carry (same
            # in-prompt/sampled selection), so this does NOT dirty the
            # carry — the host copy only feeds verify windows and debug
            self.next_token[i] = (
                self.prompts[i, pos + K]
                if pos + K < plen
                else sampled[i, K - 1]
            )
            if (
                stopped
                or self.emitted[i] >= req.max_new_tokens
                or req.cancelled
            ):
                req.done.set()
                self._release_slot(i)
