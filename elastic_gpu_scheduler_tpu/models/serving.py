"""Continuous-batching inference engine (slot-based KV cache pool).

Serving-side subsystem of the workload plane: requests join and leave a
fixed-shape batch *between* decode steps, so the TPU always steps one static
(B_max, …) computation while work arrives and finishes asynchronously —
the standard continuous-batching design, kept XLA-friendly:

- one KV cache of shape (L, B_max, max_len, H, Dh); a slot per request;
- per-slot ``length`` and ``active`` vectors; finished/empty slots keep
  computing (masked, harmless) so shapes never change;
- prefill is decode-steps over the prompt (models/generate.py math) into
  the slot's cache region; admission happens between steps;
- greedy or temperature sampling per slot.

No reference analogue (SURVEY §2 #19); this is the inference-serving
capability slot of a complete framework.
"""

from __future__ import annotations

import functools
import queue
import threading
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .generate import cached_attention
from .quantize import wmat
from .transformer import TransformerConfig, _embed_lookup, rms_norm, rope


@dataclass
class Request:
    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    done: threading.Event = field(default_factory=threading.Event)
    output: list[int] = field(default_factory=list)
    error: str = ""  # set (with done) when the request is rejected


def _batched_decode_step(params, tokens, cache_k, cache_v, lengths, cfg):
    """One decode step for every slot at its own position.

    tokens: (B,) int32; cache_k/v: (L, B, M, H, Dh); lengths: (B,) int32
    (position each slot writes at).  Returns (logits (B,V), new_k, new_v).
    """
    dtype = jnp.dtype(cfg.dtype)
    B = tokens.shape[0]
    M = cache_k.shape[2]
    Hn, Dh = cfg.n_heads, cfg.head_dim
    x = _embed_lookup(params["embed"], tokens, dtype)[:, None, :]  # (B,1,D)

    def layer_step(x, scanned):
        p, ck, cv = scanned  # ck/cv: (B, M, H, Dh)
        h = rms_norm(x, p["attn_norm"])
        Hkv = cfg.kv_heads
        q = (h @ wmat(p["wq"], dtype)).reshape(B, 1, Hn, Dh)
        k = (h @ wmat(p["wk"], dtype)).reshape(B, 1, Hkv, Dh)
        v = (h @ wmat(p["wv"], dtype)).reshape(B, 1, Hkv, Dh)
        # rope at each slot's own position (vmap over batch)
        rope_b = jax.vmap(
            lambda xb, pos: rope(xb[None], pos[None], cfg.rope_theta)[0]
        )
        q = rope_b(q, lengths)
        k = rope_b(k, lengths)
        # write k/v at per-slot positions
        onehot = jax.nn.one_hot(lengths, M, dtype=ck.dtype)  # (B, M)
        ck = ck * (1 - onehot)[:, :, None, None] + onehot[:, :, None, None] * k
        cv = cv * (1 - onehot)[:, :, None, None] + onehot[:, :, None, None] * v
        # attend over each slot's valid prefix (grouped GQA + window via
        # the shared cached_attention from generate.py)
        o = cached_attention(
            q, ck, cv, lengths, window=cfg.window_size
        ).reshape(B, 1, Hn * Dh)
        x = x + (o @ wmat(p["wo"], dtype))
        h = rms_norm(x, p["mlp_norm"])
        gate = jax.nn.silu(h @ wmat(p["w_gate"], dtype))
        up = h @ wmat(p["w_in"], dtype)
        x = x + ((gate * up) @ wmat(p["w_out"], dtype))
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(layer_step, x, (params["layers"], cache_k, cache_v))
    x = rms_norm(x, params["final_norm"])
    logits = (x @ wmat(params["unembed"], dtype))[:, 0, :]
    return logits.astype(jnp.float32), new_k, new_v


class InferenceEngine:
    """Slot-based continuous batching over a fixed (B_max, max_len) cache."""

    def __init__(
        self,
        params,
        cfg: TransformerConfig,
        max_batch: int = 8,
        max_len: int = 512,
    ):
        assert cfg.n_experts == 0, "serving engine supports dense models"
        self.params = params
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len
        dtype = jnp.dtype(cfg.dtype)
        shape = (cfg.n_layers, max_batch, max_len, cfg.kv_heads, cfg.head_dim)
        self.cache_k = jnp.zeros(shape, dtype)
        self.cache_v = jnp.zeros(shape, dtype)
        self.lengths = np.zeros(max_batch, np.int32)
        self.slots: list[Optional[Request]] = [None] * max_batch
        self.pending_prompt: list[list[int]] = [[] for _ in range(max_batch)]
        self.emitted: np.ndarray = np.zeros(max_batch, np.int32)
        self.next_token = np.zeros(max_batch, np.int32)
        self.queue: "queue.Queue[Request]" = queue.Queue()
        self._step = jax.jit(
            functools.partial(_batched_decode_step, cfg=cfg)
        )
        self._rng = np.random.default_rng(0)

    # -- public API ----------------------------------------------------------

    def submit(self, req: Request) -> Request:
        """Validate and enqueue; invalid requests are failed immediately
        (req.error set, done signaled) rather than poisoning the loop."""
        if len(req.prompt) < 1:
            req.error = "empty prompt"
            req.done.set()
            return req
        if len(req.prompt) + req.max_new_tokens > self.max_len:
            req.error = (
                f"prompt {len(req.prompt)} + max_new_tokens "
                f"{req.max_new_tokens} exceeds max_len {self.max_len}"
            )
            req.done.set()
            return req
        if req.max_new_tokens <= 0:
            req.done.set()  # nothing to generate
            return req
        self.queue.put(req)
        return req

    def run_until_idle(self, max_steps: int = 10_000) -> None:
        """Drive decode steps until no request is active or queued."""
        for _ in range(max_steps):
            self._admit()
            if not any(s is not None for s in self.slots):
                if self.queue.empty():
                    return
                continue
            self.step()
        raise RuntimeError("run_until_idle: step budget exhausted")

    # -- engine internals ----------------------------------------------------

    def _admit(self) -> None:
        for i in range(self.max_batch):
            if self.slots[i] is not None:
                continue
            try:
                req = self.queue.get_nowait()
            except queue.Empty:
                return
            self.slots[i] = req
            self.pending_prompt[i] = list(req.prompt[1:])
            self.next_token[i] = req.prompt[0]
            self.lengths[i] = 0
            self.emitted[i] = 0
            # no cache zeroing needed: the position mask only exposes
            # positions <= length, all of which the new request rewrites

    def step(self) -> None:
        """One batched decode step across all slots (prefill + generate)."""
        tokens = jnp.asarray(self.next_token)
        lengths = jnp.asarray(self.lengths)
        logits, self.cache_k, self.cache_v = self._step(
            self.params, tokens, self.cache_k, self.cache_v, lengths
        )
        logits_np = np.asarray(logits)
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            self.lengths[i] += 1
            if self.pending_prompt[i]:
                # still prefilling: feed the next prompt token
                self.next_token[i] = self.pending_prompt[i].pop(0)
                continue
            # generating
            if req.temperature > 0:
                z = logits_np[i] / req.temperature
                z = z - z.max()
                p = np.exp(z) / np.exp(z).sum()
                tok = int(self._rng.choice(len(p), p=p))
            else:
                tok = int(np.argmax(logits_np[i]))
            req.output.append(tok)
            self.emitted[i] += 1
            self.next_token[i] = tok
            if self.emitted[i] >= req.max_new_tokens:
                req.done.set()
                self.slots[i] = None
