"""Mixture-of-Experts FFN with expert parallelism (Switch-style top-1).

TPU-first formulation: routing is expressed as dense one-hot dispatch/combine
einsums (the GSPMD MoE pattern) so XLA lowers it to MXU matmuls plus an
all-to-all over the ``expert`` mesh axis — no gathers/scatters with dynamic
shapes.  Capacity-factor token dropping keeps every shape static.

Expert weights carry a leading E axis sharded over the ``expert`` mesh axis
(parallel/sharding.py); with E experts over ``expert``-axis devices, each
device holds E/expert-size experts and XLA inserts the dispatch all-to-all.

No reference analogue (the reference schedules pods; SURVEY §2 #19) — this
is workload-plane capability, the EP slot of dp/fsdp/ep/pp/tp/sp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .quantize import wmat


def moe_ffn(
    x: jax.Array,
    gate_w: jax.Array,
    w_in: jax.Array,
    w_gate: jax.Array,
    w_out: jax.Array,
    capacity_factor: float = 1.25,
    dtype=jnp.bfloat16,
) -> tuple[jax.Array, jax.Array]:
    """Switch-style MoE feed-forward.

    x:      (B, S, D) tokens
    gate_w: (D, E)    router
    w_in/w_gate: (E, D, F); w_out: (E, F, D)  — expert-stacked SwiGLU FFN
    Returns (output (B,S,D), aux_loss scalar) — aux is the load-balancing
    loss (mean_prob · mean_assignment · E), the standard Switch auxiliary.
    """
    B, S, D = x.shape
    E = gate_w.shape[-1]
    tokens = B * S
    capacity = max(1, int(capacity_factor * tokens / E))

    xf = x.reshape(tokens, D)
    logits = (xf @ wmat(gate_w, x.dtype)).astype(jnp.float32)  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (T,)
    expert_prob = jnp.max(probs, axis=-1)  # (T,)

    # position of each token within its expert's queue (static shapes)
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # (T, E)
    position = jnp.cumsum(onehot, axis=0) * onehot  # 1-based where assigned
    pos_in_expert = jnp.sum(position, axis=-1) - 1  # (T,), -1 if unassigned
    kept = (pos_in_expert >= 0) & (pos_in_expert < capacity)

    # dispatch/combine tensors (T, E, C)
    dispatch = (
        jax.nn.one_hot(expert_idx, E, dtype=x.dtype)[:, :, None]
        * jax.nn.one_hot(jnp.clip(pos_in_expert, 0, capacity - 1), capacity,
                         dtype=x.dtype)[:, None, :]
        * kept[:, None, None].astype(x.dtype)
    )
    combine = dispatch * expert_prob[:, None, None].astype(x.dtype)

    # route to experts: (E, C, D)
    expert_in = jnp.einsum(
        "tec,td->ecd", dispatch, xf, preferred_element_type=jnp.float32
    ).astype(dtype)
    # expert SwiGLU, batched over the (sharded) E axis
    gate = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", expert_in, wmat(w_gate, dtype))
    )
    up = jnp.einsum("ecd,edf->ecf", expert_in, wmat(w_in, dtype))
    expert_out = jnp.einsum(
        "ecf,efd->ecd", gate * up, wmat(w_out, dtype)
    )
    # combine back: (T, D)
    out = jnp.einsum(
        "tec,ecd->td", combine, expert_out.astype(x.dtype),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)

    # Switch load-balancing auxiliary loss
    density = jnp.mean(onehot.astype(jnp.float32), axis=0)  # fraction routed
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * E

    return out.reshape(B, S, D), aux
