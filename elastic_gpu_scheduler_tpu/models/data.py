"""Training data pipeline: token streams → sharded batches.

Two sources:
- ``MemmapTokenDataset``: a flat binary file of token ids (np.uint16/uint32
  memmap) — zero-copy random windows, the standard LM pretraining layout;
- ``SyntheticTokenDataset``: a deterministic synthetic language (repeated
  motifs + noise) so convergence tests have real signal without any files.

Batches are sharded for multi-process SPMD: each data-parallel process takes
its ``process_index``-th slice of the global batch, so the same global batch
order is seen regardless of process count (host-sharded data loading).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np


class MemmapTokenDataset:
    def __init__(self, path: str, dtype: str = "uint16"):
        self.path = path
        self.tokens = np.memmap(path, dtype=np.dtype(dtype), mode="r")
        if len(self.tokens) == 0:
            raise ValueError(f"{path}: empty token file")

    def __len__(self) -> int:
        return len(self.tokens)

    def window(self, start: int, length: int) -> np.ndarray:
        """A contiguous `length`-token window; `start` is taken modulo the
        valid range so any 64-bit start is usable."""
        if len(self.tokens) < length:
            raise ValueError(
                f"{self.path}: {len(self.tokens)} tokens < window {length}"
            )
        # valid start positions are 0..len-length INCLUSIVE
        valid = len(self.tokens) - length + 1
        start = int(start) % valid
        return np.asarray(self.tokens[start : start + length], dtype=np.int32)


class SyntheticTokenDataset:
    """Motif language: sequences stitched from a fixed motif bank + noise.

    Predictable structure (motifs repeat) gives a learnable signal; the
    noise rate bounds the achievable loss above zero.
    """

    def __init__(
        self, vocab_size: int, seed: int = 0, n_motifs: int = 32,
        motif_len: int = 8, noise: float = 0.1,
    ):
        rng = np.random.default_rng(seed)
        self.vocab_size = vocab_size
        self.noise = noise
        self.motifs = rng.integers(
            0, vocab_size, size=(n_motifs, motif_len), dtype=np.int64
        )

    def sample(self, rng: np.random.Generator, length: int) -> np.ndarray:
        out = []
        while sum(len(m) for m in out) < length:
            out.append(self.motifs[rng.integers(0, len(self.motifs))])
        seq = np.concatenate(out)[:length]
        noise_mask = rng.random(length) < self.noise
        seq = np.where(
            noise_mask, rng.integers(0, self.vocab_size, size=length), seq
        )
        return seq.astype(np.int32)


def batches(
    source,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
    process_index: int = 0,
    process_count: int = 1,
    max_batches: Optional[int] = None,
    start_batch: int = 0,
) -> Iterator[np.ndarray]:
    """Yields (local_batch, seq_len+1) int32 arrays (inputs+shift target).

    ``batch_size`` is the GLOBAL batch; each process yields its slice.
    Each batch index gets its own RNG derived from (seed, index), so
    ``start_batch`` fast-forwards a resumed run in O(1) — no arrays are
    built for skipped batches — while the stream stays identical.
    """
    if batch_size % process_count:
        raise ValueError(
            f"global batch {batch_size} not divisible by {process_count} processes"
        )
    local = batch_size // process_count
    i = start_batch
    while max_batches is None or i < start_batch + max_batches:
        rng = np.random.default_rng([seed, i])
        rows = []
        for b in range(batch_size):
            if isinstance(source, MemmapTokenDataset):
                row = source.window(rng.integers(0, 1 << 62), seq_len + 1)
            else:
                row = source.sample(rng, seq_len + 1)
            rows.append(row)
        global_batch = np.stack(rows)
        start = process_index * local
        yield global_batch[start : start + local]
        i += 1


def write_token_file(path: str, tokens: np.ndarray, dtype: str = "uint16") -> None:
    np.asarray(tokens, dtype=np.dtype(dtype)).tofile(path)
