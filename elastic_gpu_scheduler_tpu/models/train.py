"""Training step: loss, optimizer, and the sharded update function.

The full SPMD recipe: params sharded per parallel/sharding.py, batch sharded
over (data, fsdp) × seq, one jitted ``train_step`` in which XLA inserts all
collectives (gradient psum over data/fsdp, all-gathers for tensor-parallel
matmuls, ppermute ring hops for sequence parallelism).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel import sharding as shardlib
from .transformer import TransformerConfig, forward_with_aux, init_params


class MasterState(NamedTuple):
    """Optimizer state for low-precision-at-rest params: the fp32 master
    copy (the standard mixed-precision recipe — bf16 weights are read by
    the forward, fp32 masters absorb the small updates) + the inner optax
    state, which tracks the masters."""

    master: Any
    inner: Any


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Masked mean next-token CE.  logits: (B,S,V) fp32; targets: (B,S)
    int32.

    Target ids outside [0, V) are IGNORED: they contribute nothing and are
    excluded from the mean's denominator — the torch ``ignore_index``
    convention, so padding pipelines can mark positions with -100 (or any
    out-of-range id) and get a correct loss instead of the gather
    default's silent NaN.  The vocab-chunked path (ops/xent.py) implements
    exactly the same semantics, so toggling ``xent_chunks`` changes the
    reported loss only by bf16 rounding (the dense path rounds logits
    through the bf16 matmul output; the chunked path keeps fp32 via
    preferred_element_type — see ops/xent.py's numerics note)."""
    V = logits.shape[-1]
    valid = (targets >= 0) & (targets < V)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, jnp.clip(targets, 0, V - 1)[..., None], axis=-1
    )[..., 0]
    n_valid = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(jnp.where(valid, logz - gold, 0.0)) / n_valid


def make_optimizer(
    lr: float = 3e-4,
    weight_decay: float = 0.01,
    warmup_steps: int = 0,
    total_steps: int = 0,
    grad_clip: float = 0.0,
    mu_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """AdamW with optional linear-warmup + cosine decay and global-norm clip
    (the standard LM pretraining recipe).

    ``mu_dtype="bfloat16"`` stores the FIRST moment in bf16 — the common
    large-run memory/bandwidth trim (m is smooth, so bf16 is safe; the
    second moment v stays fp32 because rsqrt amplifies its error)."""
    if warmup_steps > 0 and total_steps > warmup_steps:
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=lr,
            warmup_steps=warmup_steps,
            decay_steps=total_steps,
            end_value=lr * 0.1,
        )
    else:
        schedule = lr
    tx = optax.adamw(
        schedule, b1=0.9, b2=0.95, weight_decay=weight_decay,
        mu_dtype=jnp.dtype(mu_dtype) if mu_dtype else None,
    )
    if grad_clip > 0:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip), tx)
    return tx


def loss_fn(
    params, tokens, cfg: TransformerConfig, mesh: Optional[Mesh] = None
) -> jax.Array:
    """tokens: (B, S+1); predicts tokens[:,1:] from tokens[:,:-1]."""
    inputs = tokens[:, :-1]
    targets = tokens[:, 1:]
    if mesh is not None:
        inputs = shardlib.constrain(inputs, mesh, shardlib.batch_spec())
    if cfg.xent_chunks > 0:
        # vocab-chunked CE: the (B, S, V) logits tensor never materializes
        # (ops/xent.py) — O(S·D) activations end to end for long context
        from ..ops.xent import chunked_softmax_xent, chunked_softmax_xent_tp
        from .quantize import wmat
        from .transformer import hidden_with_aux

        hidden, aux = hidden_with_aux(params, inputs, cfg, mesh=mesh)
        w = wmat(params["unembed"], jnp.dtype(cfg.dtype))
        if mesh is not None and mesh.shape.get("tensor", 1) > 1:
            # V-sharded unembed: per-rank chunk scan + one logsumexp merge
            # (the TP×chunked composition; invalid chunk/tensor combos are
            # rejected there with a named error)
            loss = chunked_softmax_xent_tp(
                hidden, w, targets, cfg.xent_chunks, mesh
            )
        else:
            loss = chunked_softmax_xent(hidden, w, targets, cfg.xent_chunks)
    else:
        logits, aux = forward_with_aux(params, inputs, cfg, mesh=mesh)
        loss = cross_entropy_loss(logits, targets)
    if cfg.n_experts > 0:
        loss = loss + cfg.aux_loss_weight * aux
    return loss


def make_train_step(
    cfg: TransformerConfig,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    grad_accum: int = 1,
):
    """Returns train_step(params, opt_state, tokens) → (params, opt_state, loss).

    ``grad_accum`` > 1 splits the batch into that many microbatches and
    accumulates fp32 gradients in a ``lax.scan`` before ONE optimizer
    update — the standard recipe for effective batch sizes that don't fit
    activations in HBM (complementary to remat, which trades FLOPs for
    activation memory within one microbatch)."""

    def grads_of(params, tokens):
        if grad_accum <= 1:
            return jax.value_and_grad(loss_fn)(params, tokens, cfg, mesh)
        B = tokens.shape[0]
        assert B % grad_accum == 0, (
            f"batch {B} not divisible by grad_accum {grad_accum}"
        )
        micro = tokens.reshape(grad_accum, B // grad_accum, tokens.shape[1])

        def body(acc, mb):
            loss_sum, g_acc = acc
            loss, g = jax.value_and_grad(loss_fn)(params, mb, cfg, mesh)
            g_acc = jax.tree.map(
                lambda a, x: a + x.astype(jnp.float32), g_acc, g
            )
            return (loss_sum + loss, g_acc), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        (loss_sum, g_sum), _ = jax.lax.scan(body, (0.0, zeros), micro)
        inv = 1.0 / grad_accum
        return loss_sum * inv, jax.tree.map(lambda g: g * inv, g_sum)

    def train_step(params, opt_state, tokens):
        loss, grads = grads_of(params, tokens)
        if isinstance(opt_state, MasterState):
            master, inner = opt_state
            grads = jax.tree.map(lambda g, m: g.astype(m.dtype), grads, master)
            updates, inner = optimizer.update(grads, inner, master)
            master = optax.apply_updates(master, updates)
            params = jax.tree.map(lambda m, p: m.astype(p.dtype), master, params)
            return params, MasterState(master, inner), loss
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def init_sharded_state(
    key: jax.Array,
    cfg: TransformerConfig,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
):
    """Init params (+opt state), placed per the sharding rules when a mesh is
    given."""
    params = init_params(key, cfg)  # already at-rest dtype (maybe bf16)
    if mesh is not None:
        pipelined = cfg.n_microbatches > 0 and mesh.shape.get("pipe", 1) > 1
        params = shardlib.shard_params(params, mesh, pipeline=pipelined)

    def place_scalars(opt_state):
        """Commit scalar/unsharded optimizer leaves (e.g. adam's step count)
        as mesh-REPLICATED.  optax.init creates them on the default device;
        leaving them there makes checkpoint templates carry a single-device
        sharding that conflicts with mesh-sharded params after an elastic
        restore onto a different mesh."""
        if mesh is None:
            return opt_state
        rep = NamedSharding(mesh, P())
        return jax.tree.map(
            lambda x: x
            if isinstance(getattr(x, "sharding", None), NamedSharding)
            else jax.device_put(x, rep),
            opt_state,
        )

    if any(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(params)):
        # fp32 leaves must be COPIES, not aliases of the params leaves —
        # the jitted step donates both trees and a shared buffer would be
        # donated twice
        master = jax.tree.map(
            lambda x: x.astype(jnp.float32)
            if x.dtype == jnp.bfloat16
            else jnp.copy(x),
            params,
        )
        return params, MasterState(master, place_scalars(optimizer.init(master)))
    return params, place_scalars(optimizer.init(params))


def make_jitted_train_step(
    cfg: TransformerConfig,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    grad_accum: int = 1,
):
    step = make_train_step(cfg, optimizer, mesh, grad_accum=grad_accum)
    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))
    batch_sharding = NamedSharding(mesh, P(("data", "fsdp"), None))
    return jax.jit(
        step,
        in_shardings=(None, None, batch_sharding),
        donate_argnums=(0, 1),
    )


def evaluate(
    params,
    cfg: TransformerConfig,
    batches,
    mesh: Optional[Mesh] = None,
) -> dict:
    """Mean next-token loss + perplexity over an iterable of (B, S+1) token
    batches (the standard held-out eval loop)."""
    eval_loss = jax.jit(functools.partial(loss_fn, cfg=cfg, mesh=mesh))
    total, n = 0.0, 0
    for tokens in batches:
        total += float(eval_loss(params, tokens))
        n += 1
    if n == 0:
        raise ValueError("evaluate: no batches")
    mean = total / n
    import math

    return {"loss": mean, "perplexity": math.exp(min(mean, 30.0)), "batches": n}
