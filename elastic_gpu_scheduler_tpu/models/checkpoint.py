"""Checkpoint / resume for training jobs (orbax-backed).

The scheduler's durable state is the pod-annotation ledger (core/
annotations.py — crash-safe restart, mirroring the reference); the workload's
durable state is this: params + optimizer state + step, saved via orbax so a
rescheduled/preempted pod resumes where it left off.  Sharded arrays are
saved/restored with their shardings (orbax handles jax.sharding natively).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

log = logging.getLogger("tpu-launcher")


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep),
        )

    def save(
        self, params: Any, opt_state: Any, step: int, block: bool = False
    ) -> None:
        """ASYNC by default: orbax snapshots device arrays now and
        serializes in background threads while training continues — the
        save costs the train loop a device-to-host copy, not the disk
        write.  orbax joins any in-flight save internally before writing
        (and on close); restore() adds its own join so a reader never
        races a write.  ``block=True`` for the final save of a job."""
        saved = self.manager.save(
            step,
            args=self._ocp.args.Composite(
                params=self._ocp.args.StandardSave(params),
                opt_state=self._ocp.args.StandardSave(opt_state),
            ),
        )
        if block:
            self.manager.wait_until_finished()
        if saved:
            log.info("checkpoint save dispatched at step %d (block=%s)",
                     step, block)
        else:  # orbax no-opped (step already saved / should_save False)
            log.info("checkpoint save skipped at step %d", step)

    def restore(
        self, params_template: Any, opt_state_template: Any
    ) -> Optional[tuple[Any, Any, int]]:
        """Restore the latest checkpoint, or None if none exists.

        Templates provide structure/shardings for sharded restore."""
        self.manager.wait_until_finished()  # join any in-flight save
        step = self.manager.latest_step()
        if step is None:
            return None
        restored = self.manager.restore(
            step,
            args=self._ocp.args.Composite(
                params=self._ocp.args.StandardRestore(params_template),
                opt_state=self._ocp.args.StandardRestore(opt_state_template),
            ),
        )
        # ELASTIC resume: force every leaf onto the template's sharding.
        # Orbax restores array shards faithfully but can leave small/scalar
        # leaves (e.g. the optimizer step counter) on a single device, which
        # then clashes with mesh-sharded params inside one jit.
        import jax

        def match(r, t):
            if hasattr(t, "sharding"):
                return jax.device_put(r, t.sharding)
            return r

        params = jax.tree.map(match, restored["params"], params_template)
        opt_state = jax.tree.map(match, restored["opt_state"], opt_state_template)
        return params, opt_state, step

    def close(self) -> None:
        self.manager.close()  # joins any in-flight save internally
