"""Second model family: Vision Transformer (image classification).

Shares the TPU-first machinery of the flagship LM — flash attention
(non-causal), RMSNorm/SwiGLU blocks, stacked-layer ``lax.scan``, and the
same parameter naming so parallel/sharding.py's rules shard it unchanged
(wq/wk/wv column-parallel, wo row-parallel, etc.).  Patchify is a single
reshape+matmul (MXU-native; no conv needed for square non-overlapping
patches).

No reference analogue (SURVEY §2 #19) — workload-plane breadth: one
framework, multiple model families over the same mesh axes.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from ..ops.attention import flash_attention
from .quantize import wmat
from .transformer import rms_norm


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    channels: int = 3
    n_classes: int = 10
    d_model: int = 192
    n_layers: int = 6
    n_heads: int = 6
    d_ff: int = 512
    dtype: str = "bfloat16"
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def init_vit_params(key: jax.Array, cfg: ViTConfig) -> dict:
    D, H, F, L = cfg.d_model, cfg.n_heads * cfg.head_dim, cfg.d_ff, cfg.n_layers
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.channels
    k = iter(jax.random.split(key, 16))

    def dense(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) * fan_in**-0.5

    return {
        "patch_embed": dense(next(k), (patch_dim, D), patch_dim),
        "pos_embed": dense(next(k), (cfg.n_patches + 1, D), D) * 0.02,
        "cls_token": jnp.zeros((D,), jnp.float32),
        "layers": {
            "attn_norm": jnp.ones((L, D), jnp.float32),
            "wq": dense(next(k), (L, D, H), D),
            "wk": dense(next(k), (L, D, H), D),
            "wv": dense(next(k), (L, D, H), D),
            "wo": dense(next(k), (L, H, D), H),
            "mlp_norm": jnp.ones((L, D), jnp.float32),
            "w_in": dense(next(k), (L, D, F), D),
            "w_gate": dense(next(k), (L, D, F), D),
            "w_out": dense(next(k), (L, F, D), F),
        },
        "final_norm": jnp.ones((D,), jnp.float32),
        "head": dense(next(k), (D, cfg.n_classes), D),
    }


def patchify(images: jax.Array, patch: int) -> jax.Array:
    """(B, H, W, C) → (B, N, patch*patch*C) non-overlapping patches."""
    B, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(B, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, gh, gw, p, p, C)
    return x.reshape(B, gh * gw, patch * patch * C)


def _vit_layer(x, p, cfg: ViTConfig):
    """Pre-norm bidirectional block. x: (B, N+1, D)."""
    B, S, D = x.shape
    Hn, Dh = cfg.n_heads, cfg.head_dim
    dtype = jnp.dtype(cfg.dtype)

    h = rms_norm(x, p["attn_norm"])
    q = (h @ wmat(p["wq"], dtype)).reshape(B, S, Hn, Dh).transpose(0, 2, 1, 3)
    k = (h @ wmat(p["wk"], dtype)).reshape(B, S, Hn, Dh).transpose(0, 2, 1, 3)
    v = (h @ wmat(p["wv"], dtype)).reshape(B, S, Hn, Dh).transpose(0, 2, 1, 3)
    o = flash_attention(q, k, v, False, None)  # bidirectional
    o = o.transpose(0, 2, 1, 3).reshape(B, S, Hn * Dh)
    x = x + (o @ wmat(p["wo"], dtype))

    h = rms_norm(x, p["mlp_norm"])
    gate = jax.nn.silu(h @ wmat(p["w_gate"], dtype))
    up = h @ wmat(p["w_in"], dtype)
    x = x + ((gate * up) @ wmat(p["w_out"], dtype))
    return x


def forward_vit(params: dict, images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """images: (B, H, W, C) float → logits (B, n_classes)."""
    dtype = jnp.dtype(cfg.dtype)
    patches = patchify(images.astype(dtype), cfg.patch_size)
    x = patches @ wmat(params["patch_embed"], dtype)  # (B, N, D)
    B = x.shape[0]
    cls = jnp.broadcast_to(
        params["cls_token"].astype(dtype), (B, 1, cfg.d_model)
    )
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"].astype(dtype)

    layer_fn = lambda h, p: (_vit_layer(h, p, cfg), None)
    if cfg.remat:
        inner = jax.checkpoint(lambda h, p: _vit_layer(h, p, cfg))
        layer_fn = lambda h, p: (inner(h, p), None)
    x, _ = lax.scan(layer_fn, x, params["layers"])
    x = rms_norm(x, params["final_norm"])
    logits = x[:, 0, :] @ wmat(params["head"], dtype)  # CLS token
    return logits.astype(jnp.float32)


def vit_loss(params, images, labels, cfg: ViTConfig) -> jax.Array:
    logits = forward_vit(params, images, cfg)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - gold)


def make_vit_train_step(cfg: ViTConfig, optimizer, mesh: Mesh = None):
    import optax

    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(vit_loss)(params, images, labels, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))
