"""Weight import: HF/torch Llama-architecture checkpoints → our params.

The flagship transformer (models/transformer.py) is architecturally a
Llama-family decoder (RMSNorm pre-norm, SwiGLU MLP, non-interleaved RoPE, no
biases), so HF ``LlamaForCausalLM`` weights map 1:1:

    model.embed_tokens.weight        → embed            (V, D)
    layers.N.input_layernorm         → attn_norm[N]     (D,)
    layers.N.self_attn.{q,k,v}_proj  → wq/wk/wv[N]      (D, H)   [transposed]
    layers.N.self_attn.o_proj        → wo[N]            (H, D)   [transposed]
    layers.N.post_attention_layernorm→ mlp_norm[N]      (D,)
    layers.N.mlp.gate_proj           → w_gate[N]        (D, F)   [transposed]
    layers.N.mlp.up_proj             → w_in[N]          (D, F)   [transposed]
    layers.N.mlp.down_proj           → w_out[N]         (F, D)   [transposed]
    model.norm                       → final_norm       (D,)
    lm_head.weight                   → unembed          (D, V)   [transposed]

GQA checkpoints (num_key_value_heads < num_heads) map via ``n_kv_heads``;
Mistral-style sliding windows map via ``window_size``.  Conversion runs on
CPU numpy — no torch on the TPU path.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .transformer import TransformerConfig


def _np(t) -> np.ndarray:
    """torch tensor (or array) → float32 numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def config_from_hf_llama(hf_config) -> TransformerConfig:
    # refuse silently-wrong conversions: features our forward doesn't model
    if getattr(hf_config, "rope_scaling", None):
        raise ValueError(
            "rope_scaling (e.g. llama3 long-context scaling) not supported"
        )
    if getattr(hf_config, "attention_bias", False) or getattr(
        hf_config, "mlp_bias", False
    ):
        raise ValueError("bias terms (attention_bias/mlp_bias) not supported")
    explicit_hd = getattr(hf_config, "head_dim", None)
    derived_hd = hf_config.hidden_size // hf_config.num_attention_heads
    if explicit_hd and explicit_hd != derived_hd:
        raise ValueError(
            f"explicit head_dim {explicit_hd} != hidden/heads {derived_hd}"
        )
    kv = getattr(hf_config, "num_key_value_heads", hf_config.num_attention_heads)
    window = getattr(hf_config, "sliding_window", None) or 0
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=0 if kv == hf_config.num_attention_heads else kv,
        window_size=int(window),
        d_ff=hf_config.intermediate_size,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        dtype="float32",
    )


def params_from_hf_llama(state_dict, cfg: TransformerConfig) -> dict:
    """Build our param pytree from an HF LlamaForCausalLM state_dict."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    L = cfg.n_layers

    def stack(fmt: str, transpose: bool) -> np.ndarray:
        mats = []
        for i in range(L):
            w = sd[fmt.format(i)]
            mats.append(w.T if transpose else w)
        return np.stack(mats)

    embed = sd["model.embed_tokens.weight"]  # (V, D)
    if "lm_head.weight" in sd:
        unembed = sd["lm_head.weight"].T  # (D, V)
    else:  # tied embeddings
        unembed = embed.T.copy()

    params = {
        "embed": jnp.asarray(embed),
        "layers": {
            "attn_norm": jnp.asarray(
                stack("model.layers.{}.input_layernorm.weight", False)
            ),
            "wq": jnp.asarray(
                stack("model.layers.{}.self_attn.q_proj.weight", True)
            ),
            "wk": jnp.asarray(
                stack("model.layers.{}.self_attn.k_proj.weight", True)
            ),
            "wv": jnp.asarray(
                stack("model.layers.{}.self_attn.v_proj.weight", True)
            ),
            "wo": jnp.asarray(
                stack("model.layers.{}.self_attn.o_proj.weight", True)
            ),
            "mlp_norm": jnp.asarray(
                stack("model.layers.{}.post_attention_layernorm.weight", False)
            ),
            "w_gate": jnp.asarray(
                stack("model.layers.{}.mlp.gate_proj.weight", True)
            ),
            "w_in": jnp.asarray(stack("model.layers.{}.mlp.up_proj.weight", True)),
            "w_out": jnp.asarray(
                stack("model.layers.{}.mlp.down_proj.weight", True)
            ),
        },
        "final_norm": jnp.asarray(sd["model.norm.weight"]),
        "unembed": jnp.asarray(unembed),
    }
    return params
