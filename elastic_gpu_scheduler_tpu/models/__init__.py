"""Workload-plane models: transformer LM, MoE, training, generation, data."""

from .transformer import TransformerConfig, forward, forward_with_aux, init_params
from .train import make_jitted_train_step, make_optimizer, init_sharded_state

__all__ = [
    "TransformerConfig", "forward", "forward_with_aux", "init_params",
    "make_jitted_train_step", "make_optimizer", "init_sharded_state",
]
