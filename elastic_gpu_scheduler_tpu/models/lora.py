"""LoRA: low-rank adapter fine-tuning for the flagship models.

TPU-first shape: the transformer stores each weight family STACKED over
layers ((L, d_in, d_out), models/transformer.py init_params), so a LoRA
adapter is one pair of stacked low-rank factors A (L, d_in, r) and
B (L, r, d_out) per target family, and the merge W + (alpha/r)·A@B is ONE
batched einsum on the MXU per family — no per-layer Python loops, nothing
for XLA to unroll.

Training uses the ACTIVATION-domain view (``inject_lora`` +
transformer._proj): each adapted matmul computes x@W + scale·(x@A)@B with
the low-rank delta added in fp32 before the compute-dtype cast.  Autodiff
flows through the explicit adapter branch so gradients land only on
(A, B) — the base stays frozen bits (and can live in bf16 at rest).  The
adapter matmuls cost r/d of one weight read — noise next to a train step.
(A merged view W + scale·A@B would round deltas below the bf16 base's ulp
to zero for every token — early fine-tuning would silently stall.)

For serving, ``merge_lora`` bakes the adapters in once and returns plain
params usable by every existing path (generate, serving engine, export);
merging quantizes the delta into the base dtype, which is fine for a
TRAINED adapter (its effect is far above ulp) but not for training.

No reference analogue (the reference schedules pods, SURVEY §2 #19); this
fills the fine-tuning capability slot of the workload plane.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import optax

from .transformer import TransformerConfig

# weight families eligible for adaptation (dense path)
DEFAULT_TARGETS = ("wq", "wv")
ALL_TARGETS = ("wq", "wk", "wv", "wo", "w_in", "w_gate", "w_out")


def lora_init(
    key: jax.Array,
    params: dict,
    rank: int,
    targets: Iterable[str] = DEFAULT_TARGETS,
    alpha: Optional[float] = None,
) -> dict:
    """Create zero-impact adapters: A ~ N(0, 1/d_in), B = 0 (the standard
    init — the merged model starts EXACTLY equal to the base)."""
    targets = tuple(targets)
    layers = params["layers"]
    adapters = {}
    keys = jax.random.split(key, len(targets))
    for t, kk in zip(targets, keys):
        if t not in layers:
            raise ValueError(f"LoRA target {t!r} not in model layers")
        W = layers[t]
        if isinstance(W, dict):  # quantize.py QTensor {"q8","scale"}
            raise ValueError(
                f"LoRA target {t!r} is int8-quantized; adapters need a "
                "full-precision base (quantize AFTER merge_lora if serving)"
            )
        if W.ndim != 3:
            raise ValueError(
                f"LoRA target {t!r} must be stacked (L, d_in, d_out); "
                f"got shape {W.shape} (MoE experts are not supported)"
            )
        L, d_in, d_out = W.shape
        adapters[t] = {
            "a": (
                jax.random.normal(kk, (L, d_in, rank), jnp.float32)
                * d_in ** -0.5
            ),
            "b": jnp.zeros((L, rank, d_out), jnp.float32),
        }
    return {
        "adapters": adapters,
        "alpha": float(alpha if alpha is not None else rank),
        "rank": rank,
    }


def lora_param_count(lora: dict) -> int:
    return sum(
        x.size for x in jax.tree.leaves(lora["adapters"])
    )


def inject_lora(params: dict, lora: dict) -> dict:
    """Return a params tree whose layer dict carries ``<target>_lora``
    leaves ({"a": (L, d_in, r), "b": (L, r, d_out)} with the alpha/r scale
    pre-folded into b) — the TRAINING view.

    transformer._proj applies these in the activation domain
    (``x@W + (x@A)@B``) with the delta added in fp32 before the compute-
    dtype cast, so adapter contributions below the base weight's ulp are
    NOT rounded away (they would be under a bf16 merged view — the loss
    would sit still early in fine-tuning while adapter grads stay
    nonzero).  The extra leaves are stacked over layers like every other
    family, so the ``lax.scan``/pipeline over layers carries them
    unchanged.  Differentiable in (A, B)."""
    scale = lora["alpha"] / lora["rank"]
    layers = dict(params["layers"])
    for t, ab in lora["adapters"].items():
        layers[t + "_lora"] = {"a": ab["a"], "b": ab["b"] * scale}
    out = dict(params)
    out["layers"] = layers
    return out


def merge_lora(params: dict, lora: dict) -> dict:
    """params + scale·A@B for every adapted family; returns a params tree
    with the SAME structure/dtypes as the input (usable by every existing
    consumer).  Differentiable in (A, B)."""
    scale = lora["alpha"] / lora["rank"]
    layers = dict(params["layers"])
    for t, ab in lora["adapters"].items():
        W = layers[t]
        if isinstance(W, dict):
            raise ValueError(
                f"cannot merge into int8-quantized {t!r}; merge into the "
                "full-precision base, then quantize_params the result"
            )
        delta = jnp.einsum(
            "lir,lro->lio", ab["a"], ab["b"],
            preferred_element_type=jnp.float32,
        )
        layers[t] = (W.astype(jnp.float32) + scale * delta).astype(W.dtype)
    out = dict(params)
    out["layers"] = layers
    return out


def lora_loss_fn(
    lora: dict, params: dict, tokens: jax.Array, cfg: TransformerConfig,
    mesh=None,
) -> jax.Array:
    """The FULL-fine-tune objective (train.loss_fn) on the ADAPTER-INJECTED
    model (activation-domain application; see inject_lora for why not the
    merged view) — the same loss recipe a full fine-tune uses."""
    from .train import loss_fn

    return loss_fn(inject_lora(params, lora), tokens, cfg, mesh)


def make_lora_train_step(
    cfg: TransformerConfig,
    optimizer: optax.GradientTransformation,
    mesh=None,
):
    """train_step(lora, opt_state, params, tokens) → (lora, opt_state, loss).

    The optimizer state tracks only the adapters — for a 7B model at r=16
    that is ~0.1% of a full fine-tune's optimizer memory.
    """

    def step(lora, opt_state, params, tokens):
        # differentiate the ADAPTER leAVES only — lora also carries the
        # (non-differentiable) alpha/rank scalars
        def loss_of(adapters):
            return lora_loss_fn(
                {**lora, "adapters": adapters}, params, tokens, cfg, mesh
            )

        loss, g = jax.value_and_grad(loss_of)(lora["adapters"])
        updates, opt_state = optimizer.update(g, opt_state, lora["adapters"])
        adapters = optax.apply_updates(lora["adapters"], updates)
        return {**lora, "adapters": adapters}, opt_state, loss

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sharding = NamedSharding(mesh, P(("data", "fsdp"), None))
    return jax.jit(
        step,
        in_shardings=(None, None, None, batch_sharding),
        donate_argnums=(0, 1),
    )
