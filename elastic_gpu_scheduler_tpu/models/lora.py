"""LoRA: low-rank adapter fine-tuning for the flagship models.

TPU-first shape: the transformer stores each weight family STACKED over
layers ((L, d_in, d_out), models/transformer.py init_params), so a LoRA
adapter is one pair of stacked low-rank factors A (L, d_in, r) and
B (L, r, d_out) per target family, and the merge W + (alpha/r)·A@B is ONE
batched einsum on the MXU per family — no per-layer Python loops, nothing
for XLA to unroll.

Training uses the MERGED functional view: each step materializes
W' = W + scale·A@B inside the jit and runs the standard forward; autodiff
flows through the merge so gradients land only on (A, B) — the base stays
frozen bits (and can live in bf16 at rest).  The merge costs
O(L·d·d·r/d) = r/d of one weight read — noise next to a train step — and
XLA fuses it into the consuming matmuls' prologue.

For serving, ``merge_lora`` bakes the adapters in once and returns plain
params usable by every existing path (generate, serving engine, export).

No reference analogue (the reference schedules pods, SURVEY §2 #19); this
fills the fine-tuning capability slot of the workload plane.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import optax

from .transformer import TransformerConfig

# weight families eligible for adaptation (dense path)
DEFAULT_TARGETS = ("wq", "wv")
ALL_TARGETS = ("wq", "wk", "wv", "wo", "w_in", "w_gate", "w_out")


def lora_init(
    key: jax.Array,
    params: dict,
    rank: int,
    targets: Iterable[str] = DEFAULT_TARGETS,
    alpha: Optional[float] = None,
) -> dict:
    """Create zero-impact adapters: A ~ N(0, 1/d_in), B = 0 (the standard
    init — the merged model starts EXACTLY equal to the base)."""
    targets = tuple(targets)
    layers = params["layers"]
    adapters = {}
    keys = jax.random.split(key, len(targets))
    for t, kk in zip(targets, keys):
        if t not in layers:
            raise ValueError(f"LoRA target {t!r} not in model layers")
        W = layers[t]
        if W.ndim != 3:
            raise ValueError(
                f"LoRA target {t!r} must be stacked (L, d_in, d_out); "
                f"got shape {W.shape} (MoE experts are not supported)"
            )
        L, d_in, d_out = W.shape
        adapters[t] = {
            "a": (
                jax.random.normal(kk, (L, d_in, rank), jnp.float32)
                * d_in ** -0.5
            ),
            "b": jnp.zeros((L, rank, d_out), jnp.float32),
        }
    return {
        "adapters": adapters,
        "alpha": float(alpha if alpha is not None else rank),
        "rank": rank,
    }


def lora_param_count(lora: dict) -> int:
    return sum(
        x.size for x in jax.tree.leaves(lora["adapters"])
    )


def merge_lora(params: dict, lora: dict) -> dict:
    """params + scale·A@B for every adapted family; returns a params tree
    with the SAME structure/dtypes as the input (usable by every existing
    consumer).  Differentiable in (A, B)."""
    scale = lora["alpha"] / lora["rank"]
    layers = dict(params["layers"])
    for t, ab in lora["adapters"].items():
        W = layers[t]
        delta = jnp.einsum(
            "lir,lro->lio", ab["a"], ab["b"],
            preferred_element_type=jnp.float32,
        )
        layers[t] = (W.astype(jnp.float32) + scale * delta).astype(W.dtype)
    out = dict(params)
    out["layers"] = layers
    return out


def lora_loss_fn(
    lora: dict, params: dict, tokens: jax.Array, cfg: TransformerConfig,
    mesh=None,
) -> jax.Array:
    """The FULL-fine-tune objective (train.loss_fn) evaluated on the merged
    model — one loss recipe for both training modes, so adapters always
    train against exactly what a full fine-tune would."""
    from .train import loss_fn

    return loss_fn(merge_lora(params, lora), tokens, cfg, mesh)


def make_lora_train_step(
    cfg: TransformerConfig,
    optimizer: optax.GradientTransformation,
    mesh=None,
):
    """train_step(lora, opt_state, params, tokens) → (lora, opt_state, loss).

    The optimizer state tracks only the adapters — for a 7B model at r=16
    that is ~0.1% of a full fine-tune's optimizer memory.
    """

    def step(lora, opt_state, params, tokens):
        # differentiate the ADAPTER leAVES only — lora also carries the
        # (non-differentiable) alpha/rank scalars
        def loss_of(adapters):
            return lora_loss_fn(
                {**lora, "adapters": adapters}, params, tokens, cfg, mesh
            )

        loss, g = jax.value_and_grad(loss_of)(lora["adapters"])
        updates, opt_state = optimizer.update(g, opt_state, lora["adapters"])
        adapters = optax.apply_updates(lora["adapters"], updates)
        return {**lora, "adapters": adapters}, opt_state, loss

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sharding = NamedSharding(mesh, P(("data", "fsdp"), None))
    return jax.jit(
        step,
        in_shardings=(None, None, None, batch_sharding),
        donate_argnums=(0, 1),
    )
