"""Flagship model: a pure-functional decoder-only transformer LM.

TPU-first design choices:

- pure pytree params + functional apply (no framework classes): everything
  under ``jit`` traces once; static shapes throughout.
- layers are *stacked* on a leading L axis and applied with ``lax.scan`` —
  one compiled layer body regardless of depth (fast compiles, XLA-friendly).
- attention is the pluggable hot op: single-device flash attention
  (ops/attention.py, Pallas on TPU) or ring attention over the ``seq`` mesh
  axis for long context (parallel/ring.py).
- optional ``jax.checkpoint`` rematerialization per layer trades FLOPs for
  HBM (SURVEY §0 performance notes; standard long-context recipe).
- matmuls in bfloat16 with fp32 accumulation (MXU-native).

The reference has no model code (SURVEY §2 #19); this is the JAX SPMD
workload the north star schedules ("a JAX/XLA workload requesting
tpu-chip: N is placed, bound, and launched").
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from ..ops.attention import flash_attention
from .quantize import wmat
from ..parallel.ring import ring_attention, ring_attention_sharded


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    d_ff: int = 1376
    n_kv_heads: int = 0  # 0 → MHA; 0 < n_kv_heads < n_heads → GQA
    window_size: int = 0  # >0 → sliding-window attention (Mistral-style)
    rope_theta: float = 10000.0
    dtype: str = "bfloat16"  # compute dtype
    # at-rest dtype of the big matmul weights ("" → same as `dtype`): bf16
    # at rest halves weight HBM traffic on every read; training keeps an
    # fp32 master copy in the optimizer state (models/train.py MasterState)
    params_dtype: str = ""
    remat: bool = False
    use_ring_attention: bool = False  # sequence parallelism (needs mesh)
    n_experts: int = 0  # >0 → MoE FFN (models/moe.py), expert-parallel
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    n_microbatches: int = 0  # >0 + mesh pipe>1 → pipeline parallelism
    # >0 → training CE is computed in this many vocab chunks and the
    # (B, S, V) logits never materialize (ops/xent.py); inference paths
    # (forward/generate/serving) are unaffected.  Composes with tensor>1
    # (per-rank scan over the V-sharded unembed, ops/xent.py
    # chunked_softmax_xent_tp); must be a multiple of the tensor size.
    xent_chunks: int = 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def rest_dtype(self):
        return jnp.dtype(self.params_dtype or self.dtype)


# -- init --------------------------------------------------------------------


def init_params(key: jax.Array, cfg: TransformerConfig) -> dict:
    D, H, F, L, V = cfg.d_model, cfg.n_heads * cfg.head_dim, cfg.d_ff, cfg.n_layers, cfg.vocab_size
    KV = cfg.kv_heads * cfg.head_dim
    k = iter(jax.random.split(key, 16))

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * fan_in ** -0.5)

    layers = {
        "attn_norm": jnp.ones((L, D), jnp.float32),
        "wq": dense(next(k), (L, D, H), D),
        "wk": dense(next(k), (L, D, KV), D),
        "wv": dense(next(k), (L, D, KV), D),
        "wo": dense(next(k), (L, H, D), H),
        "mlp_norm": jnp.ones((L, D), jnp.float32),
    }
    if cfg.n_experts > 0:
        E = cfg.n_experts
        layers.update({
            "moe_gate": dense(next(k), (L, D, E), D),
            "w_in": dense(next(k), (L, E, D, F), D),
            "w_gate": dense(next(k), (L, E, D, F), D),
            "w_out": dense(next(k), (L, E, F, D), F),
        })
    else:
        layers.update({
            "w_in": dense(next(k), (L, D, F), D),
            "w_gate": dense(next(k), (L, D, F), D),
            "w_out": dense(next(k), (L, F, D), F),
        })
    params = {
        "embed": dense(next(k), (V, D), 1.0),
        "layers": layers,
        "final_norm": jnp.ones((D,), jnp.float32),
        "unembed": dense(next(k), (D, V), D),
    }
    return cast_params_to_rest(params, cfg)


# norm scales and the MoE router stay fp32 (tiny; numerics-sensitive)
_FP32_AT_REST = ("attn_norm", "mlp_norm", "final_norm", "moe_gate")


def cast_params_to_rest(params: dict, cfg: TransformerConfig) -> dict:
    """Cast matmul weights to the at-rest dtype (no-op for float32).  The
    compute path is unchanged — ``wmat`` casts to the compute dtype per use
    either way — but bf16 at rest halves weight HBM bytes per read."""
    pd = cfg.rest_dtype
    if pd == jnp.float32:
        return params

    def walk(tree, name=""):
        if isinstance(tree, dict):
            return {k: walk(v, k) for k, v in tree.items()}
        if name in _FP32_AT_REST or getattr(tree, "dtype", None) != jnp.float32:
            return tree
        return tree.astype(pd)

    return walk(params)


def _embed_lookup(embed, tokens, dtype):
    """Embedding gather; for int8-quantized tables, gather THEN dequantize
    (dequantizing first would materialize the dense (V, D) table)."""
    from .quantize import is_qtensor

    if is_qtensor(embed):
        rows = embed["q8"][tokens].astype(dtype)
        return rows * embed["scale"][0].astype(dtype)
    return embed.astype(dtype)[tokens]


def param_count(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# -- building blocks ---------------------------------------------------------


def _rms_norm_impl(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps) * scale).astype(x.dtype)


# Rematerialized: XLA's plain autodiff SAVES the fp32-upcast activations and
# large fp32 temporaries for the backward and re-reads them — measured
# ~3.5ms/layer of the train step at bench shapes.  Under jax.checkpoint only
# the bf16 input + scale are saved; the backward recomputes the (cheap,
# fully-fused) normalization on the fly.  A hand-written custom_vjp would be
# marginally better still, but breaks shard_map's varying-axes inference for
# the scale gradient (it needs a psum over whatever manual axes are active,
# which a context-free op cannot know); checkpoint composes with every
# manual-sharding region in parallel/.
_rms_norm_remat = jax.checkpoint(_rms_norm_impl)


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-6) -> jax.Array:
    return _rms_norm_remat(x, scale, eps)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: (B, S, H, Dh); positions: (S,)."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # (S, half)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """(B,S,Hkv,Dh) → (B,S,Hkv*n_rep,Dh): expand grouped KV heads for GQA."""
    if n_rep == 1:
        return k
    B, S, Hkv, Dh = k.shape
    return jnp.broadcast_to(
        k[:, :, :, None, :], (B, S, Hkv, n_rep, Dh)
    ).reshape(B, S, Hkv * n_rep, Dh)


def _attention(q, k, v, cfg: TransformerConfig, mesh: Optional[Mesh],
               seq_axis: Optional[str] = None):
    """(B,S,H,Dh) → (B,S,H,Dh), dispatching to ring or flash attention.

    ``seq_axis``: set when already INSIDE a manual region (the pipeline's
    shard_map) whose axis set includes the sequence axis — ring attention is
    then called directly with its manual collectives instead of opening a
    nested shard_map (which jax does not allow)."""
    n_rep = cfg.n_heads // cfg.kv_heads
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    qT = q.transpose(0, 2, 1, 3)  # (B,H,S,Dh)
    kT = k.transpose(0, 2, 1, 3)
    vT = v.transpose(0, 2, 1, 3)
    if seq_axis is not None:
        assert cfg.window_size == 0, "sliding window + ring attention TBD"
        oT = ring_attention(qT, kT, vT, axis_name=seq_axis, causal=True)
    elif cfg.use_ring_attention and mesh is not None:
        assert cfg.window_size == 0, "sliding window + ring attention TBD"
        oT = ring_attention_sharded(qT, kT, vT, mesh, causal=True)
    else:
        oT = flash_attention(qT, kT, vT, True, None, cfg.window_size)
    return oT.transpose(0, 2, 1, 3)


def _proj(h, p, name, dtype):
    """``h @ p[name]``, plus the LoRA adapter term when the layer tree
    carries one (``models/lora.py inject_lora`` adds ``<name>_lora``
    leaves).

    The adapter path is the ACTIVATION-domain formulation
    ``x@W + (x@A)@B·scale`` with the delta added in fp32 BEFORE the cast
    to compute dtype — merging the delta into a bf16 base weight instead
    would round contributions below W's ulp (~0.4% relative) to exactly
    zero for every token, silently stalling early fine-tuning while
    gradients stay nonzero."""
    ad = p.get(name + "_lora") if isinstance(p, dict) else None
    if ad is None:
        return h @ wmat(p[name], dtype)
    y = jnp.dot(h, wmat(p[name], dtype), preferred_element_type=jnp.float32)
    t = jnp.dot(jnp.dot(h.astype(jnp.float32), ad["a"]), ad["b"])
    return (y + t).astype(dtype)


def _layer(x, layer_params, cfg: TransformerConfig, mesh: Optional[Mesh],
           seq_axis: Optional[str] = None):
    """One transformer block. x: (B, S, D).  Returns (x, aux_loss).

    Under ``seq_axis`` (manual sequence sharding), S is the LOCAL shard
    length and rope positions are offset to global coordinates."""
    B, S, D = x.shape
    Hn, Dh = cfg.n_heads, cfg.head_dim
    dtype = jnp.dtype(cfg.dtype)
    p = layer_params

    h = rms_norm(x, p["attn_norm"])
    Hkv = cfg.kv_heads
    q = _proj(h, p, "wq", dtype).reshape(B, S, Hn, Dh)
    k = _proj(h, p, "wk", dtype).reshape(B, S, Hkv, Dh)
    v = _proj(h, p, "wv", dtype).reshape(B, S, Hkv, Dh)
    positions = jnp.arange(S)
    if seq_axis is not None:
        positions = positions + lax.axis_index(seq_axis) * S
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    o = _attention(q, k, v, cfg, mesh, seq_axis).reshape(B, S, Hn * Dh)
    x = x + _proj(o, p, "wo", dtype)

    h = rms_norm(x, p["mlp_norm"])
    if cfg.n_experts > 0:
        from .moe import moe_ffn

        ffn, aux = moe_ffn(
            h, p["moe_gate"], p["w_in"], p["w_gate"], p["w_out"],
            capacity_factor=cfg.capacity_factor, dtype=dtype,
        )
        x = x + ffn
    else:
        gate = jax.nn.silu(_proj(h, p, "w_gate", dtype))
        up = _proj(h, p, "w_in", dtype)
        x = x + _proj(gate * up, p, "w_out", dtype)
        aux = jnp.zeros((), jnp.float32)
    return x, aux


def hidden_with_aux(
    params: dict,
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh: Optional[Mesh] = None,
) -> tuple[jax.Array, jax.Array]:
    """tokens: (B, S) int32 → (final-norm hidden (B, S, D), aux scalar).

    The pre-unembed trunk, split out so the chunked-CE loss path
    (ops/xent.py) can consume hidden states without the logits ever
    existing; ``forward_with_aux`` adds the unembed projection."""
    dtype = jnp.dtype(cfg.dtype)
    x = _embed_lookup(params["embed"], tokens, dtype)  # (B, S, D)

    pipelined = (
        cfg.n_microbatches > 0
        and mesh is not None
        and mesh.shape.get("pipe", 1) > 1
    )
    # sp × pp composition: ring attention's own shard_map cannot NEST inside
    # the pipeline's, so when both axes are active the pipeline's manual
    # region is widened to {pipe, seq} and the layers call ring attention's
    # manual collectives directly (seq_axis)
    seq_manual = (
        pipelined
        and cfg.use_ring_attention
        and mesh.shape.get("seq", 1) > 1
    )
    layer_fn = functools.partial(
        _layer, cfg=cfg, mesh=None if pipelined else mesh,
        seq_axis="seq" if seq_manual else None,
    )
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)

    if pipelined:
        from ..parallel.pipeline import microbatch, pipeline_apply, unmicrobatch

        xm = microbatch(x, cfg.n_microbatches)
        ym, aux_total = pipeline_apply(
            lambda h, lp: layer_fn(h, lp), params["layers"], xm, mesh,
            seq_axis="seq" if seq_manual else None,
        )
        x = unmicrobatch(ym)
    else:
        def scan_body(x, layer_params):
            x, aux = layer_fn(x, layer_params)
            return x, aux

        x, aux = lax.scan(scan_body, x, params["layers"])
        aux_total = jnp.sum(aux)
    x = rms_norm(x, params["final_norm"])
    return x, aux_total


def forward_with_aux(
    params: dict,
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh: Optional[Mesh] = None,
) -> tuple[jax.Array, jax.Array]:
    """tokens: (B, S) int32 → (logits (B, S, V), aux_loss scalar)."""
    x, aux_total = hidden_with_aux(params, tokens, cfg, mesh)
    logits = x @ wmat(params["unembed"], jnp.dtype(cfg.dtype))
    return logits.astype(jnp.float32), aux_total


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """tokens: (B, S) int32 → logits (B, S, V)."""
    return forward_with_aux(params, tokens, cfg, mesh)[0]
