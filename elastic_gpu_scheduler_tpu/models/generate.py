"""Autoregressive generation with a KV cache.

Decode path of the flagship LM: prefill the cache from the prompt with the
batched forward, then one-token-at-a-time decode steps.  TPU-first: static
cache shape (max_len), ``lax.dynamic_update_slice`` writes, position-masked
attention — no dynamic shapes anywhere, so the step function jits once.

No reference analogue (SURVEY §2 #19); workload-plane completeness.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import NEG_INF
from .quantize import wmat
from .transformer import TransformerConfig, _embed_lookup, rms_norm, rope


class KVCache(NamedTuple):
    k: jax.Array  # (L, B, max_len, Hkv, Dh)
    v: jax.Array  # (L, B, max_len, Hkv, Dh)
    length: jax.Array  # () int32 — valid prefix length

    @classmethod
    def empty(cls, cfg: TransformerConfig, batch: int, max_len: int) -> "KVCache":
        shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
        dtype = jnp.dtype(cfg.dtype)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.zeros((), jnp.int32),
        )


def cached_attention(q, cache_k, cache_v, lengths, window=0):
    """Single-position attention against a (possibly grouped) KV cache.

    q: (B, 1, H, Dh); cache: (B, max_len, Hkv, Dh) with Hkv dividing H —
    GQA is handled by a grouped einsum (no cache expansion: the whole point
    of GQA's decode bandwidth win).  ``lengths``: scalar or (B,) per-slot
    positions; ``window`` > 0 applies sliding-window masking.
    """
    B, _, Hn, Dh = q.shape
    M = cache_k.shape[1]
    Hkv = cache_k.shape[2]
    n_rep = Hn // Hkv
    scale = Dh**-0.5
    qg = (
        q.transpose(0, 2, 1, 3)
        .reshape(B, Hkv, n_rep, Dh)
        .astype(jnp.float32)
    )
    kT = cache_k.transpose(0, 2, 1, 3).astype(jnp.float32)  # (B,Hkv,M,Dh)
    vT = cache_v.transpose(0, 2, 1, 3).astype(jnp.float32)
    s = jnp.einsum("bgrd,bgkd->bgrk", qg, kT) * scale  # (B,Hkv,n_rep,M)
    lengths = jnp.asarray(lengths)
    if lengths.ndim == 0:
        lengths = lengths[None]
    lb = lengths[:, None, None, None]  # (B,1,1,1)
    positions = jnp.arange(M)[None, None, None, :]
    keep = positions <= lb
    if window > 0:
        keep = keep & (lb - positions < window)
    s = jnp.where(keep, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgrk,bgkd->bgrd", p, vT)  # (B,Hkv,n_rep,Dh)
    return o.reshape(B, Hn, 1, Dh).transpose(0, 2, 1, 3).astype(q.dtype)


def decode_step(
    params: dict, token: jax.Array, cache: KVCache, cfg: TransformerConfig
) -> tuple[jax.Array, KVCache]:
    """token: (B,) int32 at position cache.length → (logits (B,V), cache').

    The T=1 case of ``forward_cached`` — one transformer-layer body exists
    for decode, prefill, and speculative verification, so the three paths
    cannot drift apart."""
    logits, cache = forward_cached(params, token[:, None], cache, cfg)
    return logits[:, 0, :], cache


def sample_token(
    logits: jax.Array,
    temperature: float,
    key: jax.Array,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """(B, V) logits → (B,) tokens; greedy when temperature == 0.  All
    sampling params are static — see models/sampling.py for semantics."""
    from .sampling import sample_static

    return sample_static(
        logits, key, temperature=temperature, top_k=top_k, top_p=top_p
    )


def decode_loop(
    params: dict,
    logits: jax.Array,  # (B, V) logits for the NEXT position
    cache: KVCache,
    cfg: TransformerConfig,
    n_steps: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    top_k: int = 0,
    top_p: float = 1.0,
) -> tuple[jax.Array, jax.Array, KVCache]:
    """``n_steps`` fused decode steps in ONE ``lax.scan`` — one device
    dispatch per K tokens instead of per token (sampling happens inside the
    scan, so the host never sees intermediate logits).  Returns
    (tokens (B, n_steps), final logits (B, V), cache').

    This is the decode-throughput fix for dispatch-bound serving: a single
    jitted call amortizes the host→device relay cost over K tokens
    (VERDICT r1 #4).  Token-for-token identical to calling ``decode_step``
    + sampling in a host loop with the same key schedule."""
    if key is None:
        key = jax.random.key(0)

    def body(carry, _):
        logits, cache, key = carry
        key, sub = jax.random.split(key)
        token = sample_token(logits, temperature, sub, top_k=top_k, top_p=top_p)
        logits, cache = decode_step(params, token, cache, cfg)
        return (logits, cache, key), token

    (logits, cache, _), tokens = lax.scan(
        body, (logits, cache, key), None, length=n_steps
    )
    return tokens.T, logits, cache  # (B, n_steps)


def cached_attention_multi(q, cache_k, cache_v, start, window=0):
    """T-position attention against the cache (the multi-token
    generalization of ``cached_attention``).

    q: (B, T, H, Dh) — queries at positions start..start+T-1; cache:
    (B, M, Hkv, Dh) with the same T new K/V rows already written at those
    positions.  Causal: query i sees key j iff j <= start + i.

    On TPU this can run through the Pallas blockwise-stats kernel — no
    (T, M) score matrix in HBM; rows past the written prefix are excluded
    by the causal mask (they all sit above every query position).  The
    kernel keeps the full K/V VMEM-resident per program, so the fast path
    is gated to: no window, MHA (a GQA cache would have to be expanded,
    forfeiting its bandwidth win), kernel-divisible T (≤128 or a multiple
    of 128), M a multiple of 128, and K/V fitting the VMEM budget.
    Everything else takes the einsum path with O(T·M) score memory;
    callers keep T a bounded block (prefill chunks, speculative draft
    windows) either way.
    """
    B, T, Hn, Dh = q.shape
    M = cache_k.shape[1]
    Hkv = cache_k.shape[2]
    n_rep = Hn // Hkv
    scale = Dh**-0.5
    from ..ops.attention import RESIDENT_VMEM_BYTES, _use_pallas

    t_ok = (T <= 128 and T % 8 == 0) or T % 128 == 0
    vmem_ok = (
        2 * Hn * M * Dh * jnp.dtype(cache_k.dtype).itemsize
        <= RESIDENT_VMEM_BYTES
    )
    if (
        window == 0
        and n_rep == 1
        and t_ok
        and M % 128 == 0
        and vmem_ok
        and _use_pallas()
    ):
        return _cached_attention_multi_flash(q, cache_k, cache_v, start)
    qg = (
        q.reshape(B, T, Hkv, n_rep, Dh)
        .transpose(0, 2, 3, 1, 4)
        .astype(jnp.float32)
    )  # (B, Hkv, n_rep, T, Dh)
    kT = cache_k.transpose(0, 2, 1, 3).astype(jnp.float32)  # (B,Hkv,M,Dh)
    vT = cache_v.transpose(0, 2, 1, 3).astype(jnp.float32)
    s = jnp.einsum("bgrtd,bgkd->bgrtk", qg, kT) * scale  # (B,Hkv,n_rep,T,M)
    qpos = start + jnp.arange(T)  # (T,)
    kpos = jnp.arange(M)  # (M,)
    keep = kpos[None, :] <= qpos[:, None]  # (T, M)
    if window > 0:
        keep = keep & ((qpos[:, None] - kpos[None, :]) < window)
    s = jnp.where(keep[None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgrtk,bgkd->bgrtd", p, vT)  # (B,Hkv,n_rep,T,Dh)
    return (
        o.transpose(0, 3, 1, 2, 4).reshape(B, T, Hn, Dh).astype(q.dtype)
    )


def _cached_attention_multi_flash(q, cache_k, cache_v, start,
                                  interpret=False):
    """Flash-style path for ``cached_attention_multi`` (MHA only): the
    ring-attention stats kernel already takes explicit global q/k offsets,
    which is exactly the cache-prefix geometry (queries at start.., keys
    at 0..)."""
    from ..ops.attention import flash_block_stats

    qT = q.transpose(0, 2, 1, 3)  # (B, H, T, Dh)
    kT = cache_k.transpose(0, 2, 1, 3)  # (B, H, M, Dh)
    vT = cache_v.transpose(0, 2, 1, 3)
    pv, m, l = flash_block_stats(
        qT, kT, vT, start, 0, causal=True, interpret=interpret
    )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (pv / l_safe[..., None]).astype(q.dtype)  # (B, H, T, Dh)
    return out.transpose(0, 2, 1, 3)


def forward_cached(
    params: dict, tokens: jax.Array, cache: KVCache, cfg: TransformerConfig
) -> tuple[jax.Array, KVCache]:
    """Multi-token cached forward: process T tokens starting at position
    ``cache.length`` in ONE pass, returning logits for every position.

    tokens: (B, T) → (logits (B, T, V), cache at length+T).  This is the
    device-FLOP-efficient primitive behind batched prefill (T = prompt
    length) and speculative verification (T = draft block): one wide pass
    instead of T sequential decode steps.
    """
    dtype = jnp.dtype(cfg.dtype)
    B, T = tokens.shape
    Hn, Dh = cfg.n_heads, cfg.head_dim
    x = _embed_lookup(params["embed"], tokens, dtype)  # (B, T, D)
    pos0 = cache.length
    positions = pos0 + jnp.arange(T)

    def layer_step(x, scanned):
        p, ck, cv = scanned  # ck/cv: (B, M, Hkv, Dh)
        h = rms_norm(x, p["attn_norm"])
        Hkv = cfg.kv_heads
        q = (h @ wmat(p["wq"], dtype)).reshape(B, T, Hn, Dh)
        k = (h @ wmat(p["wk"], dtype)).reshape(B, T, Hkv, Dh)
        v = (h @ wmat(p["wv"], dtype)).reshape(B, T, Hkv, Dh)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        ck = lax.dynamic_update_slice(ck, k, (0, pos0, 0, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, pos0, 0, 0))
        o = cached_attention_multi(
            q, ck, cv, pos0, window=cfg.window_size
        ).reshape(B, T, Hn * Dh)
        x = x + (o @ wmat(p["wo"], dtype))
        h = rms_norm(x, p["mlp_norm"])
        if cfg.n_experts > 0:
            from .moe import moe_ffn

            ffn, _ = moe_ffn(
                h, p["moe_gate"], p["w_in"], p["w_gate"], p["w_out"],
                capacity_factor=cfg.capacity_factor, dtype=dtype,
            )
            x = x + ffn
        else:
            gate = jax.nn.silu(h @ wmat(p["w_gate"], dtype))
            up = h @ wmat(p["w_in"], dtype)
            x = x + ((gate * up) @ wmat(p["w_out"], dtype))
        return x, (ck, cv)

    x, (new_k, new_v) = lax.scan(
        layer_step, x, (params["layers"], cache.k, cache.v)
    )
    x = rms_norm(x, params["final_norm"])
    logits = x @ wmat(params["unembed"], dtype)  # (B, T, V)
    return logits.astype(jnp.float32), KVCache(new_k, new_v, pos0 + T)


def prefill(
    params: dict, tokens: jax.Array, cache: KVCache, cfg: TransformerConfig,
    chunk: int = 512,
) -> tuple[jax.Array, KVCache]:
    """Chunked batched prefill: the prompt in ceil(S/chunk) multi-token
    passes instead of one decode step per token — wide MXU matmuls, and the
    O(T·M) attention-score memory stays bounded by the chunk size.

    tokens: (B, S) → (last-position logits (B, V), cache at length S)."""
    S = tokens.shape[1]
    logits = None
    for s0 in range(0, S, chunk):
        logits, cache = forward_cached(
            params, tokens[:, s0 : s0 + chunk], cache, cfg
        )
    return logits[:, -1, :], cache


def prefill_sequential(
    params: dict, tokens: jax.Array, cache: KVCache, cfg: TransformerConfig
) -> tuple[jax.Array, KVCache]:
    """Token-at-a-time prefill (the decode_step path) — kept as the
    equivalence oracle for ``prefill``."""

    def body(carry, tok):
        cache = carry
        logits, cache = decode_step(params, tok, cache, cfg)
        return cache, logits

    cache, logits_seq = lax.scan(body, cache, tokens.T)
    return logits_seq[-1], cache


def generate(
    params: dict,
    prompt: jax.Array,  # (B, S) int32
    cfg: TransformerConfig,
    max_new_tokens: int,
    max_len: int = 0,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: Optional[int] = None,
) -> jax.Array:
    """Greedy (temperature=0) or sampled generation; returns (B, S+new).

    Decode is FUSED: all ``max_new_tokens`` steps run in one jitted
    ``decode_loop`` scan — one device dispatch for the whole generation
    phase rather than one per token.  With ``eos_id`` set, every position
    after a row's first EOS is overwritten WITH ``eos_id`` (fixed-shape
    padding — the fused scan still runs all steps; per-row early exit is
    the serving engine's job, models/serving.py stop_tokens)."""
    B, S = prompt.shape
    max_len = max_len or S + max_new_tokens
    cache = KVCache.empty(cfg, B, max_len)
    logits, cache = prefill(params, prompt, cache, cfg)
    if key is None:
        key = jax.random.key(0)

    loop_fn = jax.jit(
        functools.partial(
            decode_loop, cfg=cfg, n_steps=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
        )
    )
    tokens, _, _ = loop_fn(params, logits, cache, key=key)
    if eos_id is not None:
        seen = jnp.cumsum((tokens == eos_id).astype(jnp.int32), axis=1)
        after_eos = (seen - (tokens == eos_id).astype(jnp.int32)) > 0
        tokens = jnp.where(after_eos, eos_id, tokens)
    return jnp.concatenate([prompt, tokens], axis=1)
