"""Journal shipping: the leader serves its journal as a resumable
stream; a follower replays it into live state (the warm-standby half of
the HA control plane).

Before this module, ``--leader-elect`` standbys started COLD: a new
leader rebuilt all state from the annotation ledger — one ``get_node``
plus one ``list_pods`` per materialized node, then an option replay per
pod, then index/profile warm-up — a full resync on every failover
(ROADMAP item 2's availability gap).  The journal is already the source
of truth (snapshot+log, deterministic replay); shipping it makes the
standby's state CURRENT before the leader dies:

- **Server** (``stream_since``, mounted at ``GET /journal/stream`` on
  the scheduler server): serves sealed segments plus a long-polled live
  tail in the journal's own wire format (CRC per record — the follower
  trusts bytes by exactly the same rule a segment reader does).
  ``from_seq`` resumes mid-stream; ``from_seq=0`` serves from the oldest
  segment INCLUDING its head checkpoint, so a fresh follower boots the
  same way a pruned-prefix replay does.  A response never splits a
  record (records are serialized lines), but a fault-injected or
  network-cut TORN TAIL is detected by the follower's CRC check and
  simply re-requested — resume-from-seq makes the stream idempotent.

- **Follower** (``JournalFollower``, CLI ``--follow <leader-url>``):
  long-polls the stream and feeds each record through the incremental
  ``ReplayEngine`` — live ChipSet + pod ledger + generations, the state
  ``scheduler/ha.warm_takeover`` swaps in on ``on_started_leading``.
  Lag is exported as ``tpu_ha_follow_lag_seqs`` / ``_seconds``.  A SEQ
  GAP (records lost between leader and follower — pruned past our
  position, or a writer drop) HARD-FAILS the follower: a standby whose
  state silently skipped mutations would take over with a corrupt
  ledger, which is strictly worse than a cold start.  Transport errors
  (leader restarting, partitions) are NOT gaps: the follower backs off
  (``utils/backoff``) and resumes from its last applied seq.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from ..faultinject import FAULTS
from ..metrics import HA_FOLLOW_LAG_SECONDS, HA_FOLLOW_LAG_SEQS
from ..utils.backoff import Backoff
from . import _encode, parse_records, read_segment, segment_paths
from .replay import ReplayEngine

log = logging.getLogger("tpu-scheduler")

__all__ = ["JournalFollower", "stream_since", "segment_first_seq"]

# one shipping response is bounded so a follower far behind catches up
# in chunks instead of buffering the whole journal in one HTTP body
DEFAULT_MAX_BYTES = 4 << 20


def segment_first_seq(path: str) -> Optional[int]:
    """The first sequence number a segment CONTRIBUTES: its first
    seq-bearing record, or (for a segment headed by a checkpoint)
    ``as_of_seq + 1``.  None for an unreadable/empty segment.  Reads at
    most the head of the file — the stream server uses this to skip
    whole segments below ``from_seq`` without parsing them."""
    try:
        with open(path, "rb") as f:
            head = f.read(1 << 20)
    except OSError:
        return None
    recs, _torn, _good = parse_records(head)
    for rec in recs:
        if "seq" in rec:
            return rec["seq"]
        if rec.get("type") == "checkpoint":
            return int(rec.get("as_of_seq", -1)) + 1
    return None


def stream_since(
    journal,
    from_seq: int,
    max_bytes: int = DEFAULT_MAX_BYTES,
    wait_s: float = 0.0,
) -> tuple[bytes, int]:
    """Encode every available record with ``seq >= from_seq`` (plus the
    boot checkpoint when serving from the journal's head) in the wire
    format, up to ``max_bytes``.  Long-poll: with ``wait_s`` > 0 and
    nothing new, parks until a record lands or the wait expires.
    Returns ``(payload, last_seq)`` — ``last_seq`` is the newest seq the
    LEADER has assigned (the follower's lag numerator), not the newest
    in the payload."""
    if FAULTS.enabled:
        FAULTS.maybe_fire("ship.stream")
    deadline = time.monotonic() + max(0.0, wait_s)
    while True:
        # cheap in-memory guard first: a caught-up follower's long poll
        # must park on the assigned-seq counter, not re-read and
        # CRC-parse the live segment from disk every 50ms (that was
        # continuous wasted I/O per idle follower).  last_seq() >=
        # from_seq is necessary for _collect to return anything —
        # assigned-but-unflushed records just mean one more 50ms lap.
        if journal.last_seq() >= from_seq:
            payload = _collect(journal, from_seq, max_bytes)
            if payload:
                return payload, journal.last_seq()
        if time.monotonic() >= deadline:
            return b"", journal.last_seq()
        # the writer flushes batches within its 100ms poll; half that
        # keeps tail latency low without busy-spinning the handler
        time.sleep(0.05)


def _collect(journal, from_seq: int, max_bytes: int) -> bytes:
    dirpath = journal.dir
    if not dirpath:
        return b""
    out: list[bytes] = []
    size = 0
    served_any = False
    paths = segment_paths(dirpath)
    for i, path in enumerate(paths):
        if not served_any and i + 1 < len(paths):
            # skip whole segments strictly below from_seq (the NEXT
            # segment's first seq tells us this one contributes nothing)
            nxt = segment_first_seq(paths[i + 1])
            if nxt is not None and nxt <= from_seq:
                continue
        recs, torn, _good = read_segment(path)
        for rec in recs:
            seq = rec.get("seq")
            if seq is None:
                # checkpoint: ship it only when it carries state the
                # follower does not already cover (as_of >= from_seq —
                # the boot-after-prune case); a caught-up follower must
                # NOT be re-sent the head checkpoint every poll
                if rec.get("type") != "checkpoint":
                    continue
                if served_any or int(rec.get("as_of_seq", -1)) < from_seq:
                    continue
            elif seq < from_seq:
                continue
            line = _encode(rec)
            if size + len(line) > max_bytes and served_any:
                return b"".join(out)
            out.append(line)
            size += len(line)
            served_any = True
        if torn:
            break  # nothing after a tear has continuity
    return b"".join(out)


class JournalFollower:
    """Continuously replay a leader's journal stream into live state.

    States: ``following`` (healthy; transport errors retry under
    backoff), ``failed`` (seq gap — HARD stop, see module docstring),
    ``stopped``.  ``engine.result`` holds the replayed ChipSets/pods —
    read it only after ``stop()`` (the poll thread mutates it)."""

    def __init__(
        self,
        leader_url: str,
        wait_s: float = 10.0,
        timeout_s: float = 30.0,
        max_bytes: int = DEFAULT_MAX_BYTES,
        backoff: Optional[Backoff] = None,
    ):
        self.leader_url = leader_url.rstrip("/")
        self.wait_s = max(0.0, float(wait_s))
        self.timeout_s = max(self.wait_s + 5.0, float(timeout_s))
        self.max_bytes = max_bytes
        self.backoff = backoff if backoff is not None else Backoff(
            base_s=0.2, max_s=10.0
        )
        self.engine = ReplayEngine()
        self.state = "init"
        self.error: Optional[str] = None
        self.leader_last_seq = -1
        self.last_applied_t: Optional[float] = None  # record wall clock
        self.polls = 0
        self.records_applied = 0
        self.transport_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lag -----------------------------------------------------------------

    @property
    def applied_seq(self) -> int:
        return self.engine.result.last_seq

    def lag_seqs(self) -> int:
        return max(0, self.leader_last_seq - self.applied_seq)

    def lag_seconds(self) -> float:
        if self.lag_seqs() == 0 or self.last_applied_t is None:
            return 0.0
        return max(0.0, time.time() - self.last_applied_t)

    def _export_lag(self) -> None:
        HA_FOLLOW_LAG_SEQS.set(value=float(self.lag_seqs()))
        HA_FOLLOW_LAG_SECONDS.set(value=round(self.lag_seconds(), 3))

    # -- polling -------------------------------------------------------------

    def poll_once(self, wait_s: Optional[float] = None) -> int:
        """One stream request; returns records applied.  Raises OSError
        on transport failure (the loop backs off), RuntimeError on a seq
        gap (the loop hard-fails)."""
        if FAULTS.enabled:
            FAULTS.maybe_fire("ship.follow")
        from_seq = self.applied_seq + 1
        q = urllib.parse.urlencode({
            "from_seq": from_seq,
            "wait_s": self.wait_s if wait_s is None else wait_s,
            "max_bytes": self.max_bytes,
        })
        url = f"{self.leader_url}/journal/stream?{q}"
        req = urllib.request.Request(url)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                data = resp.read()
                leader_seq = resp.headers.get("X-Journal-Last-Seq")
        except urllib.error.HTTPError as e:
            raise OSError(f"/journal/stream -> {e.code}") from None
        self.polls += 1
        if leader_seq is not None:
            try:
                self.leader_last_seq = int(leader_seq)
            except ValueError:
                pass
            else:
                if self.leader_last_seq < self.applied_seq:
                    # seq REGRESSION: the leader restarted with a
                    # fresh/wiped journal (new incarnation, seqs from
                    # 0).  Applying its records on top of the previous
                    # incarnation's state would merge two histories
                    # into one standby ledger — hard-fail, like a gap
                    self.state = "failed"
                    self.error = (
                        f"seq regression: applied up to "
                        f"{self.applied_seq} but the leader's journal "
                        f"only reaches {self.leader_last_seq} — the "
                        "leader restarted with a new journal; restart "
                        "this follower to re-replay the new stream"
                    )
                    raise RuntimeError(self.error)
        recs, torn, _good = parse_records(data)
        if torn:
            # a cut/injected tear: everything before it is trusted, the
            # torn record is NOT applied — the next poll re-requests it
            # by seq (idempotent resume; never a gap)
            log.warning(
                "journal follower: torn tail in stream response "
                "(%d clean records kept); re-requesting", len(recs),
            )
        applied = 0
        for rec in recs:
            seq = rec.get("seq")
            if seq is not None:
                expected = self.engine.next_seq()
                if expected is not None and seq < expected:
                    continue  # server overlap on resume — already applied
                if expected is not None and seq > expected:
                    self.state = "failed"
                    self.error = (
                        f"seq gap: expected {expected}, stream produced "
                        f"{seq} — records lost between leader and "
                        "follower (journal pruned past this follower, or "
                        "writer drops); a silent skip would corrupt the "
                        "standby ledger, refusing to follow"
                    )
                    raise RuntimeError(self.error)
            self.engine.apply(rec)
            if rec.get("t") is not None:
                try:
                    self.last_applied_t = float(rec["t"])
                except (TypeError, ValueError):
                    pass
            if seq is not None:
                # only seq-bearing records count as PROGRESS: a shipped
                # checkpoint the engine ignored must never make a
                # drain-until-idle loop believe the stream still moves
                applied += 1
        self.records_applied += applied
        self._export_lag()
        return applied

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
                self.backoff.reset()
                self.state = "following"
                self.error = None
            except RuntimeError:
                return  # seq gap: state/error already set; HARD stop
            except Exception as e:
                # transport: leader restarting / partition / injected
                # fault — resume from applied_seq under jittered backoff
                self.transport_errors += 1
                self.error = f"transport: {e}"
                self._export_lag()
                delay = self.backoff.next_delay()
                if self._stop.wait(delay):
                    return

    def start(self) -> "JournalFollower":
        if self._thread is not None:
            return self
        self._stop.clear()
        self.state = "following"
        self._thread = threading.Thread(
            target=self._run, name="journal-follower", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.timeout_s + 5)
        if self.state != "failed":
            self.state = "stopped"

    # -- introspection (/debug/leader) ---------------------------------------

    def debug_state(self) -> dict:
        res = self.engine.result
        return {
            "leader_url": self.leader_url,
            "state": self.state,
            "error": self.error,
            "applied_seq": self.applied_seq,
            "leader_last_seq": self.leader_last_seq,
            "lag_seqs": self.lag_seqs(),
            "lag_seconds": round(self.lag_seconds(), 3),
            "records_applied": self.records_applied,
            "polls": self.polls,
            "transport_errors": self.transport_errors,
            "nodes": len(res.nodes),
            "live_pods": len(res.pods),
            "violations": len(res.violations),
        }
