"""Deterministic journal replay: rebuild allocator state, audit invariants.

Consumes the record stream written by ``journal.JOURNAL`` (see the
package docstring for the record taxonomy) and rebuilds per-node
``ChipSet`` state through the SAME transact/cancel commit machinery the
live scheduler uses — so a journal that replays cleanly is a proof that
the recorded mutation sequence never double-booked a chip and never
freed capacity that was not charged.

Three consumers:

- ``replay(events)`` → ``ReplayResult``: the reconstructed state plus
  every invariant violation found while streaming (double-book,
  capacity inflation on free, gang admit without all members bound)
  and the post-conditions checked at the end (per-node capacity
  conservation: chips charged by live pods must equal total - avail).

- ``diff_live(result, status)``: field-by-field diff of the replayed
  state against a live ``/scheduler/status`` snapshot (accepts either
  the endpoint's ``{"schedulers": [...]}`` wrapper or one engine's
  status dict).  Empty diff = the journal and the live allocator agree.

- ``what_if(events, rater)``: replay the recorded workload but let a
  DIFFERENT rater choose each placement — offline placement-policy
  scoring against real recorded demand (the Gavel/Tesserae use case).

HA: ``replay()`` is a thin wrapper over the INCREMENTAL ``ReplayEngine``
(``apply()`` one record at a time) so a warm standby (journal/ship.py's
``JournalFollower``) can keep a live ChipSet + pod ledger current as the
leader's stream arrives, instead of re-running a batch replay per poll.
The engine's state is what ``scheduler/ha.warm_takeover`` swaps into a
scheduler on ``on_started_leading`` — the whole point of shipping the
journal is that this state is ALREADY BUILT when the leader dies.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..core.allocator import (
    ChipSet,
    ContainerAlloc,
    Option,
    Rater,
    option_demand,
)
from ..core.chip import Chip
from ..core.request import NOT_NEEDED, TPURequest, TPUUnit
from ..core.topology import Topology


def option_from_record(rec: dict) -> Option:
    """Inverse of ``journal.option_record``."""
    return Option(
        request_hash=rec.get("hash", ""),
        allocs=tuple(
            ContainerAlloc(
                container=name,
                coords=tuple(tuple(c) for c in coords),
                whole=bool(whole),
                core=int(core),
                hbm=int(hbm),
                contiguous=bool(contiguous),
            )
            for name, coords, whole, core, hbm, contiguous in rec["allocs"]
        ),
        score=float(rec.get("score", 0.0)),
    )


def request_from_option(opt: Option, pod_key: str, pod_uid: str) -> TPURequest:
    """Reconstruct the demand a recorded placement satisfied, so what-if
    replay can re-run the placement search for the same request shape."""
    units = []
    names = []
    for a in opt.allocs:
        names.append(a.container)
        if not a.needs_tpu:
            units.append(TPUUnit(core=NOT_NEEDED))
        elif a.whole:
            units.append(TPUUnit(core=0, hbm=0, chip_count=len(a.coords)))
        else:
            units.append(TPUUnit(core=a.core, hbm=a.hbm))
    return TPURequest(
        pod_uid=pod_uid or f"replay-{pod_key}",
        pod_key=pod_key,
        units=tuple(units),
        container_names=tuple(names),
    )


@dataclass
class _LivePod:
    node: str
    option: Option
    uid: str = ""
    gang: str = ""
    seq: int = -1
    # False after a reset-resync wiped the node's chip usage while the
    # scheduler ledger kept the pod: the pod is live but charges nothing
    charged: bool = True


@dataclass
class ReplayResult:
    records: int = 0
    last_seq: int = -1
    nodes: dict = field(default_factory=dict)  # node → ChipSet
    # node → TPU generation (node_add/node_resync records carry it) —
    # what the offline capacity-index rebuild keys its buckets by
    generations: dict = field(default_factory=dict)
    pods: dict = field(default_factory=dict)  # pod key → _LivePod
    gangs: dict = field(default_factory=dict)  # gang → {"admits","rollbacks"}
    violations: list = field(default_factory=list)
    warnings: list = field(default_factory=list)
    # workload-profile annotations (profile/ observatory): counted and
    # the latest kept — they never mutate allocator state
    profiles: int = 0
    last_profile: Optional[dict] = None
    # fleet-autoscaler evaluations (fleet/ subsystem): annotations like
    # profiles — counted, dense-seq audited, zero allocator mutation.
    # The stream is what fleet.autoscaler.score_policy replays offline.
    fleet_records: int = 0
    last_fleet: Optional[dict] = None
    # gang resize transactions verified (each checked against the chip-
    # conservation and membership all-or-nothing invariants)
    resizes: int = 0
    # compile warm-up annotations (compilecache/): lattice size + fill
    # time per pod boot — counted, dense-seq audited, zero allocator
    # mutation; the latest kept so offline consumers can see when a
    # replica last became warm (and whether it filled or loaded)
    warmup_records: int = 0
    last_warmup: Optional[dict] = None
    # policy-plane annotations (policy/ subsystem): lifecycle events
    # (load/gate/canary/promote/rollback) + canary bind decisions, and
    # runtime faults.  ``policy_decisions`` rebuilds WHICH policy (and
    # which canary arm) decided every journaled canary bind — the
    # replay-reconstructs-every-decision guarantee check-policy gates.
    policy_records: int = 0
    policy_faults: int = 0
    last_policy: Optional[dict] = None
    policy_decisions: dict = field(default_factory=dict)  # pod → decision
    # HA takeover annotations (scheduler/ha.py): a new leader journaled
    # that it adopted a follower's replayed state and diff-resynced
    # against the annotation ledger — counted, dense-seq audited, zero
    # allocator mutation (the adopted state's mutations were journaled
    # by the PREVIOUS leader; this leader's own journal opens with a
    # boot checkpoint)
    ha_takeovers: int = 0
    last_takeover: Optional[dict] = None
    # live KV-session migration annotations (fleet/ disaggregated data
    # plane): the autoscaler/router journals every commanded session
    # hop (shed or scale-down rebalance) — counted, dense-seq audited,
    # zero allocator mutation (the KV pages move between serving
    # replicas, not between scheduler-plane chips)
    kv_migrations: int = 0
    kv_migrations_failed: int = 0
    last_kv_migration: Optional[dict] = None
    # SLO-plane annotations (slo/): objective loads and burn-rate
    # breach/recovery transitions — counted, dense-seq audited, zero
    # allocator mutation.  Breach records carry exemplar trace ids so
    # an offline audit can name the concrete journeys behind an alert;
    # the latest breach is kept for the replay CLI / check-slo gate.
    slo_records: int = 0
    slo_breaches: int = 0
    last_slo_breach: Optional[dict] = None
    # digital-twin annotations (twin/): scenario metadata stamped at the
    # head/tail of a twin journal (seed, scenario, workload model,
    # scores) — counted, dense-seq audited, zero allocator mutation.
    # Their presence marks a journal as SIMULATED: tooling must never
    # mistake a twin journal for a live flight recording.
    twin_records: int = 0
    last_twin: Optional[dict] = None
    # federated cross-shard gang transactions (federation/): THIS shard's
    # view of each two-phase admission — phases in stream order plus the
    # local member set, keyed by txn id.  Each phase record is audited
    # in place (prepare/commit ⇒ local members bound; abort ⇒ none), and
    # conservation_violations() flags any txn whose last local phase is
    # still "prepare" (an unresolved reservation: the shard died between
    # phase 1 and the decision, and recovery never compensated it).  The
    # CROSS-shard agreement audit — every participant reaching the same
    # terminal phase — folds these views across shard journals
    # (federation.audit / the journal CLI's --dir-of-dirs mode).
    fed_gang_records: int = 0
    fed_gangs: dict = field(default_factory=dict)  # txn → view dict

    def summary(self) -> dict:
        # fragmentation derived from the REPLAYED chip state — the same
        # numbers /metrics computes live, available offline at whatever
        # seq the replay stopped at
        frag = {}
        for node, cs in sorted(self.nodes.items()):
            fi, largest, free_n = cs.fragmentation()
            frag[node] = {
                "index": fi, "largest_free_box": largest,
                "free_chips": free_n,
            }
        return {
            "records": self.records,
            "last_seq": self.last_seq,
            "nodes": len(self.nodes),
            "live_pods": len(self.pods),
            "fragmentation": frag,
            "gangs": {
                g: dict(v) for g, v in sorted(self.gangs.items())
            },
            "profile_records": self.profiles,
            "fleet_records": self.fleet_records,
            "resizes": self.resizes,
            "warmup_records": self.warmup_records,
            "policy_records": self.policy_records,
            "policy_faults": self.policy_faults,
            "policy_decisions": len(self.policy_decisions),
            "ha_takeovers": self.ha_takeovers,
            "kv_migrations": self.kv_migrations,
            "kv_migrations_failed": self.kv_migrations_failed,
            "slo_records": self.slo_records,
            "slo_breaches": self.slo_breaches,
            "twin_records": self.twin_records,
            "fed_gang_records": self.fed_gang_records,
            "fed_gangs": {
                txn: {
                    "gang": v.get("gang"),
                    "phases": list(v.get("phases", [])),
                    "members": list(v.get("members", [])),
                    "shards": list(v.get("shards", [])),
                }
                for txn, v in sorted(self.fed_gangs.items())
            },
            "violations": list(self.violations),
            "warnings": list(self.warnings),
        }

    def index_snapshot(self) -> dict:
        """Rebuild the capacity index's comparable entry set from the
        REPLAYED chip state — the same derivation the live index uses
        (core/index.entry_from_chips), so
        ``replay(events).index_snapshot() == sched.index.snapshot()``
        whenever the journal captured every mutation.  The
        check-cluster-scale gate hard-fails on any diff."""
        from ..core.index import entry_from_chips

        return {
            node: entry_from_chips(
                node, self.generations.get(node, "v5e"), cs
            ).snapshot()
            for node, cs in sorted(self.nodes.items())
        }


def _chipset_from_record(rec: dict) -> ChipSet:
    topo = Topology(tuple(rec["dims"]), tuple(bool(w) for w in rec["wrap"]))
    return ChipSet(topo, [Chip.from_record(c) for c in rec["chips"]])


# public alias: the digital twin (twin/) rebuilds a recorded fleet's
# node ChipSets from node_add records through the same decoder replay
# uses, so a twin fleet can never diverge from what replay would build
chipset_from_record = _chipset_from_record


def _boot_from_checkpoint(rec: dict, res: ReplayResult) -> None:
    """Initialize replay state from a segment-head snapshot (the journal's
    prefix was pruned; this snapshot stands in for it)."""
    for name, inv in (rec.get("nodes") or {}).items():
        try:
            res.nodes[name] = _chipset_from_record(inv)
            if inv.get("generation"):
                res.generations[name] = inv["generation"]
        except Exception as e:
            res.violations.append(f"checkpoint: bad node {name}: {e}")
    for p in rec.get("pods") or []:
        try:
            opt = option_from_record(p["option"])
        except Exception as e:
            res.violations.append(
                f"checkpoint: bad pod option {p.get('pod')}: {e}"
            )
            continue
        cs = res.nodes.get(p.get("node"))
        if cs is None or not cs.can_transact(opt):
            res.violations.append(
                f"checkpoint: pod {p.get('pod')} does not fit its node "
                f"{p.get('node')} — snapshot is internally inconsistent"
            )
            continue
        cs.transact(opt)
        res.pods[p["pod"]] = _LivePod(
            node=p["node"], option=opt, uid=p.get("uid", ""),
            gang=p.get("gang", "") or "",
        )


class ReplayEngine:
    """Incremental replay: ``apply()`` one record at a time into a live
    ``ReplayResult``.  ``replay()`` below wraps it for batch callers;
    the journal-shipping follower (journal/ship.py) feeds it the
    leader's stream as it arrives, keeping a warm standby's state
    CURRENT instead of re-replaying the whole journal per poll.

    Every anomaly is collected in ``result.violations``, never raised —
    a corrupt journal must yield a report, not a traceback.
    ``conservation_violations()`` runs the end-of-stream post-conditions
    on demand (a follower checks them at takeover, not per record)."""

    def __init__(self):
        self.result = ReplayResult()
        self._expected_seq: Optional[int] = None
        self._booted_from_checkpoint = False
        self._boot_as_of = -1

    def next_seq(self) -> Optional[int]:
        """The sequence number the stream should produce next (None
        before anything seq-bearing — or a checkpoint boot — arrived).
        The shipping follower keys its dedup/gap decisions off this, so
        they stay correct across a checkpoint boot (where ``last_seq``
        is still -1 but the snapshot already covers a prefix)."""
        return self._expected_seq

    def apply(self, rec: dict) -> None:
        res = self.result
        res.records += 1
        t = rec.get("type")
        if t == "checkpoint":
            # segment-head state snapshot (no seq — outside the mutation
            # stream).  Mid-stream copies are redundant re-assertions;
            # the FIRST record being one means the prefix was pruned and
            # this snapshot is the boot state.
            if self._expected_seq is None and not res.nodes and not res.pods:
                _boot_from_checkpoint(rec, res)
                self._booted_from_checkpoint = True
                self._boot_as_of = rec.get("as_of_seq", -1)
                if self._boot_as_of >= 0:
                    # the dense-seq audit must hold ACROSS the boot
                    # boundary too: the first applied record is as_of+1
                    # unless something was lost
                    self._expected_seq = self._boot_as_of + 1
            return
        seq = rec.get("seq", -1)
        if self._booted_from_checkpoint and seq <= self._boot_as_of:
            # appended before the boot snapshot → its mutation is already
            # inside the checkpoint; re-applying would double-book (bind)
            # or double-free (forget)
            return
        if self._expected_seq is None:
            if seq > 0 and not self._booted_from_checkpoint:
                res.violations.append(
                    f"journal starts mid-stream at seq {seq} with no "
                    "checkpoint — prefix pruned/lost; state cannot be "
                    "reconstructed"
                )
        elif seq != self._expected_seq:
            res.violations.append(
                f"seq gap: expected {self._expected_seq}, found {seq} — "
                "records lost (writer drops or a pruned/torn segment "
                "mid-stream)"
            )
        self._expected_seq = seq + 1
        res.last_seq = seq
        where = f"seq {seq}"
        if t in ("node_add", "node_resync"):
            node = rec["node"]
            try:
                cs = _chipset_from_record(rec)
            except Exception as e:
                res.violations.append(f"{where}: bad {t} record: {e}")
                return
            if rec.get("reset"):
                # layout-change resync: the live allocator rebuilt the
                # ChipSet and WIPED usage while the scheduler ledger kept
                # its pod entries — mirror that: fresh chips, pods stay
                # live but uncharged
                for lp in res.pods.values():
                    if lp.node == node:
                        lp.charged = False
            else:
                # re-charge charged pods still live on this node: a
                # same-shape resync (and a restart's node_add) preserves
                # usage in the live allocator, so replay must too
                for pk, lp in res.pods.items():
                    if lp.node != node or not lp.charged:
                        continue
                    if cs.can_transact(lp.option):
                        cs.transact(lp.option)
                    else:
                        res.violations.append(
                            f"{where}: {t} of {node} cannot re-charge live "
                            f"pod {pk} (capacity shrank under a live "
                            "allocation)"
                        )
            res.nodes[node] = cs
            if rec.get("generation"):
                res.generations[node] = rec["generation"]
        elif t == "bind":
            pod, node = rec.get("pod"), rec.get("node")
            cs = res.nodes.get(node)
            if cs is None:
                res.violations.append(
                    f"{where}: bind {pod} on unknown node {node}"
                )
                return
            try:
                opt = option_from_record(rec["option"])
            except Exception as e:
                res.violations.append(f"{where}: bad bind option: {e}")
                return
            if pod in res.pods:
                lp = res.pods[pod]
                if lp.node == node and lp.option.allocs == opt.allocs:
                    # idempotent re-assertion: a restart re-journals every
                    # surviving pod (source=replay/add) after its node_add
                    # re-charged it — same node, same placement, no new
                    # state.  (Scores may differ: annotation recovery
                    # rebuilds options with score 0.)
                    lp.seq = seq
                    return
                res.violations.append(
                    f"{where}: double bind of {pod} (already live on "
                    f"{res.pods[pod].node} since seq {res.pods[pod].seq} "
                    "with a different placement)"
                )
                return
            if not cs.can_transact(opt):
                res.violations.append(
                    f"{where}: bind {pod} on {node} double-books a chip "
                    f"(placement no longer fits the replayed state)"
                )
                return
            cs.transact(opt)
            res.pods[pod] = _LivePod(
                node=node, option=opt, uid=rec.get("uid", ""),
                gang=rec.get("gang", "") or "", seq=seq,
            )
        elif t == "forget":
            pod = rec.get("pod")
            lp = res.pods.pop(pod, None)
            if lp is None:
                # legitimate race: a pod deleted mid-gang-commit journals
                # a forget before its bind was ever journaled
                res.warnings.append(f"{where}: forget of unbound pod {pod}")
                return
            if not lp.charged:
                return  # reset-resync wiped its charge; nothing to free
            cs = res.nodes.get(lp.node)
            if cs is None:
                res.violations.append(
                    f"{where}: forget {pod} on unknown node {lp.node}"
                )
                return
            if not cs.can_cancel(lp.option):
                res.violations.append(
                    f"{where}: forget {pod} would free capacity not "
                    f"charged on {lp.node} (double free / inflation)"
                )
                return
            cs.cancel(lp.option)
        elif t == "migrate":
            # defrag live migration: one atomic evict→rebind.  Invariant:
            # a migration CONSERVES the pod's per-container chip demand
            # (same chips, same core/hbm — only WHERE changes); the live
            # transaction charges the destination before freeing the
            # source, so replay mirrors that order.
            pod = rec.get("pod")
            frm, to = rec.get("source_node"), rec.get("node")
            lp = res.pods.get(pod)
            if lp is None:
                res.violations.append(
                    f"{where}: migrate of unbound pod {pod}"
                )
                return
            try:
                new = option_from_record(rec["option"])
                old = option_from_record(rec["option_old"])
            except Exception as e:
                res.violations.append(f"{where}: bad migrate option: {e}")
                return
            if option_demand(old) != option_demand(new):
                res.violations.append(
                    f"{where}: migrate {pod} does not conserve per-pod "
                    "chip demand (chips created or destroyed in flight)"
                )
                return
            if lp.node != frm or lp.option.allocs != old.allocs:
                res.violations.append(
                    f"{where}: migrate {pod} from {frm} does not match "
                    f"its live placement (on {lp.node} since seq {lp.seq})"
                )
                return
            cs_to = res.nodes.get(to)
            cs_from = res.nodes.get(frm)
            if cs_to is None or cs_from is None:
                res.violations.append(
                    f"{where}: migrate {pod} touches unknown node "
                    f"{frm if cs_from is None else to}"
                )
                return
            if not cs_to.can_transact(new):
                res.violations.append(
                    f"{where}: migrate {pod} onto {to} double-books a "
                    "chip (destination no longer fits the replayed state)"
                )
                return
            cs_to.transact(new)
            if lp.charged:
                if cs_from.can_cancel(old):
                    cs_from.cancel(old)
                else:
                    res.violations.append(
                        f"{where}: migrate {pod} frees capacity not "
                        f"charged on {frm} (double free / inflation)"
                    )
            res.pods[pod] = _LivePod(
                node=to, option=new, uid=rec.get("uid", lp.uid),
                gang=rec.get("gang", "") or lp.gang, seq=seq,
                charged=True,  # the destination IS charged either way
            )
        elif t == "gang_admit":
            gang = rec.get("gang", "?")
            g = res.gangs.setdefault(gang, {"admits": 0, "rollbacks": 0})
            g["admits"] += 1
            members = rec.get("members", [])
            missing = [
                m
                for m in members
                if m not in res.pods or res.pods[m].gang != gang
            ]
            if missing:
                res.violations.append(
                    f"{where}: gang {gang} admitted with {len(missing)}/"
                    f"{len(members)} member(s) not bound at admit time: "
                    f"{missing[:4]} — all-or-nothing violated"
                )
        elif t == "gang_rollback":
            gang = rec.get("gang", "?")
            g = res.gangs.setdefault(gang, {"admits": 0, "rollbacks": 0})
            g["rollbacks"] += 1
            # a rolled-back gang must have left nothing bound
            bound = [
                pk for pk, lp in res.pods.items() if lp.gang == gang
            ]
            if bound:
                res.violations.append(
                    f"{where}: gang {gang} rolled back but {len(bound)} "
                    f"member(s) still journaled as bound: {bound[:4]}"
                )
        elif t == "fed_gang":
            # one shard's view of a federated two-phase gang admission
            # (federation/frontdoor.py).  The LOCAL members' binds and
            # compensating forgets are journaled individually by the
            # split-phase primitives; each phase record seals what the
            # stream must show at that point:
            #   prepare — every local member bound (journaled under the
            #   same engine-lock hold as the binds, so nothing can
            #   interleave);
            #   commit  — the prepared members still bound;
            #   abort   — none bound (the compensating forgets are
            #   journaled BEFORE the abort, reverse-commit order).
            # Cross-shard agreement (all participants reach the same
            # terminal phase) is the dir-of-dirs audit's job — one
            # stream cannot see the other shards.
            txn = rec.get("txn", "?")
            phase = rec.get("phase", "?")
            members = rec.get("members") or []
            res.fed_gang_records += 1
            fg = res.fed_gangs.setdefault(txn, {
                "gang": rec.get("gang", "?"), "phases": [],
                "members": [], "shards": rec.get("shards") or [],
            })
            fg["phases"].append(phase)
            if members:
                fg["members"] = list(members)
            else:
                members = fg["members"]
            if phase == "prepare":
                missing = [m for m in members if m not in res.pods]
                if missing:
                    res.violations.append(
                        f"{where}: fed_gang {txn} prepared with "
                        f"{len(missing)}/{len(members)} local member(s) "
                        f"not bound: {missing[:4]} — phase-1 reservation "
                        "not sealed atomically"
                    )
            elif phase == "commit":
                if "prepare" not in fg["phases"][:-1]:
                    res.violations.append(
                        f"{where}: fed_gang {txn} committed without a "
                        "local prepare — decision outran the reservation"
                    )
                missing = [m for m in members if m not in res.pods]
                if missing:
                    res.violations.append(
                        f"{where}: fed_gang {txn} committed but "
                        f"{len(missing)} local member(s) not bound: "
                        f"{missing[:4]} — all-or-nothing violated"
                    )
            elif phase == "abort":
                bound = [m for m in members if m in res.pods]
                if bound:
                    res.violations.append(
                        f"{where}: fed_gang {txn} aborted but "
                        f"{len(bound)} local member(s) still bound: "
                        f"{bound[:4]} — compensating rollback incomplete"
                    )
            else:
                res.violations.append(
                    f"{where}: fed_gang {txn} has unknown phase "
                    f"{phase!r}"
                )
        elif t == "node_remove":
            # the live remove_node refuses while ledger pods still charge
            # the node, so a journal recording a removal with live pods on
            # it witnesses a conservation break (capacity vaporized with
            # its charges)
            node = rec.get("node")
            still = [
                pk for pk, lp in res.pods.items()
                if lp.node == node and lp.charged
            ]
            if still:
                res.violations.append(
                    f"{where}: node_remove of {node} with {len(still)} "
                    f"live pod(s) still charging it: {still[:4]} — "
                    "capacity removed out from under its charges"
                )
            res.nodes.pop(node, None)
            res.generations.pop(node, None)
        elif t == "profile":
            # workload-profile snapshot (profile/ observatory): an
            # ANNOTATION in the mutation stream — it participates in the
            # dense-seq audit above but never touches allocator state.
            # The latest one is kept so offline consumers (what_if
            # raters, the replay CLI) can read the profiles as recorded.
            res.profiles += 1
            res.last_profile = {
                "seq": seq,
                "t": rec.get("t"),
                "profiles": rec.get("profiles") or {},
                "interference": rec.get("interference") or {},
            }
        elif t == "policy":
            # policy-plane annotation (policy/ subsystem): lifecycle
            # events and canary bind decisions.  Participates in the
            # dense-seq audit, never mutates allocator state.  Decide
            # records rebuild the pod → (policy, arm) map so replay can
            # answer "which policy decided this bind".
            res.policy_records += 1
            res.last_policy = {"seq": seq, **{
                k: rec.get(k)
                for k in ("action", "verb", "name", "pod", "arm")
                if rec.get(k) is not None
            }}
            if rec.get("action") == "canary_decide" and rec.get("pod"):
                res.policy_decisions[rec["pod"]] = {
                    "seq": seq,
                    "name": rec.get("name"),
                    "verb": rec.get("verb"),
                    "arm": rec.get("arm"),
                    "score": rec.get("score"),
                    "score_other": rec.get("score_other"),
                    "divergence": rec.get("divergence"),
                }
        elif t == "policy_fault":
            # a policy runtime fault (budget/deadline/math): the verb
            # fell back to the incumbent built-in — annotation only
            res.policy_faults += 1
        elif t == "warmup":
            # compile warm-up completion (compilecache/): an annotation
            # in the mutation stream — lattice size, fill/load split and
            # wall time for one pod's pre-lowering phase.  Participates
            # in the dense-seq audit, never touches allocator state.
            res.warmup_records += 1
            res.last_warmup = {
                "seq": seq,
                "t": rec.get("t"),
                "lattice_size": rec.get("lattice_size"),
                "built": rec.get("built"),
                "fills": rec.get("fills"),
                "loads": rec.get("loads"),
                "wall_s": rec.get("wall_s"),
                "cache_dir": rec.get("cache_dir"),
            }
        elif t == "fleet":
            # autoscaler evaluation (fleet/ subsystem): an annotation
            # like `profile` — the signals + decision stream that
            # fleet.autoscaler.score_policy replays a candidate scaling
            # policy against.  Never mutates allocator state.  The
            # ``slo`` field (burn posture the evaluation saw) replays
            # with the signals so candidates face the same SLO history.
            res.fleet_records += 1
            res.last_fleet = {
                "seq": seq,
                "t": rec.get("t"),
                "action": rec.get("action"),
                "signals": rec.get("signals") or {},
                "replicas": rec.get("replicas"),
                "slo": rec.get("slo"),
            }
        elif t == "slo":
            # SLO-plane annotation (slo/): objective loads and burn-rate
            # breach/recovery transitions.  Participates in the dense-
            # seq audit, never mutates allocator state; a breach record
            # carries the exemplar trace ids that resolve via
            # /debug/trace/<id> — the offline audit trail from a p99
            # alert to the concrete journeys behind it.
            res.slo_records += 1
            if rec.get("action") == "breach":
                res.slo_breaches += 1
                res.last_slo_breach = {
                    "seq": seq,
                    "t": rec.get("t"),
                    "wclass": rec.get("wclass"),
                    "objective": rec.get("objective"),
                    "burn_short": rec.get("burn_short"),
                    "burn_long": rec.get("burn_long"),
                    "exemplars": rec.get("exemplars") or [],
                }
        elif t == "twin":
            # digital-twin scenario annotation (twin/): seed + scenario
            # + model/score metadata a twin run stamps into ITS OWN
            # journal.  Participates in the dense-seq audit, never
            # mutates allocator state — and marks the stream as
            # simulated.
            res.twin_records += 1
            res.last_twin = {"seq": seq, **{
                k: rec.get(k)
                for k in ("action", "scenario", "seed", "mode")
                if rec.get(k) is not None
            }}
        elif t == "resize":
            # gang-resize commit summary (fleet/resize.py).  The member
            # binds/forgets/migrates that changed state were journaled
            # individually by the transaction; THIS record declares the
            # intended end state, and replay verifies the stream reached
            # exactly it:
            #   all-or-nothing — the recorded membership matches the live
            #   member set for the gang (no half-admitted joiner, no
            #   surviving evictee);
            #   chip conservation — every member charges exactly the
            #   recorded per-member chip count (chips move only WITH a
            #   member, never appear or vanish in flight).
            res.resizes += 1
            gang = rec.get("gang", "?")
            members = rec.get("members") or []
            chips_each = rec.get("chips_per_member")
            live = {
                pk for pk, lp in res.pods.items() if lp.gang == gang
            }
            missing = [m for m in members if m not in live]
            extra = sorted(live - set(members))
            if missing:
                res.violations.append(
                    f"{where}: resize of gang {gang} records "
                    f"{len(missing)} member(s) not bound: {missing[:4]} "
                    "— all-or-nothing violated"
                )
            if extra:
                res.violations.append(
                    f"{where}: resize of gang {gang} left "
                    f"{len(extra)} non-member(s) still bound: {extra[:4]} "
                    "— all-or-nothing violated"
                )
            if chips_each is not None:
                for m in members:
                    lp = res.pods.get(m)
                    if lp is None:
                        continue  # already flagged as missing
                    got = sum(
                        len(a.coords)
                        for a in lp.option.allocs
                        if a.needs_tpu
                    )
                    if got != chips_each:
                        res.violations.append(
                            f"{where}: resize of gang {gang}: member {m} "
                            f"charges {got} chips, record declares "
                            f"{chips_each} — chips not conserved"
                        )
            for r in rec.get("removed") or []:
                if r in res.pods:
                    res.violations.append(
                        f"{where}: resize of gang {gang}: removed member "
                        f"{r} is still bound"
                    )
        elif t == "kv_migrate":
            # live KV-session migration (fleet/ disaggregated data
            # plane): a commanded session hop between serving replicas —
            # an ANNOTATION in the mutation stream (dense-seq audited,
            # zero allocator mutation: KV pages move between engines'
            # HBM pools, not between scheduler-plane chips).  Failed
            # hops are counted separately — a fleet whose sheds mostly
            # fail is an operational signal replay should surface.
            res.kv_migrations += 1
            if not rec.get("ok", False):
                res.kv_migrations_failed += 1
            res.last_kv_migration = {
                "seq": seq,
                "t": rec.get("t"),
                "src": rec.get("src"),
                "dst": rec.get("dst"),
                "reason": rec.get("reason"),
                "ok": rec.get("ok"),
                "pages": rec.get("pages"),
                "tokens_done": rec.get("tokens_done"),
            }
        elif t == "ha_takeover":
            # warm-takeover summary (scheduler/ha.py): the new leader
            # adopted a follower's replayed state and diff-resynced
            # against the annotation ledger.  An ANNOTATION — the diff's
            # actual mutations (add_pod binds / forgets) journaled
            # individually around it; participates in the dense-seq
            # audit, never mutates allocator state here.
            res.ha_takeovers += 1
            res.last_takeover = {
                "seq": seq,
                "t": rec.get("t"),
                "nodes": rec.get("nodes"),
                "pods": rec.get("pods"),
                "adopted_seq": rec.get("adopted_seq"),
                "diff_added": rec.get("diff_added"),
                "diff_removed": rec.get("diff_removed"),
                "wall_ms": rec.get("wall_ms"),
            }
        else:
            res.warnings.append(f"{where}: unknown record type {t!r}")

    def conservation_violations(self) -> list[str]:
        """End-of-stream post-conditions: per-node capacity conservation
        — the chips charged by live pods must account exactly for
        total - avail.  Returns a FRESH list (never appended to the
        result), so a follower can audit repeatedly while streaming."""
        res = self.result
        out: list[str] = []
        for node, cs in sorted(res.nodes.items()):
            exp_core = exp_hbm = 0
            for lp in res.pods.values():
                if lp.node != node or not lp.charged:
                    continue
                for a in lp.option.allocs:
                    if not a.needs_tpu:
                        continue
                    for c in a.coords:
                        i = cs._slot.get(c)
                        if i is None:
                            continue
                        if a.whole:
                            exp_core += cs._core_total[i]
                            exp_hbm += cs._hbm_total[i]
                        else:
                            exp_core += a.core
                            exp_hbm += a.hbm
            used_core = cs.total_core() - cs.avail_core()
            used_hbm = cs.total_hbm() - cs.avail_hbm()
            if used_core != exp_core or used_hbm != exp_hbm:
                out.append(
                    f"node {node}: capacity not conserved — chips show "
                    f"core={used_core}/hbm={used_hbm} in use but live pods "
                    f"charge core={exp_core}/hbm={exp_hbm}"
                )
        # federated 2PC: a txn whose LAST local phase is "prepare" holds
        # a reservation nobody decided — the shard died mid-transaction
        # and recovery never compensated it (chips silently pinned)
        for txn, fg in sorted(res.fed_gangs.items()):
            phases = fg.get("phases") or []
            if phases and phases[-1] == "prepare":
                out.append(
                    f"fed_gang {txn}: unresolved at end of stream — "
                    "prepared but never committed or aborted "
                    "(reservation leaked; recovery owed a compensating "
                    "rollback)"
                )
        return out


def replay(events: list[dict]) -> ReplayResult:
    """Rebuild state from a record stream; every anomaly is collected,
    never raised — a corrupt journal must yield a report, not a
    traceback.  (Batch wrapper over the incremental ``ReplayEngine``.)"""
    eng = ReplayEngine()
    for rec in events:
        eng.apply(rec)
    res = eng.result
    res.violations.extend(eng.conservation_violations())
    return res


def diff_live(res: ReplayResult, status: dict) -> list[str]:
    """Replayed state vs a live ``/scheduler/status`` snapshot.  Returns
    human-readable mismatch lines; empty = identical."""
    scheds = status.get("schedulers")
    if scheds is None:
        scheds = [status]
    diffs: list[str] = []
    live_nodes: dict[str, dict] = {}
    live_pods: set[str] = set()
    for s in scheds:
        live_nodes.update(s.get("nodes", {}))
        live_pods.update(s.get("pods", []))

    for node in sorted(set(live_nodes) | set(res.nodes)):
        ns = live_nodes.get(node)
        cs = res.nodes.get(node)
        if ns is None:
            # the engine's allocator registry is a lazy cache of cluster
            # state: after a restart an idle node exists in the journal
            # but is not materialized live until something schedules on
            # it — identical states, not a divergence.  A replayed node
            # with USAGE missing live is a real one.
            if (
                cs.avail_core() == cs.total_core()
                and cs.avail_hbm() == cs.total_hbm()
                and not any(lp.node == node for lp in res.pods.values())
            ):
                continue
            diffs.append(
                f"node {node}: in journal replay with usage but not live"
            )
            continue
        if cs is None:
            diffs.append(f"node {node}: live but never journaled")
            continue
        live_chips = ns.get("chips", {})
        replayed = cs.status()["chips"]
        for coord in sorted(set(live_chips) | set(replayed)):
            lc, rc = live_chips.get(coord), replayed.get(coord)
            if lc is None or rc is None:
                diffs.append(
                    f"node {node} chip {coord}: present only "
                    f"{'live' if rc is None else 'in replay'}"
                )
                continue
            for k in ("core_avail", "core_total", "hbm_avail", "hbm_total"):
                if lc.get(k) != rc.get(k):
                    diffs.append(
                        f"node {node} chip {coord}: {k} live={lc.get(k)} "
                        f"replayed={rc.get(k)}"
                    )
    for pod in sorted(live_pods - set(res.pods)):
        diffs.append(f"pod {pod}: live in ledger but not in replayed state")
    for pod in sorted(set(res.pods) - live_pods):
        diffs.append(f"pod {pod}: replayed as live but absent from ledger")
    return diffs


def what_if(events: list[dict], rater: Rater) -> dict:
    """Replay the recorded workload, re-placing every bind with ``rater``
    instead of the recorded decision.  Forgotten pods release whatever
    the what-if run placed for them, so the alternative policy faces the
    same arrival/departure sequence the real one did.

    Returns aggregate placement-quality stats for the alternative policy
    next to the recorded one: mean score, contiguous fraction, and how
    many binds the alternative could not place at all (it then falls
    back to the recorded placement so the stream stays consistent).

    MAINTENANCE NOTE: the checkpoint-boot / as_of seq-skip / node
    add+resync handling below deliberately mirrors ``replay()`` (which
    owns the authoritative versions with the invariant checks) — a new
    record field or flag handled there must be handled here too."""
    nodes: dict[str, ChipSet] = {}
    gens: dict[str, str] = {}  # node → TPU generation (node_add records)
    placed: dict[str, tuple[str, Option]] = {}
    binds = unplaced = contiguous = rec_contiguous = 0
    profiles_seen = 0
    scores: list[float] = []
    rec_scores: list[float] = []
    # rater-NEUTRAL packing quality, sampled after every re-placed bind:
    # the cluster-wide fraction of fully-free chips.  A policy that
    # scatters fractional tenants across untouched chips burns whole-free
    # chips a consolidating one preserves — measured in chips, not in any
    # rater's own score scale, so the promotion gate can compare two
    # raters on it.  Maintained incrementally (free counts re-read only
    # for the node a record touched), so the sweep stays O(records).
    free_cache: dict[str, int] = {}
    chips_cache: dict[str, int] = {}
    free_sum = 0
    total_chips_sum = 0
    preserve_samples = 0
    preserve_acc = 0.0

    def _free_resync(node: str) -> None:
        nonlocal free_sum
        cs = nodes.get(node)
        old_free = free_cache.get(node)
        if old_free is not None:
            free_sum -= old_free
        if cs is None:
            free_cache.pop(node, None)
            return
        new = cs.free_count()
        free_cache[node] = new
        free_sum += new

    def _total_resync(node: str) -> None:
        # per-node delta, like _free_resync — a full re-sum per
        # node_add record would make the sweep O(nodes²) on a
        # 10k-node fleet's journal
        nonlocal total_chips_sum
        cs = nodes.get(node)
        total_chips_sum -= chips_cache.get(node, 0)
        if cs is None:
            chips_cache.pop(node, None)
        else:
            chips_cache[node] = cs.num_chips
            total_chips_sum += cs.num_chips
    # profile-aware raters consume the recorded profile stream and each
    # bind's workload class/target generation; both hooks are duck-typed
    # so geometry raters replay exactly as before
    observe_profile = getattr(rater, "observe_profile", None)
    set_workload = getattr(rater, "set_workload", None)
    booted = False
    boot_as_of = -1
    for rec in events:
        t = rec.get("type")
        if t == "checkpoint":
            if booted or nodes or placed:
                continue  # mid-stream re-assertion
            booted = True
            boot_as_of = rec.get("as_of_seq", -1)
            for name, inv in (rec.get("nodes") or {}).items():
                try:
                    nodes[name] = _chipset_from_record(inv)
                except Exception:
                    continue
            for p in rec.get("pods") or []:
                try:
                    opt = option_from_record(p["option"])
                except Exception:
                    continue
                cs = nodes.get(p.get("node"))
                if cs is not None and cs.can_transact(opt):
                    # boot-state pods keep their RECORDED placement (the
                    # what-if policy only re-places binds it witnesses)
                    cs.transact(opt)
                    placed[p["pod"]] = (p["node"], opt)
            for name in nodes:
                _free_resync(name)
                _total_resync(name)
            continue
        if booted and rec.get("seq", -1) <= boot_as_of:
            continue  # already reflected in the boot snapshot
        if t == "profile":
            # recorded workload profiles, in stream order — scores from
            # here on use them, exactly as a live promotion would
            profiles_seen += 1
            if observe_profile is not None:
                observe_profile(rec)
            continue
        if t in ("fleet", "resize", "policy", "policy_fault", "warmup",
                 "gang_admit", "gang_rollback", "fed_gang", "ha_takeover",
                 "kv_migrate", "slo", "twin"):
            # annotations (autoscaler evaluations / resize summaries /
            # policy-plane events / compile warm-ups / gang admit+rollback
            # markers): the member binds/forgets/migrates around a
            # resize or gang commit carry the state changes; scoring a
            # scaling POLICY offline is fleet.autoscaler.score_policy's
            # job, the policy plane's own decision trail must not
            # perturb a what-if re-run that may itself be gating a
            # policy, and gang markers are verified by replay()'s
            # all-or-nothing audit, not re-placed here
            continue
        if t == "node_remove":
            # mirrors replay(): the live remove refuses while pods still
            # charge the node, so dropping it (and any what-if placement
            # stranded there by a policy that placed where the recorded
            # stream did not) keeps the streams consistent
            node = rec.get("node")
            for pk in [p for p, (n, _o) in placed.items() if n == node]:
                placed.pop(pk)
            nodes.pop(node, None)
            gens.pop(node, None)
            _free_resync(node)
            _total_resync(node)
            continue
        if t in ("node_add", "node_resync"):
            try:
                cs = _chipset_from_record(rec)
            except Exception:
                continue
            node = rec["node"]
            if rec.get("generation"):
                gens[node] = rec["generation"]
            if rec.get("reset"):
                for pk in [p for p, (n, _o) in placed.items() if n == node]:
                    placed.pop(pk)
            else:
                for pk, (n, opt) in placed.items():
                    if n == node and cs.can_transact(opt):
                        cs.transact(opt)
            nodes[node] = cs
            _free_resync(node)
            _total_resync(node)
        elif t == "bind":
            node = rec.get("node")
            cs = nodes.get(node)
            if cs is None or rec.get("pod") in placed:
                continue  # unknown node, or a restart's re-assertion
            try:
                recorded = option_from_record(rec["option"])
            except Exception:
                continue
            binds += 1
            rec_scores.append(recorded.score)
            if all(
                a.contiguous for a in recorded.allocs if a.needs_tpu
            ):
                rec_contiguous += 1
            req = request_from_option(
                recorded, rec.get("pod", "?"), rec.get("uid", "")
            )
            if set_workload is not None:
                set_workload(
                    rec.get("wclass"), node=node,
                    generation=gens.get(node),
                )
            opt = cs.trade(req, rater)
            if opt is None:
                # alternative policy cannot place what the recorded one
                # did (should not happen on the same node state; count it
                # loudly) — apply the recorded option to stay consistent
                unplaced += 1
                opt = recorded
                if not cs.can_transact(opt):
                    continue
            else:
                scores.append(opt.score)
                if all(a.contiguous for a in opt.allocs if a.needs_tpu):
                    contiguous += 1
            cs.transact(opt)
            placed[rec.get("pod")] = (node, opt)
            _free_resync(node)
            if total_chips_sum > 0:
                preserve_acc += free_sum / total_chips_sum
                preserve_samples += 1
        elif t == "migrate":
            # defrag relocation (mirrors replay()'s handling — see the
            # MAINTENANCE NOTE above): free the what-if placement, then
            # let the ALTERNATIVE rater re-place the same demand on the
            # recorded destination node; fall back to the recorded new
            # placement so the stream stays consistent.  Not counted as
            # a bind — the demand was already scored at its bind record.
            pod = rec.get("pod")
            entry = placed.pop(pod, None)
            if entry is None:
                # the what-if stream never placed this pod (its bind
                # fell through under the alternative rater) — placing
                # it here would charge chips for a pod the comparison
                # counts as unplaced
                continue
            node, opt = entry
            cs = nodes.get(node)
            if cs is not None and cs.can_cancel(opt):
                cs.cancel(opt)
                _free_resync(node)
            to = rec.get("node")
            cs = nodes.get(to)
            if cs is None:
                continue
            try:
                recorded_new = option_from_record(rec["option"])
            except Exception:
                continue
            req = request_from_option(
                recorded_new, pod or "?", rec.get("uid", "")
            )
            if set_workload is not None:
                set_workload(
                    rec.get("wclass"), node=to, generation=gens.get(to),
                )
            opt = cs.trade(req, rater)
            if opt is None:
                if not cs.can_transact(recorded_new):
                    continue
                opt = recorded_new
            cs.transact(opt)
            placed[pod] = (to, opt)
            _free_resync(to)
        elif t == "forget":
            entry = placed.pop(rec.get("pod"), None)
            if entry is not None:
                node, opt = entry
                cs = nodes.get(node)
                if cs is not None and cs.can_cancel(opt):
                    cs.cancel(opt)
                    _free_resync(node)
    # rater-NEUTRAL end-state quality: mean fragmentation index over the
    # final node states.  The policy plane's replay gate judges a
    # candidate on this (plus placed/contiguous_frac) rather than on the
    # raters' OWN scores — two raters' score scales are not comparable,
    # and a candidate must not be able to gate itself through by
    # awarding 100 to everything.
    frag_vals = [cs.fragmentation()[0] for cs in nodes.values()]
    return {
        "rater": rater.name,
        "binds": binds,
        "placed": binds - unplaced,
        "unplaced": unplaced,
        "profile_records": profiles_seen,
        "final_frag_mean": round(
            sum(frag_vals) / len(frag_vals), 4
        ) if frag_vals else 0.0,
        "mean_free_chip_frac": round(
            preserve_acc / preserve_samples, 4
        ) if preserve_samples else 0.0,
        "mean_score": round(sum(scores) / len(scores), 3) if scores else 0.0,
        "contiguous_frac": round(contiguous / binds, 4) if binds else 0.0,
        "recorded_mean_score": (
            round(sum(rec_scores) / len(rec_scores), 3) if rec_scores else 0.0
        ),
        "recorded_contiguous_frac": (
            round(rec_contiguous / binds, 4) if binds else 0.0
        ),
    }
