"""Flight-recorder CLI: offline replay, audit and what-if scoring.

    python -m elastic_gpu_scheduler_tpu.journal replay --dir DIR \\
        [--status FILE|URL] [--rater NAME] [--json]
    python -m elastic_gpu_scheduler_tpu.journal tail --dir DIR [-n N]

``replay`` rebuilds allocator state from the journal, verifies the
invariants (no double-booked chip, capacity conserved per node, gang
placements all-or-nothing), optionally diffs against a live
``/scheduler/status`` snapshot (a URL, a file path, or ``-`` for
stdin), and optionally re-scores the recorded workload under a
different rater (``--rater binpack|spread|random|ici-locality``).

``--dir`` may also point at a FEDERATION journal root — a directory of
per-shard journal directories (no segments of its own) — in which case
every shard stream replays independently and the cross-shard
``fed_gang`` conservation audit runs on top (all-or-nothing agreement,
no silent committed participants, no unresolved reservations).
``--rater`` then scores each shard's recorded workload separately;
``--status`` is single-stream only.

Exit status: 0 clean, 1 invariant violations or live-state divergence,
2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import read_journal, segment_paths
from .replay import diff_live, replay, what_if


def _load_status(src: str) -> dict:
    if src == "-":
        return json.load(sys.stdin)
    if src.startswith("http://") or src.startswith("https://"):
        import urllib.request

        with urllib.request.urlopen(src, timeout=10) as resp:
            return json.loads(resp.read())
    with open(src) as f:
        return json.load(f)


def _replay_federated(args, shard_dirs: dict) -> int:
    from ..federation.audit import audit_federation

    if args.status:
        print("error: --status diffs one live scheduler against one "
              "stream; point --dir at a single shard's journal instead",
              file=sys.stderr)
        return 2
    audit = audit_federation(args.dir, dirs=shard_dirs)
    audit.pop("results")  # ReplayResult objects aren't JSON-serializable
    out = {
        "journal": {"dir": args.dir, "shards": len(shard_dirs)},
        "federated": audit,
    }
    if args.rater:
        from ..policy.registry import resolve_rater

        try:
            rater = resolve_rater(args.rater)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        out["what_if"] = {
            sid: what_if(read_journal(path), rater)
            for sid, path in sorted(shard_dirs.items())
        }
    failed = bool(audit["violations"])
    if args.json:
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        print(f"federation: {len(shard_dirs)} shard journal(s) under "
              f"{args.dir}")
        for sid, s in sorted(audit["shards"].items()):
            print(f"shard:   {sid}: {s['records']} record(s), "
                  f"{s['live_pods']} live pod(s), "
                  f"{s.get('fed_gang_records', 0)} fed_gang record(s)")
        if audit["fed_gangs"]:
            print(f"fed_gang: {len(audit['fed_gangs'])} cross-shard "
                  f"transaction(s)")
        for v in audit["violations"]:
            print(f"VIOLATION: {v}")
        for sid, w in sorted(out.get("what_if", {}).items()):
            print(
                f"what-if [{sid}] {w['rater']}: {w['placed']}/{w['binds']} "
                f"placed (mean score {w['mean_score']})"
            )
        if not failed:
            print("ok: invariants hold across all shard journals")
    return 1 if failed else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser("elastic_gpu_scheduler_tpu.journal")
    sub = p.add_subparsers(dest="cmd")
    rp = sub.add_parser("replay", help="rebuild state, audit invariants")
    rp.add_argument("--dir", required=True, help="journal directory")
    rp.add_argument(
        "--status",
        default="",
        help="live /scheduler/status snapshot to diff against "
        "(URL, file path, or - for stdin)",
    )
    rp.add_argument(
        "--rater",
        default="",
        help="what-if replay: re-place the recorded workload under this "
        "placement policy.  One registry serves this flag and the "
        "scheduler's --priority (policy.registry.resolve_rater): "
        "binpack|spread|random|ici-locality, profile-aware[:BASE] "
        "(geometry BASE scaled by the journal's recorded `profile` "
        "records), or policy:FILE[:BASE] (a policy-plane expression "
        "file; BASE = fallback rater on fault)",
    )
    rp.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    tp = sub.add_parser("tail", help="print the last N records")
    tp.add_argument("--dir", required=True)
    tp.add_argument("-n", type=int, default=20)
    args = p.parse_args(argv)

    if args.cmd == "tail":
        events = read_journal(args.dir)
        for rec in events[-max(0, args.n):]:
            print(json.dumps(rec, sort_keys=True))
        return 0
    if args.cmd != "replay":
        p.print_help()
        return 2

    # Federation root (directory of per-shard journal directories)?
    # Replay every stream and audit fed_gang conservation ACROSS them —
    # a single-stream replay cannot see the other 2PC participants.
    from ..federation.audit import shard_journal_dirs

    shard_dirs = shard_journal_dirs(args.dir)
    if shard_dirs:
        return _replay_federated(args, shard_dirs)

    events = read_journal(args.dir)
    res = replay(events)
    out = {
        "journal": {
            "dir": args.dir,
            "segments": len(segment_paths(args.dir)),
        },
        "replay": res.summary(),
    }
    failed = bool(res.violations)
    if args.status:
        try:
            status = _load_status(args.status)
        except Exception as e:
            print(f"error: cannot load status {args.status!r}: {e}",
                  file=sys.stderr)
            return 2
        diffs = diff_live(res, status)
        out["live_diff"] = diffs
        failed = failed or bool(diffs)
    if args.rater:
        # ONE registry lookup for built-ins, profile-aware wrapping and
        # policy-plane expressions — the same resolver the scheduler's
        # --priority flag uses (policy/registry.py), so the two CLIs can
        # never drift on spec parsing
        from ..policy.registry import resolve_rater

        try:
            rater = resolve_rater(args.rater)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        out["what_if"] = what_if(events, rater)

    if args.json:
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        r = out["replay"]
        print(
            f"journal: {r['records']} record(s) over {out['journal']['segments']} "
            f"segment(s), last seq {r['last_seq']}"
        )
        print(f"state:   {r['nodes']} node(s), {r['live_pods']} live pod(s)")
        for g, v in r["gangs"].items():
            print(
                f"gang:    {g}: {v['admits']} admit(s), "
                f"{v['rollbacks']} rollback(s)"
            )
        for w in r["warnings"]:
            print(f"warn:    {w}")
        for v in r["violations"]:
            print(f"VIOLATION: {v}")
        for d in out.get("live_diff", []):
            print(f"DIVERGED: {d}")
        if "what_if" in out:
            w = out["what_if"]
            print(
                f"what-if {w['rater']}: {w['placed']}/{w['binds']} placed "
                f"(recorded mean score {w['recorded_mean_score']} / "
                f"contiguous {w['recorded_contiguous_frac']}; "
                f"{w['rater']} mean score {w['mean_score']} / "
                f"contiguous {w['contiguous_frac']})"
            )
        if not failed:
            print("ok: invariants hold"
                  + (" and live state matches" if args.status else ""))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
