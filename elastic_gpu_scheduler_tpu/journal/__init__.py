"""Scheduling flight recorder: a durable, append-only decision journal.

The tracer (tracing/__init__.py) answers "why did pod X land on node Y"
only while the span ring still holds the trace; once the pod is gone
there is no durable record of how the cluster reached its current
allocation state, no offline way to prove the allocator never
double-booked a chip, and no way to evaluate a different rater against
real recorded workload.  Gavel and Tesserae (PAPERS.md) both build
policy comparison on exactly this substrate: replayable scheduling
traces.  This module is the persistence layer of the observability
stack:

- **Records.**  Every allocator state mutation lands here, emitted from
  the commit boundaries above ``ChipSet._set_slot`` (the scheduler's
  bind commit / ledger write, ``forget_pod``, ``add_pod``/startup
  replay, allocator creation and capacity resync, gang admit and
  rollback, the defrag planner's ``migrate`` evict→rebind
  transactions — replay verifies a migration conserves the pod's
  per-container chip demand — and ``node_remove`` when the
  reconciliation controller drops a node the cluster no longer lists;
  the live removal refuses while ledger pods still charge the node, so
  replay treats an occupied removal as a conservation violation).
  Emit-site vs replay-handler exhaustiveness is checked statically:
  a record type emitted anywhere without a ``journal/replay.py``
  handler fails ``make check-analysis``.  Each record carries the pod's
  ``trace_id`` so journal entries cross-link to ``/traces``, plus the
  node's fragmentation snapshot at the checkpoint (the gauges' source
  of truth).  The profile observatory (``profile/``) additionally lands
  periodic ``profile`` records — per-class throughput/latency/
  interference snapshots; these are ANNOTATIONS in the stream (replay
  never mutates allocator state from them) that let ``what_if`` replay
  re-score recorded workload under a profile-aware rater.  The fleet
  subsystem (``fleet/``) adds two more types: ``fleet`` (autoscaler
  evaluations — signals + decision, the stream
  ``fleet.autoscaler.score_policy`` replays a candidate scaling policy
  against offline; annotations like ``profile``), ``resize`` (a gang
  membership-change commit summary; replay VERIFIES it — chip
  conservation per member and exact all-or-nothing membership — against
  the state the surrounding bind/forget/migrate records rebuilt), and
  ``kv_migrate`` (a commanded live KV-session hop between serving
  replicas — shed or scale-down rebalance on the disaggregated data
  plane; an annotation, since the pages move between engine HBM pools,
  never between scheduler-plane chips).  The SLO plane (``slo/``) adds
  ``slo``: objective loads and error-budget burn breach/recovery
  transitions — annotations whose breach form carries exemplar trace
  ids, so the flight recorder links a p99 alert to the concrete
  request journeys (``/debug/trace/<id>``) that caused it.

- **Wire format.**  Length-prefixed JSONL with a per-record CRC32::

      <crc32 hex8> <payload length> <compact json>\\n

  A reader validates both the length and the CRC before trusting a
  line, so a torn tail (crash mid-write) is detected, not parsed into
  garbage.  Records carry a dense ``seq``; recovery yields everything
  up to the first torn record.

- **Segments.**  Size-based rotation (``journal-NNNNNN.log`` in the
  journal directory); the oldest segments are pruned past
  ``max_segments`` so a long-lived scheduler's disk use is bounded.

- **Writer.**  ``record()`` is one buffer append under a small lock —
  never file IO on the scheduling hot path.  A background thread
  drains the buffer, writes, rotates, and fsyncs per the configured
  policy (``always`` | ``interval`` | ``off``).

- **Replay.**  ``journal.replay`` (separate module — this one is
  stdlib-only so core/ may import it without cycles) rebuilds
  ChipSet/allocator state from a journal, verifies invariants (no
  double-booked chip, per-node capacity conservation, gang
  all-or-nothing), diffs against a live ``/scheduler/status`` snapshot,
  and supports what-if replay under a different rater.  CLI:
  ``python -m elastic_gpu_scheduler_tpu.journal replay``.

Disabled by default (``JOURNAL.enabled`` is False and every emission
site checks it first — one attribute load); enable with
``--journal-dir`` / ``TPU_JOURNAL_DIR`` or ``JOURNAL.configure()``.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Iterator, Optional

from ..faultinject import FAULTS

__all__ = [
    "Journal",
    "JOURNAL",
    "option_record",
    "parse_records",
    "read_journal",
    "read_segment",
    "segment_paths",
]

_SEGMENT_RE = re.compile(r"^journal-(\d{6})\.log$")

FSYNC_POLICIES = ("always", "interval", "off")


def option_record(opt) -> dict:
    """Encode an Option as plain JSON data (pure attribute access — no
    core imports, so this module stays import-cycle-free).  Decoded by
    ``journal.replay.option_from_record``."""
    return {
        "hash": opt.request_hash,
        "score": round(opt.score, 4),
        "allocs": [
            [
                a.container,
                [list(c) for c in a.coords],
                bool(a.whole),
                a.core,
                a.hbm,
                bool(a.contiguous),
            ]
            for a in opt.allocs
        ],
    }


def _encode(rec: dict) -> bytes:
    # compact; default=str so an unexpected field type can never crash
    # the writer.  No sort_keys: it costs ~20% of the encode on the bind
    # hot path and the CRC covers whatever byte order was written.
    payload = json.dumps(rec, separators=(",", ":"), default=str).encode()
    return b"%08x %d " % (zlib.crc32(payload), len(payload)) + payload + b"\n"


def segment_paths(dirpath: str) -> list[str]:
    """Journal segment files in rotation order."""
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    segs = sorted(n for n in names if _SEGMENT_RE.match(n))
    return [os.path.join(dirpath, n) for n in segs]


def parse_records(data: bytes) -> tuple[list[dict], bool, int]:
    """Parse a byte run of journal wire lines.  Returns (records, torn,
    good_bytes): ``torn`` is True when the run ends in a record that
    fails the length/CRC check (crash mid-write, or a shipping stream
    cut mid-record) — everything before is trusted, nothing after;
    ``good_bytes`` is the offset of the first bad byte.

    JSON payloads never contain a raw newline (json.dumps escapes), so
    line-splitting cannot cut a valid record.  Shared by segment reads
    and the journal-shipping follower (journal/ship.py), so both sides
    of the wire trust bytes by exactly the same rule."""
    out: list[dict] = []
    pos = 0
    for line in data.split(b"\n"):
        if not line:
            pos += 1  # a bare newline (or the empty post-final split)
            continue
        try:
            crc_s, len_s, payload = line.split(b" ", 2)
            crc = int(crc_s, 16)
            ln = int(len_s)
        except ValueError:
            return out, True, pos
        if len(payload) != ln or zlib.crc32(payload) != crc:
            return out, True, pos
        try:
            rec = json.loads(payload)
        except ValueError:
            return out, True, pos
        out.append(rec)
        pos += len(line) + 1
    return out, False, len(data)


def read_segment(path: str) -> tuple[list[dict], bool, int]:
    """Parse one segment file (see ``parse_records`` for the trust
    rule); ``good_bytes`` is what ``configure`` truncates to when
    repairing a crashed tail."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return [], True, 0
    return parse_records(data)


def read_journal(dirpath: str) -> list[dict]:
    """All recoverable records, in sequence order, stopping at the first
    torn record (records after a tear have no continuity guarantee —
    replay must not leap a hole in the mutation stream)."""
    out: list[dict] = []
    for path in segment_paths(dirpath):
        recs, torn, _good = read_segment(path)
        out.extend(recs)
        if torn:
            break
    return out


class Journal:
    """Append-only journal with a buffered background writer.

    Concurrency model: ``record()`` assigns the sequence number and
    appends to an in-memory buffer under one condition lock (no IO);
    the writer thread swaps the buffer out, encodes, writes, rotates
    and fsyncs.  ``flush()`` blocks until every record appended before
    the call has reached the OS (file flushed) — the test/CLI barrier
    before reading the files back."""

    def __init__(self):
        self._cond = threading.Condition(threading.Lock())
        self.enabled = False
        # callable returning {"nodes": {name: inventory}, "pods": [...]}
        # (or None) — written as a "checkpoint" record at the head of every
        # rotated segment, so a journal whose oldest segments were PRUNED
        # still replays: any segment suffix starts with a full state
        # snapshot (snapshot+log).  The engine registers itself here.
        self.checkpoint_provider = None
        # record-timestamp source.  The digital twin (twin/) runs its
        # OWN Journal instance with a VirtualClock here so twin records
        # carry SIMULATED time (and two same-seed runs are byte-
        # identical); the process-global JOURNAL keeps wall time.
        self.wall_clock = time.time
        self._atexit_registered = False
        self._pending_checkpoint = False
        self.dir: Optional[str] = None
        self.fsync_policy = "interval"
        self.fsync_interval_s = 0.2
        self.max_segment_bytes = 64 << 20
        self.max_segments = 64
        self.max_pending = 100_000  # records buffered before drops
        self._seq = 0
        self._buf: list[dict] = []  # records pending the writer
        self._appended = 0
        self._written = 0
        self._dropped = 0
        self._io_errors = 0
        self._io_lost = 0  # records lost to write failures (writer-only)
        self._rotations = 0
        self._pruned = 0
        self._tail: deque = deque(maxlen=256)
        # pod key → recent journal seqs (bounded both ways) for the
        # /debug/schedule cross-link
        self._pod_seqs: "OrderedDict[str, list[int]]" = OrderedDict()
        self._pod_seqs_cap = 2048
        self._pod_seqs_each = 32
        self._fh = None
        self._segment_index = 0
        self._segment_bytes = 0
        self._poisoned = False  # last write failed; reopen = fresh segment
        self._thread: Optional[threading.Thread] = None
        self._stop = False

    # -- lifecycle -----------------------------------------------------------

    def configure(
        self,
        dirpath: str,
        fsync: str = "interval",
        fsync_interval_s: float = 0.2,
        max_segment_bytes: int = 64 << 20,
        max_segments: int = 64,
    ) -> None:
        """Open (or re-open) the journal at ``dirpath`` and start the
        writer.  A torn tail from a crash is REPAIRED (the last segment
        is truncated back to its last valid record — the torn record was
        never acknowledged, so dropping it restores a clean stream);
        sequence numbering resumes after the last recoverable record and
        writing starts a fresh segment."""
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync policy {fsync!r} not in {FSYNC_POLICIES}"
            )
        self.close()
        os.makedirs(dirpath, exist_ok=True)
        existing = segment_paths(dirpath)
        next_seq = 0
        if existing:
            last_path = existing[-1]
            last_recs, torn, good = read_segment(last_path)
            if torn:
                with open(last_path, "r+b") as f:
                    f.truncate(good)
            last = os.path.basename(last_path)
            self._segment_index = int(_SEGMENT_RE.match(last).group(1)) + 1
            # resume numbering from the last valid SEQ-BEARING record
            # (checkpoints carry none and can be a segment's only line),
            # scanning segments BACKWARDS — never parse the whole journal
            # here (64 segments × 64MiB would stall scheduler startup)
            seqd = [r for r in last_recs if "seq" in r]
            if seqd:
                next_seq = seqd[-1]["seq"] + 1
            else:
                for path in reversed(existing[:-1]):
                    recs, _torn, _g = read_segment(path)
                    seqd = [r for r in recs if "seq" in r]
                    if seqd:
                        next_seq = seqd[-1]["seq"] + 1
                        break
        else:
            self._segment_index = 1
        with self._cond:
            # a fresh journal has NO checkpoint provider until an engine
            # registers: carrying one over from an earlier engine in the
            # same process would write segment-head snapshots of a stale,
            # unrelated registry into this journal
            self.checkpoint_provider = None
            self.dir = dirpath
            self.fsync_policy = fsync
            self.fsync_interval_s = max(0.01, float(fsync_interval_s))
            self.max_segment_bytes = max(1024, int(max_segment_bytes))
            self.max_segments = max(2, int(max_segments))
            self._seq = next_seq
            # a RESUMED journal's fresh segment needs a head checkpoint
            # too (rotation-written ones only cover rotations): once
            # pruning crosses a restart boundary, replay must still find
            # a boot snapshot.  Written by the writer with the first
            # batch, once a provider is registered.
            self._pending_checkpoint = next_seq > 0
            self._buf = []
            self._appended = self._written = 0
            self._dropped = self._io_errors = self._io_lost = 0
            self._rotations = self._pruned = 0
            self._tail.clear()
            self._pod_seqs.clear()
            self._poisoned = False
            self._stop = False
            self.enabled = True
        self._open_segment()
        if not self._atexit_registered:
            # a clean process exit must not strand the tail of the buffer
            # (the writer is a daemon polling at 100ms); close() is
            # idempotent so registering once covers every reconfigure
            import atexit

            atexit.register(self.close)
            self._atexit_registered = True
        self._thread = threading.Thread(
            target=self._writer_loop, name="journal-writer", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        """Flush, fsync (policy permitting), stop the writer, disable."""
        t = self._thread
        with self._cond:
            if not self.enabled and t is None:
                return
            self.enabled = False
            self._stop = True
            self._cond.notify_all()
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def abort(self) -> None:
        """Crash simulation (HA tests/chaos gate): stop WITHOUT draining
        — buffered records that never reached the writer are dropped,
        exactly what kill -9 loses.  The file handle is abandoned, not
        closed (closing would flush Python's userspace buffer — bytes a
        real crash never writes)."""
        t = self._thread
        with self._cond:
            dropped = len(self._buf)
            self._buf = []
            self._dropped += dropped
            self.enabled = False
            self._stop = True
            self._cond.notify_all()
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        self._fh = None  # abandoned, never flushed

    def request_checkpoint(self) -> None:
        """Ask the writer to emit a full-state boot checkpoint with its
        next batch (HA warm takeover: the new leader's journal must be
        self-contained — replayable without the previous leader's
        stream — so takeover snapshots the adopted state here instead of
        re-journaling 10k node_add/bind re-assertions)."""
        with self._cond:
            self._pending_checkpoint = True
            self._cond.notify_all()

    # -- hot path ------------------------------------------------------------

    def record(self, type_: str, **fields) -> Optional[int]:
        """Append one record; returns its sequence number, or None when
        disabled or the pending buffer is full (drop-new: the seq space
        stays dense, so replay can treat a seq gap as corruption).
        ``None``-valued fields are elided."""
        if not self.enabled:
            return None
        rec = {"type": type_}
        rec.update({k: v for k, v in fields.items() if v is not None})
        with self._cond:
            if not self.enabled:
                return None
            if len(self._buf) >= self.max_pending:
                self._dropped += 1
                return None
            seq = self._seq
            self._seq += 1
            rec["seq"] = seq
            rec["t"] = round(self.wall_clock(), 6)
            # the raw dict: encoding happens on the WRITER thread.  The
            # bind path pays one dict append — moving json+CRC here was
            # measured at ~+10% bind latency on a 2-core box
            self._buf.append(rec)
            self._appended += 1
            self._tail.append(rec)
            pk = fields.get("pod")
            if pk:
                seqs = self._pod_seqs.get(pk)
                if seqs is None:
                    seqs = self._pod_seqs[pk] = []
                    if len(self._pod_seqs) > self._pod_seqs_cap:
                        self._pod_seqs.popitem(last=False)
                else:
                    self._pod_seqs.move_to_end(pk)
                seqs.append(seq)
                if len(seqs) > self._pod_seqs_each:
                    del seqs[: -self._pod_seqs_each]
            # NO notify on the hot path (except under the always-fsync
            # durability contract): waking the writer per record costs a
            # GIL round-trip per bind — measured 2x on bind p99.  The
            # writer polls at 100ms and drains the whole buffer in one
            # batch; flush()/close() kick it explicitly.
            if self.fsync_policy == "always":
                self._cond.notify()
        return seq

    def pod_seqs(self, pod_key: str) -> list[int]:
        with self._cond:
            return list(self._pod_seqs.get(pod_key, ()))

    def last_seq(self) -> int:
        """Highest assigned sequence number (-1 before the first record).
        A checkpoint provider reads this under ITS OWN mutation lock to
        produce an exact as_of_seq for its snapshot."""
        with self._cond:
            return self._seq - 1

    # -- writer --------------------------------------------------------------

    def _segment_name(self) -> str:
        return f"journal-{self._segment_index:06d}.log"

    def _open_segment(self) -> None:
        path = os.path.join(self.dir, self._segment_name())
        self._fh = open(path, "ab")
        self._segment_bytes = self._fh.tell()

    def _rotate(self) -> None:
        try:
            self._fsync()
            self._fh.close()
        except OSError:
            pass
        self._fh = None
        self._segment_index += 1
        self._rotations += 1
        self._open_segment()  # may raise: the writer's batch handler recovers
        self._write_checkpoint()
        segs = segment_paths(self.dir)
        while len(segs) > self.max_segments:
            victim = segs.pop(0)
            try:
                os.unlink(victim)
                self._pruned += 1
            except OSError:
                break

    def _write_checkpoint(self) -> None:
        """Write a state snapshot at the head of a fresh segment (writer
        thread).  Checkpoints carry NO seq: they sit outside the mutation
        stream (replay skips them mid-stream and boots from one when the
        stream's prefix was pruned).  The provider runs on the writer
        thread holding no journal locks, so it may take engine/node locks
        freely; a snapshot slightly AHEAD of still-buffered records is
        fine — replay treats later binds it already contains as idempotent
        re-assertions."""
        provider = self.checkpoint_provider
        if provider is None:
            return
        # as_of_seq: every record with seq <= it is REFLECTED in the
        # snapshot; replay booting from the checkpoint skips them instead
        # of double-applying.  Read BEFORE the provider runs: the safe
        # error direction is snapshot-AHEAD-of-as_of (a later record the
        # snapshot already contains replays as an idempotent
        # re-assertion), never a mutation claimed-covered but absent.
        # A provider that reads the seq under its own engine lock supplies
        # an exact value instead.
        with self._cond:
            fallback_as_of = self._seq - 1
        try:
            state = provider()
        except Exception:
            return  # a failed snapshot must not kill the rotation
        if not state:
            return
        as_of = state.pop("as_of_seq", None)
        if as_of is None:
            as_of = fallback_as_of
        rec = {
            "type": "checkpoint", "t": round(self.wall_clock(), 6),
            "as_of_seq": as_of, **state,
        }
        line = _encode(rec)
        self._fh.write(line)
        self._segment_bytes += len(line)

    def _fsync(self) -> None:
        if self._fh is None:
            return
        try:
            if FAULTS.enabled:
                FAULTS.maybe_fire("journal.fsync")
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except OSError:
            self._io_errors += 1

    def _writer_loop(self) -> None:
        dirty = False
        last_sync = time.monotonic()
        while True:
            with self._cond:
                while not self._buf and not self._stop:
                    self._cond.wait(timeout=0.1)
                    if (
                        dirty
                        and self.fsync_policy == "interval"
                        and time.monotonic() - last_sync
                        >= self.fsync_interval_s
                    ):
                        break
                batch = self._buf
                self._buf = []
                stopping = self._stop
            if batch:
                written_lines = 0
                try:
                    if self._fh is None:  # recover from an earlier failure
                        if self._poisoned:
                            # the failed segment may end in a PARTIAL
                            # record — REPAIR it (truncate back to its
                            # last valid record, same rule as the
                            # configure() crash repair): CRC readers
                            # stop at the first bad line, so a tear left
                            # mid-journal would strand every later
                            # segment for replay AND the shipping
                            # stream.  Then recover onto a FRESH segment
                            # headed by a state checkpoint; records the
                            # failed batch lost stay visible as an
                            # honest seq gap.
                            try:
                                prev = os.path.join(
                                    self.dir, self._segment_name()
                                )
                                _recs, torn, good = read_segment(prev)
                                if torn:
                                    with open(prev, "r+b") as f:
                                        f.truncate(good)
                            except OSError:
                                pass  # unreadable: rotation still moves on
                            self._poisoned = False
                            self._segment_index += 1
                            self._open_segment()
                            self._write_checkpoint()
                        else:
                            self._open_segment()
                    if (
                        self._pending_checkpoint
                        and self.checkpoint_provider is not None
                    ):
                        # resumed journal: boot snapshot at (near) the
                        # head of the fresh segment, before any batch
                        self._pending_checkpoint = False
                        self._write_checkpoint()
                    for rec in batch:
                        line = _encode(rec)
                        if FAULTS.enabled:
                            # deterministic chaos: 'error' fails the
                            # batch like a dead disk; 'torn-write' emits
                            # a PARTIAL record then fails — byte-for-byte
                            # the tail kill -9 leaves mid-write (the
                            # repair path in configure() and the
                            # follower's CRC check both train on it)
                            directive = FAULTS.maybe_fire("journal.write")
                            if (
                                directive is not None
                                and directive.kind == "torn-write"
                            ):
                                self._fh.write(line[: max(1, len(line) // 2)])
                                self._fh.flush()
                                raise OSError(
                                    "injected torn write at journal.write"
                                )
                        self._fh.write(line)
                        written_lines += 1
                        if written_lines % 16 == 0:
                            # cap the encode burst's GIL hold: a large
                            # batch drained in one go would stall a
                            # concurrent bind for the whole burst on a
                            # small-core box
                            time.sleep(0)
                        self._segment_bytes += len(line)
                        if self._segment_bytes >= self.max_segment_bytes:
                            self._fh.flush()
                            self._rotate()
                    self._fh.flush()  # readers see bytes after flush()
                    dirty = True
                except Exception:
                    # disk full / dir removed / handle poisoned: count the
                    # loss (replay will flag the seq gap), drop the handle
                    # so the next batch re-opens, and keep the writer ALIVE
                    # — a dead writer thread with record() still buffering
                    # is an unbounded-memory failure mode
                    self._io_errors += 1
                    self._io_lost += len(batch) - written_lines
                    try:
                        if self._fh is not None:
                            self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
                    self._poisoned = True  # reopen on a FRESH segment
                    dirty = False
            now = time.monotonic()
            if dirty and (
                self.fsync_policy == "always"
                or stopping
                or (
                    self.fsync_policy == "interval"
                    and now - last_sync >= self.fsync_interval_s
                )
            ):
                if self.fsync_policy != "off":
                    self._fsync()
                dirty = False
                last_sync = now
            with self._cond:
                self._written += len(batch)
                self._cond.notify_all()
                if stopping and not self._buf:
                    return

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every record appended before this call has been
        processed by the writer and flushed to the OS.  Returns False on
        timeout, when the journal is disabled, or when any record was
        LOST to a write failure while waiting — callers using this as a
        durability barrier must not read success out of a failed disk."""
        with self._cond:
            if not self.enabled:
                return False
            target = self._appended
            lost0 = self._io_lost
            self._cond.notify_all()  # kick the writer out of its poll
            deadline = time.monotonic() + timeout
            while self._written < target:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
            return self._io_lost == lost0

    # -- introspection (/debug/journal) --------------------------------------

    def debug_state(self, tail_n: int = 50) -> dict:
        with self._cond:
            state = {
                "enabled": self.enabled,
                "dir": self.dir,
                "fsync": self.fsync_policy,
                "fsync_interval_s": self.fsync_interval_s,
                "max_segment_bytes": self.max_segment_bytes,
                "max_segments": self.max_segments,
                "next_seq": self._seq,
                "appended": self._appended,
                "written": self._written,
                "pending": len(self._buf),
                "dropped": self._dropped,
                "io_errors": self._io_errors,
                "io_lost_records": self._io_lost,
                "rotations": self._rotations,
                "pruned_segments": self._pruned,
                "tail": list(self._tail)[-tail_n:] if tail_n > 0 else [],
            }
        if state["dir"]:
            segs = []
            for p in segment_paths(state["dir"]):
                try:
                    segs.append(
                        {"file": os.path.basename(p),
                         "bytes": os.path.getsize(p)}
                    )
                except OSError:
                    continue
            state["segments"] = segs
        return state


# Process-global instance, same pattern as tracing.TRACER / metrics
# REGISTRY: emission sites import this and check .enabled first.
JOURNAL = Journal()
