"""Cross-process trace assembly: one request, every process's spans.

Spans die in per-process ``/traces`` rings — the router's ``fleet.route``
span lives in the scheduler/router process, the replica's
``serve.request``/``engine.step`` spans in the serving pod, and the
scheduler's placement spans in its own ring.  All of them share ONE W3C
trace id (the traceparent chain PRs 1/7 built), so assembling a request
end-to-end is a pull problem, not an instrumentation problem:
:class:`TraceAssembler` pulls ``/traces?trace=<id>`` from every
configured source, merges with the local tracer's ring, orders the spans
causally (parents before children, siblings by start time) and keeps the
result in a bounded LRU store — ``GET /debug/trace/<trace_id>`` then
renders one journey across processes even after the origin rings
recycled.

SLO-breach integration: a breach record carries exemplar trace ids;
:meth:`capture_async` pins those journeys by assembling them eagerly on
the assembler's worker thread (never the scrape or breach-detection
path), so the evidence for a p99 alert survives span pressure.
"""

from __future__ import annotations

import json
import queue
import socket
import threading
import time
from collections import OrderedDict
from typing import Optional

from ..tracing import TRACER

__all__ = ["TraceAssembler", "merged_sources"]


def merged_sources(*fns):
    """Compose several source callables — one per router shard — into
    the single list ``TraceAssembler`` pulls.  A sharded data plane
    (federation ``RouterRing``) runs one ``ReplicaSet`` per router, so
    the assembler must fold every shard's replica list or journeys that
    crossed shards resolve with holes; duplicate (host, port) entries
    (shards polling the same backends) pull once."""
    def fold():
        seen = set()
        out = []
        for fn in fns:
            for name, addr in list(fn() or []):
                key = tuple(addr)
                if key in seen:
                    continue
                seen.add(key)
                out.append((name, addr))
        return out
    return fold


def _pull_trace(
    addr: tuple[str, int], trace_id: str, timeout_s: float
) -> list[dict]:
    """GET /traces?trace=<id> from one source — the same 3-line raw
    exchange the router's health probe uses (dependency-free, obvious
    timeout semantics)."""
    with socket.create_connection(addr, timeout=timeout_s) as s:
        s.settimeout(timeout_s)
        s.sendall(
            f"GET /traces?trace={trace_id} HTTP/1.1\r\n"
            f"Host: {addr[0]}\r\nConnection: close\r\n\r\n".encode()
        )
        buf = b""
        while True:
            b = s.recv(65536)
            if not b:
                break
            buf += b
    head, _, body = buf.partition(b"\r\n\r\n")
    try:
        status = int(head.split(b" ", 2)[1])
    except (IndexError, ValueError):
        raise ConnectionError("malformed status line")
    if status != 200:
        raise ConnectionError(f"/traces answered {status}")
    payload = json.loads(body)
    spans = payload.get("spans")
    return spans if isinstance(spans, list) else []


def causal_order(spans: list[dict]) -> list[dict]:
    """Parents before children, siblings by start time.  Spans whose
    parent is outside the collected set (a remote parent the pull
    missed) rank as roots by their own start time — the order degrades
    to start-time sorting, never drops a span."""
    by_id = {s.get("span_id"): s for s in spans if s.get("span_id")}
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for s in spans:
        parent = s.get("parent_id") or ""
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    key = lambda s: (s.get("start_unix") or 0.0, s.get("span_id") or "")
    out: list[dict] = []
    stack = sorted(roots, key=key, reverse=True)
    seen: set = set()
    while stack:
        s = stack.pop()
        sid = s.get("span_id")
        if sid in seen:
            continue  # defensive: duplicate ids must not loop
        seen.add(sid)
        out.append(s)
        stack.extend(
            sorted(children.get(sid, ()), key=key, reverse=True)
        )
    return out


def local_trace_payload(trace_id: str, tracer=None) -> dict:
    """The assembler-less ``/debug/trace/<id>`` answer: THIS process's
    spans only, causally ordered, in the same shape ``assemble()``
    returns — every server's fallback shares this one construction so
    consumers can read ``sources``/``processes`` regardless of which
    port answered."""
    tracer = tracer if tracer is not None else TRACER
    spans = causal_order(tracer.trace(trace_id))
    for s in spans:
        s.setdefault("source", "local")
    return {
        "trace_id": trace_id,
        "spans": spans,
        "span_count": len(spans),
        "sources": ["local"] if spans else [],
        "processes": 1 if spans else 0,
    }


class TraceAssembler:
    """Bounded fleet-wide trace store fed by on-demand pulls.

    ``sources``: callable returning ``[(name, (host, port)), ...]`` —
    the CLI wires the router's live replica set here, so the pull list
    tracks scale-ups/downs; extra static sources (another scheduler)
    can ride the same list.  The local tracer is always a source
    (name ``local``)."""

    def __init__(
        self,
        sources=None,
        tracer=None,
        cap: int = 256,
        pull_timeout_s: float = 2.0,
    ):
        self.sources = sources or (lambda: [])
        self.tracer = tracer if tracer is not None else TRACER
        self.cap = max(8, int(cap))
        self.pull_timeout_s = pull_timeout_s
        self._lock = threading.Lock()
        self._store: "OrderedDict[str, dict]" = OrderedDict()
        self.assemblies = 0
        self.pulls = 0
        self.pull_errors = 0
        self.captured = 0  # breach-exemplar eager captures
        self._q: "queue.Queue" = queue.Queue(maxsize=64)
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- assembly ------------------------------------------------------------

    def assemble(self, trace_id: str, refresh: bool = True) -> dict:
        """Merge local + every source's spans for ``trace_id`` into one
        causally-ordered record.  ``refresh=False`` serves the cached
        assembly when present (the exemplar-capture path pinned it);
        otherwise sources are re-pulled and merged INTO any cached spans
        — a replica whose ring already evicted the trace cannot erase
        spans an earlier assembly saved."""
        if not refresh:
            with self._lock:
                cached = self._store.get(trace_id)
                if cached is not None:
                    self._store.move_to_end(trace_id)
                    return cached
        merged: dict[str, dict] = {}
        with self._lock:
            prev = self._store.get(trace_id)
            if prev is not None:
                for s in prev["spans"]:
                    merged[s.get("span_id")] = s
        for s in self.tracer.trace(trace_id):
            s.setdefault("source", "local")
            merged[s.get("span_id")] = s
        errors: dict[str, str] = {}
        for name, addr in list(self.sources() or []):
            self.pulls += 1
            try:
                for s in _pull_trace(
                    tuple(addr), trace_id, self.pull_timeout_s
                ):
                    s.setdefault("source", name)
                    # first writer wins: a span already captured (e.g.
                    # by the local tracer for an in-process source)
                    # keeps its original source tag
                    merged.setdefault(s.get("span_id"), s)
            except (OSError, ConnectionError, ValueError) as e:
                self.pull_errors += 1
                errors[name] = str(e)
        spans = causal_order(list(merged.values()))
        record = {
            "trace_id": trace_id,
            "spans": spans,
            "span_count": len(spans),
            "sources": sorted({
                s.get("source", "local") for s in spans
            }),
            "processes": len({s.get("source", "local") for s in spans}),
            "assembled_unix": round(time.time(), 3),
            "pull_errors": errors,
        }
        with self._lock:
            self._store[trace_id] = record
            self._store.move_to_end(trace_id)
            while len(self._store) > self.cap:
                self._store.popitem(last=False)
        self.assemblies += 1
        return record

    # -- breach-exemplar capture ---------------------------------------------

    def capture_async(self, trace_ids: list) -> None:
        """Queue exemplar trace ids for eager assembly on the worker
        thread (breach hooks run on the evaluate tick; the HTTP pulls
        must not stall it).  A full queue drops the capture — the trace
        may still assemble on demand while the rings hold it."""
        self._ensure_worker()
        for tid in trace_ids or []:
            if not tid:
                continue
            try:
                self._q.put_nowait(tid)
            except queue.Full:
                break

    def on_breach(self, rec: dict) -> None:
        """``SLO.breach_hooks`` shape: capture the breach's exemplars."""
        self.capture_async(rec.get("exemplars") or [])

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    tid = self._q.get(timeout=0.5)
                except queue.Empty:
                    continue
                try:
                    self.assemble(tid)
                    self.captured += 1
                except Exception:
                    pass  # capture is best-effort evidence pinning

        self._worker = threading.Thread(
            target=loop, name="trace-assembler", daemon=True
        )
        self._worker.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._worker = self._worker, None
        if t is not None:
            t.join(timeout=2)

    # -- introspection -------------------------------------------------------

    def debug_state(self) -> dict:
        with self._lock:
            stored = [
                {
                    "trace_id": tid,
                    "spans": rec["span_count"],
                    "processes": rec["processes"],
                    "assembled_unix": rec["assembled_unix"],
                }
                for tid, rec in self._store.items()
            ]
        return {
            "stored": len(stored),
            "cap": self.cap,
            "assemblies": self.assemblies,
            "pulls": self.pulls,
            "pull_errors": self.pull_errors,
            "captured": self.captured,
            "traces": stored[-16:],
        }
