"""Fleet-wide SLO plane: request journeys, error budgets, burn rate.

Every hop of a request already emits W3C-chained spans (client → router
→ replica → engine.step) and every control-plane decision is journaled,
yet nothing could answer the operator's first question — *is this
workload class meeting its latency SLO, and which requests blew it?* —
because spans die in per-process ``/traces`` rings and no surface
computes TTFT/TPOT/e2e against a declared objective.  This module is
that surface:

- **Request-journey records.**  The fleet router (the one vantage that
  sees client-perceived latency) calls :meth:`SloPlane.record_journey`
  once per routed request with queue wait, TTFT, per-token TPOT, e2e
  wall, hop overhead and journey events (prefill split, adoption,
  failover, breaker trips); serving replicas record their own vantage.
  The hot path follows the PROFILER discipline exactly: one GIL-atomic
  list append, cap-trimmed through a try-lock with the drop COUNTED
  (``tpu_slo_dropped_samples_total``) — all folding into per-class
  sliding windows happens lazily on reader threads (scrape, /debug/slo,
  the evaluate tick).

- **Declared objectives + burn rate.**  Per-class targets load from
  ``--slo-config`` / ``TPU_SLO_CONFIG`` / ``POST /slo/load`` as
  ``{"classes": {cls: {"ttft_p95_ms": 200, "e2e_p99_ms": 2000,
  "availability": 0.99, ...}}}``: ``<metric>_p<NN>_ms`` declares "NN% of
  requests must see <metric> ≤ that many ms", ``availability`` the ok
  fraction.  The error budget is ``1 - target``; the burn rate over a
  window is the violating fraction divided by the budget (burn 1.0 =
  consuming budget exactly as fast as sustainable).  Breach fires when
  BOTH the short and long windows burn past ``burn_threshold``
  (multi-window, so one slow request cannot page and a long regression
  cannot hide) with at least ``min_samples`` journeys in the short
  window; recovery when both drop back under.

- **Journal + exemplars.**  Breach/recovery/objective-load land as
  ``slo`` records in the decision journal — ANNOTATIONS (dense-seq
  audited, zero allocator mutation; ``what_if`` skips them) — and a
  breach record carries the exemplar trace ids of the concrete journeys
  that violated, so a p99 alert links straight to
  ``/debug/trace/<trace_id>`` (slo/assembly.py pulls those spans
  fleet-wide before per-process rings evict them; breach hooks let the
  wiring capture exemplars eagerly).

- **SLO-proactive scaling.**  :meth:`SloPlane.scaling_input` returns the
  burn posture as PURE data for the fleet autoscaler's
  ``PolicyEngine.evaluate`` — journaled inside ``fleet`` records and
  replayed by ``score_policy``, so scale-ups can trigger on budget burn
  before queue depth moves, advisory-safe like every other input.

Process-global instance ``SLO`` (TRACER/JOURNAL/PROFILER pattern):
emission sites check ``.enabled`` first — one attribute load when no
objectives are configured.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

from ..metrics import (
    REGISTRY,
    Counter,
    LazyGauge,
    _exact_quantile,
)

__all__ = [
    "SLO",
    "SloObjective",
    "SloPlane",
    "parse_objectives",
]

# latency metrics a journey can carry (availability is derived from ok)
LATENCY_METRICS = ("ttft", "tpot", "e2e", "queue", "hop")

SLO_LATENCY = REGISTRY.register(
    LazyGauge(
        "tpu_slo_latency_ms",
        "Per-class request-journey latency percentiles over the short "
        "SLO window, in ms, by metric (ttft/tpot/e2e/queue/hop) and "
        "quantile (p50/p95/p99) — folded from the journey ring at "
        "scrape time, the client-perceived numbers the declared "
        "objectives are judged against",
        ("wclass", "metric", "quantile"),
    )
)
SLO_BURN = REGISTRY.register(
    LazyGauge(
        "tpu_slo_burn_rate",
        "Error-budget burn rate per declared objective and window "
        "(short/long): violating fraction over the window divided by "
        "the objective's error budget (1 - target).  1.0 = consuming "
        "budget exactly as fast as sustainable; a breach journals when "
        "BOTH windows exceed the configured threshold",
        ("wclass", "objective", "window"),
    )
)
SLO_BREACHED = REGISTRY.register(
    LazyGauge(
        "tpu_slo_breached",
        "1 while the (class, objective) pair is in a journaled breach "
        "(multi-window burn above threshold), 0 once recovered — the "
        "alerting surface; the journaled `slo` record carries the "
        "exemplar trace ids",
        ("wclass", "objective"),
    )
)
SLO_EVENTS = REGISTRY.register(
    Counter(
        "tpu_slo_events_total",
        "SLO-plane lifecycle events: breach (burn alert tripped, "
        "journaled with exemplars), recover, objectives_loaded",
        ("event",),
    )
)
SLO_RECORDS = REGISTRY.register(
    Counter(
        "tpu_slo_records_total",
        "Request-journey records folded into the SLO windows, by "
        "vantage (router = client-perceived, replica = server-side)",
        ("vantage",),
    )
)
SLO_DROPPED = REGISTRY.register(
    Counter(
        "tpu_slo_dropped_samples_total",
        "Journey records discarded because the raw ring hit its cap "
        "with no reader folding it — non-zero means the SLO windows "
        "UNDERSTATE traffic by that many requests",
        ("reason",),
    )
)


def _num(val, what: str) -> float:
    """Config value → float with ONE error type: a null/list/string
    value must surface as the same ValueError a malformed key does
    (float(None) raises TypeError, which would otherwise escape every
    config error handler as a crash)."""
    try:
        return float(val)
    except (TypeError, ValueError):
        raise ValueError(f"{what} must be a number, got {val!r}") from None


class SloObjective:
    """One declared objective: ``target`` fraction of journeys must be
    good.  Latency objectives (``metric`` in LATENCY_METRICS) judge
    ``value <= threshold_ms``; the ``availability`` objective judges the
    journey's ``ok`` flag.  ``key`` is the config-file spelling
    (``ttft_p95_ms`` / ``availability``) used VERBATIM in journal
    records, metrics labels and /debug/slo — a fractional percentile
    like ``e2e_p99.5_ms`` keeps its declared name."""

    __slots__ = ("metric", "target", "threshold_ms", "key")

    def __init__(self, metric: str, target: float,
                 threshold_ms: Optional[float] = None,
                 key: Optional[str] = None):
        if metric != "availability" and metric not in LATENCY_METRICS:
            raise ValueError(f"unknown SLO metric {metric!r}")
        target = _num(target, "SLO target")
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"SLO target must be in (0, 1), got {target} — a target "
                "of 1.0 has zero error budget and every request is a page"
            )
        if metric != "availability":
            threshold_ms = _num(
                threshold_ms, f"latency objective {metric!r} threshold"
            )
            if threshold_ms <= 0:
                raise ValueError(
                    f"latency objective {metric!r} needs a positive "
                    "threshold_ms"
                )
            self.key = key or f"{metric}_p{target * 100:g}_ms"
        else:
            self.key = key or "availability"
        self.metric = metric
        self.target = target
        self.threshold_ms = (
            float(threshold_ms) if threshold_ms is not None else None
        )

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def violated(self, journey: tuple) -> Optional[bool]:
        """True/False verdict for one journey tuple, or None when the
        journey carries no value for this metric (a non-streamed
        completion has no TPOT — it must not count either way)."""
        if self.metric == "availability":
            return not journey[_J_OK]
        v = journey[_J_METRIC_IDX[self.metric]]
        if v is None:
            return None
        return v > self.threshold_ms

    def to_dict(self) -> dict:
        return {
            "metric": self.metric,
            "target": self.target,
            "threshold_ms": self.threshold_ms,
        }


def parse_objectives(spec: dict) -> list[SloObjective]:
    """One class's config dict → objectives.  Keys: ``<metric>_p<NN>_ms``
    (latency) and ``availability`` (fraction); unknown keys are errors —
    a typo'd objective silently never alerting is the worst outcome."""
    out: list[SloObjective] = []
    for key, val in sorted(spec.items()):
        if key == "availability":
            out.append(SloObjective("availability", _num(val, key)))
            continue
        parts = key.split("_")
        if (
            len(parts) == 3
            and parts[0] in LATENCY_METRICS
            and parts[1].startswith("p")
            and parts[2] == "ms"
        ):
            try:
                pct = float(parts[1][1:])
            except ValueError:
                raise ValueError(f"bad SLO objective key {key!r}")
            out.append(
                # the declared spelling IS the objective's identity:
                # journal records / metric labels / debug must name
                # exactly what the operator wrote (p99.5 stays p99.5)
                SloObjective(parts[0], pct / 100.0, _num(val, key),
                             key=key)
            )
            continue
        raise ValueError(
            f"unknown SLO objective key {key!r} (want "
            "<ttft|tpot|e2e|queue|hop>_p<NN>_ms or availability)"
        )
    if not out:
        raise ValueError("SLO class config declares no objectives")
    return out


# journey tuple layout (hot path appends tuples, not objects)
_J_T = 0
_J_VANTAGE = 1
_J_CLASS = 2
_J_OK = 3
_J_TTFT = 4
_J_TPOT = 5
_J_E2E = 6
_J_QUEUE = 7
_J_HOP = 8
_J_TOKENS = 9
_J_TRACE = 10
_J_REPLICA = 11
_J_KIND = 12
_J_TENANT = 13
_J_METRIC_IDX = {
    "ttft": _J_TTFT, "tpot": _J_TPOT, "e2e": _J_E2E,
    "queue": _J_QUEUE, "hop": _J_HOP,
}


class _ClassWindow:
    """Per-class sliding journey window (fold-path only: every mutation
    happens under the plane's fold lock).

    Raw journeys feed percentiles/exemplars/debug and are bounded two
    ways — by age (older than the long window prunes at fold) and by
    count (the deque cap).  BURN accounting deliberately does NOT read
    the raw deque: at high traffic the count cap would silently
    truncate the long window (4096 journeys at 100 rps cover ~41s —
    less than the short window — collapsing multi-window alerting into
    single-window paging).  Instead ``buckets`` holds time-bucketed
    per-objective (total, bad) counters: exact counts at any rate,
    memory bounded by window_long / bucket width per objective, with
    at most one bucket width of boundary slop."""

    __slots__ = ("journeys", "exemplars", "count", "violations",
                 "buckets")

    def __init__(self, cap: int):
        self.journeys: deque = deque(maxlen=cap)
        # objective key → recent violating trace ids (the breach
        # record's exemplar source)
        self.exemplars: dict[str, deque] = {}
        self.count = 0  # lifetime folded journeys
        self.violations: dict[str, int] = {}  # lifetime per objective
        # bucket index (t // bucket_s) → {objective key: [total, bad]}
        self.buckets: dict[int, dict[str, list]] = {}

    def fresh_exemplars(self, key: str, horizon: float) -> list:
        """Violating trace ids recorded at or after ``horizon`` — a
        breach must never cite journeys older than its own burn
        windows (their spans are long evicted and the evidence would
        point at the wrong requests)."""
        return [
            tid for t, tid in self.exemplars.get(key, ())
            if t >= horizon
        ]


class SloPlane:
    """Declared objectives + journey windows + burn-rate alerting.

    Concurrency model (mirrors profile.WorkloadProfiler): the HOT path —
    :meth:`record_journey` — is one GIL-atomic list append behind an
    ``enabled`` check; folding, percentile math, burn computation and
    breach journaling run under ``_fold_lock`` on READER threads (the
    evaluate tick, /debug/slo, the gauge refresher)."""

    def __init__(self, clock=time.monotonic):
        self.enabled = False
        # time source for journey stamps and burn buckets — the digital
        # twin (twin/) swaps in a VirtualClock so simulated journeys land
        # in simulated buckets; live planes keep time.monotonic, so
        # behavior there is bit-identical
        self.clock = clock
        # journal sink override: None = the process-global JOURNAL (live
        # planes); the twin wires its OWN Journal instance here so
        # simulated breach records can never land in — or burn seq
        # numbers of — the live flight recorder
        self.journal = None
        self.default_class = "default"
        self.window_short_s = 60.0
        self.window_long_s = 300.0
        self.burn_threshold = 1.0
        self.min_samples = 5
        self._cap = 20000  # raw-buffer bound, same stance as PROFILER
        self._window_cap = 4096  # raw journeys kept per class
        # burn-counter bucket width (recomputed at load_config so the
        # boundary slop stays a small fraction of the short window)
        self.bucket_s = 2.0
        self._exemplar_cap = 8
        self._buf: list[tuple] = []
        self.dropped = 0
        self._fold_lock = threading.Lock()
        self._classes: dict[str, _ClassWindow] = {}
        self._objectives: dict[str, list[SloObjective]] = {}
        self._breached: dict[tuple[str, str], dict] = {}
        self._recent: deque = deque(maxlen=64)  # full dicts for /debug
        self._folded = {"router": 0, "replica": 0}
        self.breaches = 0
        self.recoveries = 0
        self.journal_records = 0
        # breach hooks: called (record dict) AFTER the breach journals —
        # the CLI wires eager exemplar-trace capture here.  Fired on the
        # evaluate tick's thread (never the scrape path).
        self.breach_hooks: list = []
        self._eval_lock = threading.Lock()
        self._eval_at = 0.0
        self.min_eval_interval_s = 0.5
        self._ticker: Optional[threading.Thread] = None
        self._ticker_stop = threading.Event()
        SLO_LATENCY.refresher = self._refresh_gauges

    # -- configuration -------------------------------------------------------

    def load_config(self, spec: dict, journal: bool = True) -> dict:
        """Install objectives from a config dict::

            {"window_short_s": 60, "window_long_s": 300,
             "burn_threshold": 1.0, "min_samples": 5,
             "default_class": "default",
             "classes": {"serve": {"ttft_p95_ms": 200,
                                   "e2e_p99_ms": 2000,
                                   "availability": 0.99}}}

        Replaces ALL objectives (the policy-plane load-by-name stance);
        raises ValueError on any malformed entry, installing nothing.
        Returns the /debug/slo-shaped objective summary."""
        if not isinstance(spec, dict):
            raise ValueError("SLO config must be a JSON object")
        classes = spec.get("classes")
        if not isinstance(classes, dict) or not classes:
            raise ValueError('SLO config needs a non-empty "classes" map')
        parsed = {
            str(cls): parse_objectives(objs)
            for cls, objs in classes.items()
        }
        short = _num(
            spec.get("window_short_s", self.window_short_s),
            "window_short_s",
        )
        long_ = _num(
            spec.get("window_long_s", self.window_long_s),
            "window_long_s",
        )
        burn_thr = _num(
            spec.get("burn_threshold", self.burn_threshold),
            "burn_threshold",
        )
        min_samples = int(_num(
            spec.get("min_samples", self.min_samples), "min_samples"
        ))
        if not 0 < short < long_:
            raise ValueError(
                f"need 0 < window_short_s ({short}) < window_long_s "
                f"({long_})"
            )
        with self._fold_lock:
            self._objectives = parsed
            self.window_short_s = short
            self.window_long_s = long_
            # ≤ ~3% boundary slop on the short window; bucket scale
            # changed ⇒ existing bucket indices are meaningless
            self.bucket_s = max(0.05, short / 30.0)
            for win in self._classes.values():
                win.buckets.clear()
                # exemplars cite objectives that may no longer exist
                # (or have new thresholds): a breach after the swap
                # must only cite journeys judged under the NEW config
                win.exemplars.clear()
            self.burn_threshold = max(0.01, burn_thr)
            self.min_samples = max(1, min_samples)
            if spec.get("default_class"):
                self.default_class = str(spec["default_class"])
            self._breached.clear()
            self.enabled = True
        SLO_EVENTS.inc("objectives_loaded")
        summary = self.objectives_dict()
        if journal:
            JOURNAL = self._journal_sink()
            if JOURNAL.enabled:
                JOURNAL.record(
                    "slo", action="objectives", classes=summary,
                    window_short_s=self.window_short_s,
                    window_long_s=self.window_long_s,
                    burn_threshold=self.burn_threshold,
                )
                self.journal_records += 1
        return summary

    def _journal_sink(self):
        if self.journal is not None:
            return self.journal
        from ..journal import JOURNAL

        return JOURNAL

    def objectives_dict(self) -> dict:
        return {
            cls: {o.key: o.to_dict() for o in objs}
            for cls, objs in sorted(self._objectives.items())
        }

    def reset(self) -> None:
        """Drop every buffer/aggregate and disable (tests, CI soaks)."""
        with self._fold_lock:
            del self._buf[:]
            self.dropped = 0
            self._classes.clear()
            self._objectives = {}
            self._breached.clear()
            self._recent.clear()
            self._folded = {"router": 0, "replica": 0}
            self.breaches = self.recoveries = 0
            self.journal_records = 0
            self.enabled = False
            self.clock = time.monotonic
            self.journal = None
        del self.breach_hooks[:]

    # -- hot path ------------------------------------------------------------

    def record_journey(
        self,
        wclass: str = "",
        ok: bool = True,
        ttft_ms: Optional[float] = None,
        tpot_ms: Optional[float] = None,
        e2e_ms: Optional[float] = None,
        queue_ms: Optional[float] = None,
        hop_ms: Optional[float] = None,
        tokens: int = 0,
        trace_id: str = "",
        replica: str = "",
        kind: str = "",
        tenant: str = "",
        vantage: str = "router",
        events: Optional[list] = None,
    ) -> bool:
        """One request journey.  Cost when the plane is on: one tuple
        append (the PROFILER stance); returns False when disabled."""
        if not self.enabled:
            return False
        buf = self._buf
        buf.append((
            self.clock(), vantage,
            wclass or self.default_class, bool(ok),
            ttft_ms, tpot_ms, e2e_ms, queue_ms, hop_ms,
            int(tokens), trace_id, replica, kind, tenant,
            tuple(events) if events else (),
        ))
        if len(buf) > self._cap and self._fold_lock.acquire(blocking=False):
            # nothing is folding: trim like the TimedLock wait buffers —
            # try-acquire keeps this path non-blocking, and the drop is
            # COUNTED (never silently discard journeys)
            try:
                n = self._cap // 2
                del buf[:n]
                self.dropped += n
            finally:
                self._fold_lock.release()
        return True

    # -- fold path (reader threads) ------------------------------------------

    def _fold_locked(self, now: float) -> None:
        """Drain the raw ring into the per-class windows (caller holds
        ``_fold_lock``).  Slice-then-del is safe against concurrent
        hot-path appends landing at the tail (the TimedLock pattern)."""
        n = len(self._buf)
        rows = self._buf[:n]
        del self._buf[:n]
        folded = {"router": 0, "replica": 0}
        recent_rows: list[tuple] = []
        for row in rows:
            vantage = row[_J_VANTAGE]
            folded[vantage] = folded.get(vantage, 0) + 1
            cls = row[_J_CLASS]
            if cls not in self._objectives:
                # the class name arrives from the CLIENT's request body:
                # undeclared values collapse into the default class so
                # per-class state (and tpu_slo_* label cardinality) is
                # bounded by the operator's config, never by a client
                # cycling random strings (the fixed-verb-set stance the
                # HTTP layer takes for its own metric labels)
                cls = self.default_class
            win = self._classes.get(cls)
            if win is None:
                win = self._classes[cls] = _ClassWindow(self._window_cap)
            win.journeys.append(row)
            win.count += 1
            # burn counters + exemplars per objective.  Counters are
            # time-bucketed so burn never reads the count-capped raw
            # deque; only the ROUTER vantage contributes (one journey
            # must not count twice when both vantages record it).
            if vantage == "router":
                objs = self._objectives.get(cls, ())
                bucket = None
                if objs:
                    bidx = int(row[_J_T] / self.bucket_s)
                    bucket = win.buckets.get(bidx)
                    if bucket is None:
                        bucket = win.buckets[bidx] = {}
                for obj in objs:
                    verdict = obj.violated(row)
                    if verdict is None:
                        continue
                    cell = bucket.get(obj.key)
                    if cell is None:
                        cell = bucket[obj.key] = [0, 0]
                    cell[0] += 1
                    cell[1] += verdict
                    if verdict:
                        win.violations[obj.key] = (
                            win.violations.get(obj.key, 0) + 1
                        )
                        if row[_J_TRACE]:
                            ex = win.exemplars.get(obj.key)
                            if ex is None:
                                ex = win.exemplars[obj.key] = deque(
                                    maxlen=self._exemplar_cap
                                )
                            ex.append((row[_J_T], row[_J_TRACE]))
                recent_rows.append(row)
        # only the tail of the fold can survive the 64-entry recent
        # deque — building a 15-key dict per folded row would make a
        # post-burst fold (up to _cap rows) pay ~300x for nothing,
        # under the same lock readers and the hot-path trim contend on
        for row in recent_rows[-(self._recent.maxlen or 64):]:
            self._recent.append(self._journey_dict(row))
        # time-bound prune: journeys/buckets older than the long window
        # carry no signal and only slow the percentile sorts
        horizon = now - self.window_long_s
        for win in self._classes.values():
            while win.journeys and win.journeys[0][_J_T] < horizon:
                win.journeys.popleft()
            if win.buckets:
                dead = [
                    b for b in win.buckets
                    if (b + 1) * self.bucket_s < horizon
                ]
                for b in dead:
                    del win.buckets[b]
        for k, v in folded.items():
            self._folded[k] = self._folded.get(k, 0) + v
        dropped, self.dropped = self.dropped, 0
        # counter metrics outside would be nicer, but their own locks
        # suffice and the amounts are tiny; keep the call order simple
        for k, v in folded.items():
            if v:
                SLO_RECORDS.inc(k, value=float(v))
        if dropped:
            SLO_DROPPED.inc("journey_cap", value=float(dropped))

    @staticmethod
    def _journey_dict(row: tuple) -> dict:
        return {
            "t_mono": round(row[_J_T], 3),
            "vantage": row[_J_VANTAGE],
            "wclass": row[_J_CLASS],
            "tenant": row[_J_TENANT],
            "ok": row[_J_OK],
            "ttft_ms": row[_J_TTFT],
            "tpot_ms": row[_J_TPOT],
            "e2e_ms": row[_J_E2E],
            "queue_ms": row[_J_QUEUE],
            "hop_ms": row[_J_HOP],
            "tokens": row[_J_TOKENS],
            "trace_id": row[_J_TRACE],
            "replica": row[_J_REPLICA],
            "kind": row[_J_KIND],
            "events": list(row[14]),
        }

    def _burn_locked(self, now: float) -> dict:
        """Per-class, per-objective burn rates over both windows from
        the time-bucketed counters (caller holds ``_fold_lock``; fold
        first).  Exact counts at any traffic rate — burn never reads
        the count-capped raw deque — with at most one bucket width of
        window-boundary slop."""
        out: dict[str, dict] = {}
        t_short = now - self.window_short_s
        t_long = now - self.window_long_s
        for cls, objs in sorted(self._objectives.items()):
            win = self._classes.get(cls)
            entry = out[cls] = {}
            counts = {
                obj.key: [0, 0, 0, 0]  # tot_s, bad_s, tot_l, bad_l
                for obj in objs
            }
            if win is not None:
                for bidx, bucket in win.buckets.items():
                    b_end = (bidx + 1) * self.bucket_s
                    if b_end <= t_long:
                        continue
                    in_short = b_end > t_short
                    for key, (tot, bad) in bucket.items():
                        c = counts.get(key)
                        if c is None:
                            continue  # stale key from replaced config
                        c[2] += tot
                        c[3] += bad
                        if in_short:
                            c[0] += tot
                            c[1] += bad
            for obj in objs:
                tot_s, bad_s, tot_l, bad_l = counts[obj.key]
                budget = obj.budget
                burn_s = (bad_s / tot_s / budget) if tot_s else 0.0
                burn_l = (bad_l / tot_l / budget) if tot_l else 0.0
                entry[obj.key] = {
                    "burn_short": round(burn_s, 4),
                    "burn_long": round(burn_l, 4),
                    "bad_short": bad_s,
                    "total_short": tot_s,
                    "bad_long": bad_l,
                    "total_long": tot_l,
                    "target": obj.target,
                    "threshold_ms": obj.threshold_ms,
                }
        return out

    # -- evaluation (the alerting tick) --------------------------------------

    def evaluate(self, now: Optional[float] = None,
                 force: bool = False) -> dict:
        """Fold, compute burn, journal breach/recovery transitions, fire
        breach hooks.  Rate-limited (``min_eval_interval_s``) so both an
        autoscaler tick and a standalone ticker can call it freely.
        Returns the posture dict (:meth:`posture`).  Runs on background
        threads — never wire it into the scrape path (the gauge
        refresher is the side-effect-free sibling)."""
        now = self.clock() if now is None else now
        if not self.enabled:
            return {"burning": False, "breached": []}
        with self._eval_lock:
            if not force and now - self._eval_at < self.min_eval_interval_s:
                return self.posture()
            self._eval_at = now
            transitions: list[dict] = []
            with self._fold_lock:
                self._fold_locked(now)
                burn = self._burn_locked(now)
                thr = self.burn_threshold
                for cls, objs in burn.items():
                    win = self._classes.get(cls)
                    for key, b in objs.items():
                        pair = (cls, key)
                        burning = (
                            b["burn_short"] >= thr
                            and b["burn_long"] >= thr
                            and b["total_short"] >= self.min_samples
                        )
                        was = pair in self._breached
                        if burning and not was:
                            exemplars = win.fresh_exemplars(
                                key, now - self.window_long_s
                            ) if win is not None else []
                            rec = {
                                "action": "breach",
                                "wclass": cls,
                                "objective": key,
                                **b,
                                "burn_threshold": thr,
                                "window_short_s": self.window_short_s,
                                "window_long_s": self.window_long_s,
                                "exemplars": exemplars,
                            }
                            self._breached[pair] = rec
                            self.breaches += 1
                            transitions.append(rec)
                        elif was and not burning and (
                            b["burn_short"] < thr and b["burn_long"] < thr
                        ):
                            self._breached.pop(pair, None)
                            self.recoveries += 1
                            transitions.append({
                                "action": "recover",
                                "wclass": cls,
                                "objective": key,
                                **b,
                                "burn_threshold": thr,
                            })
        # journal + hooks OUTSIDE the fold lock: the journal's own lock
        # suffices, and a hook doing HTTP must never block a folding
        # scraper behind it
        if transitions:
            JOURNAL = self._journal_sink()
            for rec in transitions:
                SLO_EVENTS.inc(rec["action"])
                if JOURNAL.enabled:
                    JOURNAL.record("slo", **rec)
                    self.journal_records += 1
                if rec["action"] == "breach":
                    for hook in list(self.breach_hooks):
                        try:
                            hook(rec)
                        except Exception:
                            pass  # exemplar capture is best-effort
        return self.posture()

    def posture(self) -> dict:
        """The autoscaler's pure input: compact burn posture (plain data
        — journaled verbatim inside ``fleet`` records and replayed by
        ``score_policy``)."""
        with self._fold_lock:
            breached = [
                {
                    "wclass": cls,
                    "objective": key,
                    "burn_short": rec.get("burn_short"),
                    "burn_long": rec.get("burn_long"),
                }
                for (cls, key), rec in sorted(self._breached.items())
            ][:8]
        return {"burning": bool(breached), "breached": breached}

    def scaling_input(self) -> Optional[dict]:
        """``Autoscaler(slo_provider=SLO.scaling_input)``: evaluate
        (rate-limited) then return the posture; None while no objectives
        are configured, so journaled ``fleet`` records stay unchanged
        for deployments without an SLO plane."""
        if not self.enabled:
            return None
        return self.evaluate()

    # -- read APIs -----------------------------------------------------------

    def _percentiles_locked(self, now: float) -> dict:
        t_short = now - self.window_short_s
        out: dict[str, dict] = {}
        for cls, win in sorted(self._classes.items()):
            rows = [r for r in win.journeys if r[_J_T] >= t_short]
            if not rows:
                continue
            entry: dict = {"samples": len(rows)}
            ok_n = sum(1 for r in rows if r[_J_OK])
            entry["ok_frac"] = round(ok_n / len(rows), 4)
            for metric, idx in _J_METRIC_IDX.items():
                vals = sorted(
                    r[idx] for r in rows if r[idx] is not None
                )
                if not vals:
                    continue
                entry[metric + "_ms"] = {
                    "p50": round(_exact_quantile(vals, 0.5), 3),
                    "p95": round(_exact_quantile(vals, 0.95), 3),
                    "p99": round(_exact_quantile(vals, 0.99), 3),
                }
            out[cls] = entry
        return out

    def debug_state(self) -> dict:
        """The /debug/slo payload (folds first)."""
        now = self.clock()
        with self._fold_lock:
            if self.enabled:
                self._fold_locked(now)
            burn = self._burn_locked(now) if self.enabled else {}
            pct = self._percentiles_locked(now)
            breached = {
                f"{cls}:{key}": dict(rec)
                for (cls, key), rec in sorted(self._breached.items())
            }
            ex_horizon = now - self.window_long_s
            exemplars = {}
            for cls, win in sorted(self._classes.items()):
                fresh = {
                    k: win.fresh_exemplars(k, ex_horizon)
                    for k in sorted(win.exemplars)
                }
                fresh = {k: v for k, v in fresh.items() if v}
                if fresh:
                    exemplars[cls] = fresh
            recent = list(self._recent)[-16:]
            folded = dict(self._folded)
            pending = len(self._buf)
        return {
            "enabled": self.enabled,
            "default_class": self.default_class,
            "window_short_s": self.window_short_s,
            "window_long_s": self.window_long_s,
            "burn_threshold": self.burn_threshold,
            "min_samples": self.min_samples,
            "objectives": self.objectives_dict(),
            "windows": pct,
            "burn": burn,
            "breached": breached,
            "breaches": self.breaches,
            "recoveries": self.recoveries,
            "journal_records": self.journal_records,
            "exemplars": exemplars,
            "recent": recent,
            "folded": folded,
            "pending": pending,
        }

    # -- metrics export (LazyGauge refresher; scrape-time only) --------------

    def _refresh_gauges(self) -> None:
        # side-effect-free sibling of evaluate(): fold + compute only —
        # journaling and hooks belong to the tick thread, never a scrape
        if not self.enabled:
            return
        now = self.clock()
        with self._fold_lock:
            self._fold_locked(now)
            burn = self._burn_locked(now)
            pct = self._percentiles_locked(now)
            breached = set(self._breached)
        lat: dict[tuple[str, ...], float] = {}
        for cls, entry in pct.items():
            for metric in LATENCY_METRICS:
                q = entry.get(metric + "_ms")
                if q:
                    for qk, v in q.items():
                        lat[(cls, metric, qk)] = v
        burns: dict[tuple[str, ...], float] = {}
        states: dict[tuple[str, ...], float] = {}
        for cls, objs in burn.items():
            for key, b in objs.items():
                burns[(cls, key, "short")] = b["burn_short"]
                burns[(cls, key, "long")] = b["burn_long"]
                states[(cls, key)] = 1.0 if (cls, key) in breached else 0.0
        # whole-dict swap per gauge (the PROFILER stance): a racing
        # scrape sees either the old series set or the new one
        SLO_LATENCY.replace(lat)
        SLO_BURN.replace(burns)
        SLO_BREACHED.replace(states)

    # -- ticker --------------------------------------------------------------

    def start_ticker(self, interval_s: float = 5.0) -> "SloPlane":
        """Background evaluate loop for deployments where no autoscaler
        tick drives :meth:`scaling_input` (``--fleet=router`` or a bare
        replica).  Idempotent."""
        if self._ticker is not None:
            return self
        self._ticker_stop.clear()

        def loop():
            while not self._ticker_stop.wait(max(0.2, interval_s)):
                try:
                    self.evaluate()
                except Exception:
                    pass  # alerting must never kill its own thread

        self._ticker = threading.Thread(
            target=loop, name="slo-ticker", daemon=True
        )
        self._ticker.start()
        return self

    def stop_ticker(self) -> None:
        self._ticker_stop.set()
        t, self._ticker = self._ticker, None
        if t is not None:
            t.join(timeout=2)


def load_config_source(raw: str) -> dict:
    """``--slo-config`` / ``TPU_SLO_CONFIG`` value → config dict: inline
    JSON, or ``@path`` to a JSON file."""
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    spec = json.loads(raw)
    if not isinstance(spec, dict):
        raise ValueError("SLO config must be a JSON object")
    return spec


def configure_from_env() -> None:
    """Apply ``TPU_SLO_CONFIG`` when set (JSON or @file) — subprocesses
    (bench sections, check tools, replica pods) need no flag plumbing.
    A malformed env config must not poison every import; the CLI
    surfaces the parse error for the flag path."""
    raw = os.environ.get("TPU_SLO_CONFIG", "")
    if not raw:
        return
    try:
        SLO.load_config(load_config_source(raw), journal=False)
    except (ValueError, TypeError, OSError, json.JSONDecodeError):
        pass


SLO = SloPlane()
configure_from_env()
