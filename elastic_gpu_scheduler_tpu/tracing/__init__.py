"""End-to-end scheduling trace & decision audit (stdlib only).

The control plane makes multi-stage placement decisions (filter →
priorities → gang admission → bind → device-plugin Allocate) whose
outcomes were previously visible only as aggregate histograms
(metrics/__init__.py).  This module adds per-decision provenance:

- **Spans.**  A thread-safe ring-buffer tracer with W3C-style trace/span
  ids, wall + monotonic timestamps and structured attributes.  Finished
  spans land in a bounded deque (old traces evict FIFO — a long-lived
  scheduler never grows without limit), EXCEPT spans of **pinned**
  traces: a trace with an open pod root (and any trace explicitly
  pinned via :meth:`Tracer.pin`, e.g. a long-lived SSE stream) parks
  its finished spans in a separate bounded store so span pressure can
  no longer evict a live request's history mid-flight; pinned-overflow
  evictions are counted in ``tpu_metrics_dropped_samples_total``
  (reason ``trace_pin_cap``), never silent.  Export is Chrome
  trace-event JSON (open in Perfetto) or a per-trace JSON tree, both
  served by ``/traces`` (server/routes.py).

- **Pod-scoped traces.**  kube-scheduler's verbs arrive as independent
  HTTP requests with no trace headers, so the tracer keeps a bounded
  registry of per-pod root spans: the first filter for a pod opens its
  trace, every later verb for the same pod joins it, and bind (or
  registry eviction) closes it.  One pod = one trace spanning all verbs.

- **Propagation.**  ``traceparent`` carries context across process
  boundaries in the standard ``00-<trace>-<span>-<flags>`` form:
  HTTP header (extender verbs, inference requests), pod annotation
  ``elasticgpu.io/traceparent`` (written with the bind-time allocation
  ledger, so the on-node side can continue the scheduling trace), and
  gRPC metadata (device-plugin Allocate).

- **Decision audit.**  ``ScheduleAudit`` records each verb's PER-NODE
  verdict — the score, or the rejection reason with the failed
  constraint — keyed by pod.  ``/debug/schedule/<pod>`` renders the
  human-readable "why did this pod land on that node" answer.

- **Sampling knob.**  ``TPU_TRACE_SAMPLE`` (or ``Tracer.configure``):
  1.0 traces everything (default — the control plane's verb rate is
  trivially low), 0 < p < 1 head-samples per trace, 0 disables.  When a
  trace is not sampled every span call returns the shared no-op span:
  no ids, no clock reads, no locks — the hot path pays one attribute
  load and one comparison.

- **Profile sample spans.**  The workload-profiling observatory
  (``profile/``) cross-links measured behavior into the decision trail:
  paced ``engine.step`` spans carry a ``tokens_per_sec`` attribute when
  profiling is on, and each periodic journal flush of the per-class
  profiles emits a ``profile.flush`` span (class/pair counts) so
  ``/traces`` shows WHEN each recorded profile snapshot was taken
  relative to the placements it will re-score.

- **Fleet spans.**  The serving fleet (``fleet/``) extends the chain to
  the front door: every routed request opens a ``fleet.route`` span
  (replica, routing kind, hop overhead) as a child of the client's
  traceparent, and ITS context becomes the backend request's header —
  client → router → replica ``serve.request`` → ``engine.step`` is one
  W3C trace.  Autoscaler actions trace as ``fleet.scale_up`` /
  ``fleet.scale_down``; resize transactions as ``fleet.resize`` (the
  ``resize`` journal record carries the trace id).

The reference has none of this (its pprof mount is aggregate-only);
contention-aware schedulers (BandPilot, Gavel — PAPERS.md) rely on
exactly this per-decision provenance to debug placement quality.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

__all__ = [
    "Span",
    "SpanContext",
    "Tracer",
    "ScheduleAudit",
    "TRACER",
    "AUDIT",
    "TRACEPARENT_HEADER",
    "format_traceparent",
    "parse_traceparent",
]

TRACEPARENT_HEADER = "traceparent"

# one Random instance behind a lock would serialize span starts; os.urandom
# is kernel-backed and thread-safe, and span creation is verb-rate (not
# chip-rate), so two small reads per span are in the noise
def _gen_trace_id() -> str:
    return os.urandom(16).hex()


def _gen_span_id() -> str:
    return os.urandom(8).hex()


class SpanContext:
    """Immutable (trace_id, span_id, sampled) triple — what propagates."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def traceparent(self) -> str:
        return format_traceparent(self)


def format_traceparent(ctx) -> str:
    """W3C traceparent: version 00, 16-byte trace id, 8-byte span id,
    flags (01 = sampled)."""
    if not ctx:
        return ""
    flags = "01" if getattr(ctx, "sampled", True) else "00"
    return f"00-{ctx.trace_id}-{ctx.span_id}-{flags}"


_HEX = frozenset("0123456789abcdef")


def _is_hex(s: str, n: int) -> bool:
    # strict per-character check: int(x, 16) tolerates underscores and
    # sign prefixes, which would re-emit malformed ids downstream
    return len(s) == n and all(c in _HEX for c in s)


def parse_traceparent(value: str) -> Optional[SpanContext]:
    """``00-<32 hex>-<16 hex>-<2 hex>`` → SpanContext, or None on any
    malformation (a bad header must never fail the verb carrying it,
    and must never be propagated verbatim to spec-compliant parsers)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if not (
        _is_hex(version, 2)
        and _is_hex(trace_id, 32)
        and _is_hex(span_id, 16)
        and _is_hex(flags, 2)
    ):
        return None
    if version == "ff":  # forbidden by the W3C spec
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id, sampled=bool(int(flags, 16) & 1))


class _NoopSpan:
    """Shared do-nothing span for the sampled-out path: every method is a
    constant return, __bool__ is False so callers can branch, and the
    context-manager protocol works so ``with TRACER.span(...)`` costs
    nothing extra when tracing is off."""

    __slots__ = ()

    trace_id = ""
    span_id = ""
    name = ""

    def __bool__(self) -> bool:
        return False

    def set_attr(self, key, value) -> "_NoopSpan":
        return self

    def event(self, name, **attrs) -> "_NoopSpan":
        return self

    def context(self) -> Optional[SpanContext]:
        return None

    def traceparent(self) -> str:
        return ""

    def end(self, status: str = "ok") -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class Span:
    """One timed operation.  Mutation is single-writer by convention (the
    thread that opened the span); ``event`` uses GIL-atomic list appends
    so commit-pool threads can annotate a committer's span safely."""

    __slots__ = (
        "tracer", "trace_id", "span_id", "parent_id", "name",
        "t_wall", "t0", "duration", "attrs", "events", "status",
        "_on_stack",
    )

    def __init__(self, tracer, trace_id, parent_id, name, attrs=None):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = _gen_span_id()
        self.parent_id = parent_id
        self.name = name
        self.t_wall = time.time()
        self.t0 = time.perf_counter()
        self.duration: Optional[float] = None  # None while open
        self.attrs: dict = dict(attrs) if attrs else {}
        self.events: list = []
        self.status = "ok"
        self._on_stack = False

    def __bool__(self) -> bool:
        return True

    def set_attr(self, key, value) -> "Span":
        self.attrs[key] = value
        return self

    def event(self, name, **attrs) -> "Span":
        self.events.append(
            {"name": name, "t": time.perf_counter() - self.t0, **attrs}
        )
        return self

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def traceparent(self) -> str:
        return format_traceparent(self.context())

    def end(self, status: Optional[str] = None) -> None:
        if self.duration is not None:
            return  # idempotent: double-end keeps the first timing
        self.duration = time.perf_counter() - self.t0
        if status is not None:
            self.status = status
        self.tracer._finish(self)

    def __enter__(self) -> "Span":
        self.tracer._push(self)
        self._on_stack = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._on_stack:
            self.tracer._pop(self)
            self._on_stack = False
        if exc_type is not None:
            self.set_attr("error", f"{exc_type.__name__}: {exc}")
            self.end(status="error")
        else:
            self.end()
        return False

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_unix": round(self.t_wall, 6),
            "duration_ms": (
                round(self.duration * 1000, 3)
                if self.duration is not None
                else None
            ),
            "status": self.status,
            "attrs": self.attrs,
            "events": [
                {**e, "t": round(e["t"] * 1000, 3)} for e in self.events
            ],
        }


class Tracer:
    """Ring-buffer tracer.

    Concurrency model: finished spans append into a ``deque(maxlen=N)``
    under one small lock (append + evict is O(1)); the per-thread active
    span stack is ``threading.local`` (no lock); the pod-root registry is
    an OrderedDict under the same lock (get-or-create is rare — once per
    pod per scheduling attempt)."""

    def __init__(self, capacity: int = 4096, sample: Optional[float] = None,
                 pod_capacity: int = 2048, pinned_capacity: int = 4096):
        if sample is None:
            try:
                sample = float(os.environ.get("TPU_TRACE_SAMPLE", "1"))
            except ValueError:
                sample = 1.0
        self.sample = max(0.0, min(1.0, sample))
        self.capacity = capacity
        self.pod_capacity = pod_capacity
        self.pinned_capacity = pinned_capacity
        self._spans: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()
        # pod key → open root Span (bounded FIFO: an evicted root is
        # force-closed so it still shows up in the ring)
        self._pod_roots: "OrderedDict[str, Span]" = OrderedDict()
        self.dropped = 0  # spans evicted from the ring (telemetry)
        # pinned traces: trace_id → pin count.  A pinned trace's
        # finished spans park in _pinned_spans instead of the FIFO ring,
        # so span pressure cannot drop a LIVE request's history
        # mid-flight (open pod roots pin automatically; long streams pin
        # explicitly).  Bounded by pinned_capacity across all traces —
        # overflow evicts the oldest pinned span and COUNTS it
        # (tpu_metrics_dropped_samples_total{reason="trace_pin_cap"}).
        self._pinned: dict[str, int] = {}
        self._pinned_spans: dict[str, list] = {}
        self._pin_ring: deque = deque()  # append-order trace_id tokens
        self._pin_count = 0
        self.dropped_pinned = 0  # pinned-overflow evictions (telemetry)

    # -- config --------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0

    def configure(self, sample: float) -> None:
        """Set the sampling rate (0 disables; the knob behind
        ``--trace-sample`` / ``TPU_TRACE_SAMPLE``)."""
        self.sample = max(0.0, min(1.0, sample))

    def reset(self) -> None:
        """Drop all state (tests)."""
        with self._lock:
            self._spans.clear()
            self._pod_roots.clear()
            self.dropped = 0
            self._pinned.clear()
            self._pinned_spans.clear()
            self._pin_ring.clear()
            self._pin_count = 0
            self.dropped_pinned = 0

    # -- span lifecycle ------------------------------------------------------

    def _sampled(self) -> bool:
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        # head sampling at root-span creation; os.urandom avoids sharing
        # a locked Random instance across verb threads
        return int.from_bytes(os.urandom(2), "big") / 65536.0 < self.sample

    def span(self, name: str, parent=None, **attrs):
        """Open a span.  ``parent``: a Span, SpanContext, traceparent
        string, or None (→ the thread's current span, else a new trace).
        Returns NOOP_SPAN when tracing is disabled or the trace was not
        sampled — use as a context manager either way."""
        if self.sample <= 0.0:
            return NOOP_SPAN
        ctx = self._resolve_parent(parent)
        if ctx is None:
            # new root: head-sampling decision happens here
            if self.sample < 1.0 and not self._sampled():
                return NOOP_SPAN
            return Span(self, _gen_trace_id(), "", name, attrs)
        if not ctx.sampled:
            return NOOP_SPAN
        return Span(self, ctx.trace_id, ctx.span_id, name, attrs)

    def point(self, name: str, parent=None, **attrs):
        """Zero-duration finished span (an instant marker another thread
        can drop into a remote trace without owning an open span)."""
        sp = self.span(name, parent=parent, **attrs)
        sp.end()
        return sp

    def _resolve_parent(self, parent) -> Optional[SpanContext]:
        if parent is None:
            cur = self.current()
            return cur.context() if cur is not None else None
        if isinstance(parent, Span):
            return parent.context()
        if isinstance(parent, SpanContext):
            return parent
        if isinstance(parent, str):
            return parse_traceparent(parent)
        if isinstance(parent, _NoopSpan):
            # child of an unsampled span stays unsampled
            return SpanContext("0" * 32, "0" * 16, sampled=False)
        return None

    def _finish(self, span: Span) -> None:
        overflowed = 0
        with self._lock:
            if span.trace_id in self._pinned:
                # pinned trace: park the span where FIFO pressure from
                # OTHER traces cannot evict it while the request lives
                self._pinned_spans.setdefault(span.trace_id, []).append(
                    span
                )
                self._pin_ring.append(span.trace_id)
                self._pin_count += 1
                while self._pin_count > self.pinned_capacity:
                    tid = self._pin_ring.popleft()
                    lst = self._pinned_spans.get(tid)
                    if not lst:
                        continue  # stale token (trace already unpinned)
                    lst.pop(0)
                    if not lst:
                        self._pinned_spans.pop(tid, None)
                    self._pin_count -= 1
                    self.dropped_pinned += 1
                    overflowed += 1
            else:
                if len(self._spans) == self._spans.maxlen:
                    self.dropped += 1
                self._spans.append(span)
        if overflowed:
            # even pinned storage is bounded; the overflow is COUNTED
            # (never silently discard samples).  Lazy import: tracing
            # stays importable without the metrics module loaded first.
            from ..metrics import METRICS_DROPPED

            METRICS_DROPPED.inc("trace_pin_cap", value=float(overflowed))

    # -- trace pinning -------------------------------------------------------

    def pin(self, trace_id: str) -> None:
        """Protect ``trace_id``'s finished spans from FIFO eviction
        until :meth:`unpin`.  Counted (nested pins are legal: the pod
        registry and an SSE handler may pin the same trace)."""
        if not trace_id:
            return
        with self._lock:
            self._pinned[trace_id] = self._pinned.get(trace_id, 0) + 1

    def unpin(self, trace_id: str) -> None:
        """Release one pin; at zero the trace's parked spans rejoin the
        ordinary ring (subject to its normal FIFO bound)."""
        if not trace_id:
            return
        with self._lock:
            n = self._pinned.get(trace_id, 0) - 1
            if n > 0:
                self._pinned[trace_id] = n
                return
            self._pinned.pop(trace_id, None)
            released = self._pinned_spans.pop(trace_id, None)
            if released:
                self._pin_count -= len(released)
                # purge this trace's ring tokens NOW: leaving them would
                # grow the ring one stale token per released span forever
                # (the overflow loop only runs past pinned_capacity), and
                # a later RE-pin of the same trace id would let a stale
                # token evict one of the new trace's spans prematurely.
                # O(ring) per trace close; the purge keeps the ring
                # bounded by _pin_count, so the scan itself stays small.
                self._pin_ring = deque(
                    t for t in self._pin_ring if t != trace_id
                )
                for sp in released:
                    if len(self._spans) == self._spans.maxlen:
                        self.dropped += 1
                    self._spans.append(sp)

    def pinned_spans(self) -> list:
        with self._lock:
            return [
                sp for lst in self._pinned_spans.values() for sp in lst
            ]

    # thread-local active-span stack (context-manager protocol only)

    def _push(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)

    def _pop(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack and stack[-1] is span:
            stack.pop()
        elif stack is not None:
            try:
                stack.remove(span)
            except ValueError:
                pass

    def current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def current_traceparent(self) -> str:
        cur = self.current()
        return cur.traceparent() if cur is not None else ""

    # -- pod-scoped traces ---------------------------------------------------
    #
    # kube-scheduler's filter/priorities/bind are independent HTTP calls;
    # the pod key is the join key.  First touch opens the pod's root span,
    # bind (or FIFO eviction) closes it.

    def pod_span(self, pod_key: str, parent=None) -> Span:
        """Get-or-create the pod's open root span.

        The head-sampling decision is PER TRACE: an unsampled roll is
        memoized (the shared no-op span occupies the registry slot), so
        a later verb for the same pod cannot re-roll and produce a trace
        that starts at bind with no filter/priorities history."""
        if self.sample <= 0.0:
            return NOOP_SPAN
        with self._lock:
            sp = self._pod_roots.get(pod_key)
            if sp is not None:
                self._pod_roots.move_to_end(pod_key)
                return sp
        ctx = self._resolve_parent(parent)
        if (ctx is not None and not ctx.sampled) or (
            ctx is None and self.sample < 1.0 and not self._sampled()
        ):
            sp = NOOP_SPAN  # memoized negative decision
        else:
            sp = Span(
                self,
                ctx.trace_id if ctx else _gen_trace_id(),
                ctx.span_id if ctx else "",
                f"schedule {pod_key}",
                {"pod": pod_key},
            )
        evicted = None
        with self._lock:
            cur = self._pod_roots.get(pod_key)
            if cur is not None:  # lost the creation race
                return cur
            self._pod_roots[pod_key] = sp
            if isinstance(sp, Span):
                # an OPEN pod trace pins itself: its already-finished
                # verb spans must survive span pressure until bind (or
                # registry eviction) closes the trace
                self._pinned[sp.trace_id] = (
                    self._pinned.get(sp.trace_id, 0) + 1
                )
            if len(self._pod_roots) > self.pod_capacity:
                _, evicted = self._pod_roots.popitem(last=False)
        if evicted is not None:
            evicted.end(status="evicted")
            if isinstance(evicted, Span):
                self.unpin(evicted.trace_id)
        return sp

    def pod_context(self, pod_key: str) -> Optional[SpanContext]:
        """The pod's trace context if a trace is open, else None (never
        creates — the controller uses this so resyncs don't mint traces
        for pods that were never filtered)."""
        with self._lock:
            sp = self._pod_roots.get(pod_key)
        return sp.context() if sp is not None else None

    def pod_traceparent(self, pod_key: str) -> str:
        ctx = self.pod_context(pod_key)
        return format_traceparent(ctx) if ctx is not None else ""

    def finish_pod(self, pod_key: str, status: str = "ok") -> None:
        with self._lock:
            sp = self._pod_roots.pop(pod_key, None)
        if sp is not None:
            sp.end(status=status)
            if isinstance(sp, Span):
                self.unpin(sp.trace_id)

    # -- export --------------------------------------------------------------

    def finished(self) -> list:
        with self._lock:
            out = list(self._spans)
            for lst in self._pinned_spans.values():
                out.extend(lst)
            return out

    def open_pod_roots(self) -> list:
        with self._lock:
            return [
                s
                for s in self._pod_roots.values()
                if not isinstance(s, _NoopSpan)  # memoized unsampled rolls
            ]

    def traces(self, limit: int = 50) -> list:
        """Most-recent-first trace summaries assembled from the ring
        (plus still-open pod roots, so an unbound pod is visible)."""
        spans = self.finished() + self.open_pod_roots()
        by_trace: "OrderedDict[str, list]" = OrderedDict()
        for sp in spans:
            by_trace.setdefault(sp.trace_id, []).append(sp)
        out = []
        for trace_id, group in by_trace.items():
            group.sort(key=lambda s: s.t_wall)
            root = next((s for s in group if not s.parent_id), group[0])
            t_end = max(
                (s.t_wall + (s.duration or 0.0)) for s in group
            )
            out.append({
                "trace_id": trace_id,
                "name": root.name,
                "start_unix": round(group[0].t_wall, 6),
                "duration_ms": round((t_end - group[0].t_wall) * 1000, 3),
                "spans": len(group),
                "open": any(s.duration is None for s in group),
                "status": (
                    "error"
                    if any(s.status == "error" for s in group)
                    else root.status
                ),
            })
        out.sort(key=lambda t: -t["start_unix"])
        return out[:limit]

    def trace(self, trace_id: str) -> list:
        """Every span of one trace, start-ordered, as dicts."""
        spans = [
            sp
            for sp in self.finished() + self.open_pod_roots()
            if sp.trace_id == trace_id
        ]
        spans.sort(key=lambda s: s.t_wall)
        return [sp.to_dict() for sp in spans]

    def chrome_trace(self, trace_id: Optional[str] = None) -> dict:
        """Chrome trace-event JSON (Perfetto / chrome://tracing).  Spans
        become complete ("X") events on one lane per trace; span events
        become instant ("i") markers."""
        spans = self.finished() + self.open_pod_roots()
        if trace_id is not None:
            spans = [sp for sp in spans if sp.trace_id == trace_id]
        lanes: dict[str, int] = {}
        events = []
        for sp in sorted(spans, key=lambda s: s.t_wall):
            tid = lanes.setdefault(sp.trace_id, len(lanes) + 1)
            ts_us = sp.t_wall * 1e6
            dur_us = (sp.duration or 0.0) * 1e6
            events.append({
                "name": sp.name, "ph": "X", "ts": round(ts_us, 1),
                "dur": round(max(dur_us, 1.0), 1), "pid": 1, "tid": tid,
                "args": {
                    **sp.attrs,
                    "trace_id": sp.trace_id,
                    "span_id": sp.span_id,
                    "status": sp.status,
                },
            })
            for ev in sp.events:
                events.append({
                    "name": f"{sp.name}.{ev['name']}", "ph": "i",
                    "ts": round(ts_us + ev["t"] * 1e6, 1), "pid": 1,
                    "tid": tid, "s": "t",
                    "args": {
                        k: v for k, v in ev.items() if k not in ("name", "t")
                    },
                })
        for trace_id_, tid in lanes.items():
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": f"trace {trace_id_[:8]}"},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def status(self) -> dict:
        with self._lock:
            return {
                "sample": self.sample,
                "finished_spans": len(self._spans),
                "capacity": self.capacity,
                "open_pod_traces": len(self._pod_roots),
                "dropped_spans": self.dropped,
                "pinned_traces": len(self._pinned),
                "pinned_spans": self._pin_count,
                "pinned_capacity": self.pinned_capacity,
                "dropped_pinned_spans": self.dropped_pinned,
            }


class ScheduleAudit:
    """Per-pod decision audit: every verb appends one record carrying the
    PER-NODE verdict (score, or rejection reason naming the failed
    constraint).  Bounded two ways: ``capacity`` pods FIFO, and
    ``max_records`` entries per pod (a crash-looping pod re-filtering
    forever must not grow one record list without limit)."""

    def __init__(self, capacity: int = 1024, max_records: int = 64,
                 enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("TPU_TRACE_AUDIT", "1") not in (
                "0", "false", "",
            )
        self.enabled = enabled
        self.capacity = capacity
        self.max_records = max_records
        self._pods: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()

    # per-record verdict payloads are truncated to this many nodes: a
    # 500-node cluster's filter verdict times 64 records times 1024 pods
    # would otherwise hold multi-GB of audit state in a long-lived
    # scheduler — the first N verdicts answer "why not here" for the
    # nodes that matter and a count records what was elided
    MAX_NODES_PER_RECORD = 64

    @classmethod
    def _clip(cls, v):
        cap = cls.MAX_NODES_PER_RECORD
        if isinstance(v, list) and len(v) > cap:
            return v[:cap] + [f"... +{len(v) - cap} more"]
        if isinstance(v, dict) and len(v) > cap:
            out = dict(list(v.items())[:cap])
            out["..."] = f"+{len(v) - cap} more"
            return out
        return v

    def record(self, pod_key: str, stage: str, trace_id: str = "",
               **fields) -> None:
        if not self.enabled:
            return
        rec = {
            "stage": stage,
            "t_unix": round(time.time(), 6),
            **{k: self._clip(v) for k, v in fields.items()},
        }
        with self._lock:
            entry = self._pods.get(pod_key)
            if entry is None:
                entry = {"pod": pod_key, "trace_id": trace_id, "records": []}
                self._pods[pod_key] = entry
                if len(self._pods) > self.capacity:
                    self._pods.popitem(last=False)
            else:
                self._pods.move_to_end(pod_key)
                if trace_id:
                    entry["trace_id"] = trace_id
            entry["records"].append(rec)
            if len(entry["records"]) > self.max_records:
                del entry["records"][: -self.max_records]

    def get(self, pod_key: str) -> Optional[dict]:
        with self._lock:
            entry = self._pods.get(pod_key)
            if entry is None:
                return None
            return {
                "pod": entry["pod"],
                "trace_id": entry["trace_id"],
                "records": [dict(r) for r in entry["records"]],
            }

    def pods(self) -> list:
        with self._lock:
            return list(self._pods)

    def reset(self) -> None:
        with self._lock:
            self._pods.clear()

    def explain(self, pod_key: str) -> str:
        """The human-readable "why this node" answer for
        ``/debug/schedule/<pod>``."""
        entry = self.get(pod_key)
        if entry is None:
            return (
                f"no scheduling audit for pod {pod_key!r} — it was never "
                "filtered by this scheduler (or its record aged out of the "
                f"{self.capacity}-pod audit window)\n"
            )
        lines = [f"scheduling audit for {pod_key}"]
        if entry["trace_id"]:
            lines.append(f"trace: {entry['trace_id']}  (see /traces)")
        for rec in entry["records"]:
            t = time.strftime(
                "%H:%M:%S", time.localtime(rec["t_unix"])
            ) + f".{int(rec['t_unix'] * 1000) % 1000:03d}"
            stage = rec["stage"]
            if stage == "filter":
                # verdict payloads may end in a _clip() elision marker
                # ("... +N more" list entry / "..." dict key) — render it
                # as an elision line, never as a fake node verdict
                ok = rec.get("ok", [])
                ok_marker = (
                    ok[-1]
                    if ok and str(ok[-1]).startswith("... +")
                    else None
                )
                if ok_marker is not None:
                    ok = ok[:-1]
                failed = dict(rec.get("failed", {}))
                failed_marker = failed.pop("...", None)
                lines.append(
                    f"{t}  filter: {len(ok)}/{len(ok) + len(failed)} "
                    "nodes feasible"
                    + (
                        " (verdict lists truncated)"
                        if ok_marker is not None or failed_marker
                        else ""
                    )
                )
                for n in ok:
                    lines.append(f"          {n}: ok")
                if ok_marker is not None:
                    lines.append(f"          {ok_marker} feasible")
                for n, why in sorted(failed.items()):
                    lines.append(f"          {n}: REJECTED — {why}")
                if failed_marker:
                    lines.append(
                        f"          ... {failed_marker} rejected"
                    )
            elif stage == "priorities":
                scores = dict(rec.get("scores", {}))
                elided = scores.pop("...", None)  # _clip() marker is a
                # string — it must not reach the numeric sort key
                ranked = sorted(scores.items(), key=lambda kv: -kv[1])
                lines.append(
                    f"{t}  priorities: "
                    + " ".join(f"{n}={s}" for n, s in ranked)
                    + (f" (... {elided})" if elided else "")
                )
            elif stage == "bind":
                node = rec.get("node", "?")
                err = rec.get("error", "")
                if err:
                    lines.append(f"{t}  bind → {node}: FAILED — {err}")
                else:
                    extra = ""
                    if rec.get("chips"):
                        extra = f"  chips={rec['chips']}"
                    if rec.get("duration_ms") is not None:
                        extra += f"  ({rec['duration_ms']}ms)"
                    lines.append(f"{t}  bind → {node}: ok{extra}")
            elif stage == "gang":
                lines.append(
                    f"{t}  gang {rec.get('gang', '?')}: "
                    f"{rec.get('event', '?')}"
                    + (
                        f" — {rec['detail']}" if rec.get("detail") else ""
                    )
                )
            elif stage == "preemption":
                lines.append(
                    f"{t}  preemption: candidate on "
                    f"{rec.get('nodes', 0)} node(s), "
                    f"victims {rec.get('victims', {})}"
                )
            else:
                rest = {
                    k: v
                    for k, v in rec.items()
                    if k not in ("stage", "t_unix")
                }
                lines.append(f"{t}  {stage}: {json.dumps(rest, default=str)}")
        return "\n".join(lines) + "\n"


# Process-global instances: instrumentation sites import these the same
# way they import the metric families (metrics/__init__.py REGISTRY).
TRACER = Tracer()
AUDIT = ScheduleAudit()


def traces_response(params: dict, tracer: Optional[Tracer] = None) -> dict:
    """The one ``GET /traces`` response shape, shared by the extender and
    inference servers (query params: ``trace`` for one trace's span tree,
    ``format=chrome`` for Perfetto export, ``limit`` for the summary
    list)."""
    tracer = tracer if tracer is not None else TRACER
    trace_id = params.get("trace", "")
    if params.get("format") == "chrome":
        return tracer.chrome_trace(trace_id or None)
    if trace_id:
        return {"trace_id": trace_id, "spans": tracer.trace(trace_id)}
    try:
        limit = int(params.get("limit", "50"))
    except (TypeError, ValueError):
        limit = 50
    return {"tracer": tracer.status(), "traces": tracer.traces(limit)}
