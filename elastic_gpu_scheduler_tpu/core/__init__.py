"""Core scheduling domain: topology, chips, allocation, raters, annotations."""

from .allocator import ChipSet, ContainerAlloc, Option, Rater
from .chip import CORE_PER_CHIP, Chip
from .node import NodeAllocator, chips_from_node
from .rater import RATERS, get_rater
from .request import TPURequest, TPUUnit, request_from_pod
from .topology import Coord, Topology

__all__ = [
    "ChipSet", "ContainerAlloc", "Option", "Rater", "CORE_PER_CHIP", "Chip",
    "NodeAllocator", "chips_from_node", "RATERS", "get_rater", "TPURequest",
    "TPUUnit", "request_from_pod", "Coord", "Topology",
]
