"""Incremental free-capacity index: cluster-scale placement state.

The extender's verbs re-derive per-node feasibility from scratch on every
filter/score/plan request — fine at the reference's scale, the structural
bottleneck at O(10k) nodes (ROADMAP item 1; Tesserae's observation in
PAPERS.md: placement search can be incremental over cluster state *deltas*
instead of re-derived per request).  ``CapacityIndex`` keeps one small
entry per node — (TPU generation, topology class, free core/HBM sums,
untouched-chip count, largest-free-box band) — maintained at the
allocator's mutation choke points and consulted by:

- ``TPUUnitScheduler.assume/score``: candidates failing the O(1)
  *necessary* capacity conditions are rejected without a node lock or a
  trade DFS, and (for translation-invariant raters) candidates in the same
  CONGRUENCE CLASS — equal ``ChipSet.plan_key()`` — share one fresh probe
  per class instead of a DFS per node (PR 2's gang memoization, generalized
  to the filter/score verbs);
- ``GangCoordinator._plan_inner``: the plan prefilter reads free-core from
  the index (one fold, zero per-node locks) and prunes nodes that cannot
  host even one member before any clone is taken;
- the fragmentation gauges / ``frag_snapshot``: only nodes dirtied since
  the last refresh are re-scanned (the index's second dirty set);
- ``status_summary`` / the batch admission sweep: per-bucket aggregates
  keyed (generation, topology class, largest-free-box band).

Exactness contract: every chip-state mutation flows through
``NodeAllocator.allocate/forget/add/refresh_from_node``, each of which
fires the allocator's ``on_change`` hook → ``mark_dirty`` (a GIL-atomic
dict write, no lock, safe under the node lock).  Readers call ``fold()``
first, which recomputes dirty entries under each node's own lock — so a
query observes exactly the committed state, and index-backed verdicts are
bit-identical to the full-rescan oracle (tests/test_cluster_index.py).
Each entry records the ``ChipSet.version`` mutation stamp it was derived
at; ``fold()`` skips nodes whose stamp hasn't moved, and ``verify()``
re-derives every entry regardless — the divergence audit the
check-cluster-scale gate hard-fails on.

Locking: ``mark_dirty`` and entry READS are lock-free (plain-dict GIL
atomicity); ``_lock`` guards only bucket maps and the probe memo, is never
held while taking a node lock, and node locks are never taken while
holding it — no rank interaction with the gang(10)/sched(20)/node(30)
hierarchy.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Iterable, Optional

from .request import TPURequest

# sentinel: memo entries may legitimately hold None-ish results
_MISS = object()


def band_of(chips: int) -> int:
    """Largest-free-box band: 0 for 0 chips, else floor(log2)+1 — so band
    b covers [2^(b-1), 2^b).  A query for a k-chip contiguous box scans
    buckets with band >= band_of(k) plus the boundary band exactly."""
    return chips.bit_length()


def request_demand(request: TPURequest) -> tuple[int, int, int]:
    """(core_units, hbm_gib, whole_chips) a request must find on one node —
    NECESSARY conditions only (pigeonhole sums; whole chips additionally
    need that many UNTOUCHED chips), so an index rejection is always a
    rejection the trade DFS would also reach: whole-chip containers fail
    when free chips run short (the non-contiguous fallback still needs
    ``count`` free chips), fractional containers fail when the core/HBM
    sums cannot cover the total.  Nodes PASSING these checks still run the
    full search — the index never admits, it only refuses."""
    from ..utils import consts

    core = hbm = whole = 0
    for u in request.units:
        if not u.needs_tpu:
            continue
        if u.wants_whole_chips:
            whole += u.chip_count
            core += u.chip_count * consts.CORE_PER_CHIP
        else:
            core += max(u.core, 0)
            hbm += u.hbm
    return core, hbm, whole


@dataclass
class IndexEntry:
    """One node's slot in the index.  ``plan_key`` is the congruence token
    (relative geometry + full chip state, ``ChipSet.plan_key()``): equal
    keys → a placement probed on one node is valid on the other."""

    __slots__ = (
        "name", "generation", "topo_key", "free_core", "free_hbm",
        "free_chips", "total_core", "total_hbm", "largest", "band",
        "frag", "plan_key", "version",
    )

    name: str
    generation: str
    topo_key: tuple
    free_core: int
    free_hbm: int
    free_chips: int
    total_core: int
    total_hbm: int
    largest: int
    band: int
    frag: float
    plan_key: tuple
    version: int

    def bucket(self) -> tuple:
        return (self.generation, self.topo_key, self.band)

    def snapshot(self) -> dict:
        """Comparable wire form (parity tests / journal-replay rebuild).
        ``version`` is process-local (excluded); ``plan_key`` is derived
        from the same state as the rest, so the scalar fields suffice."""
        return {
            "generation": self.generation,
            "topo": list(self.topo_key[0]),
            "free_core": self.free_core,
            "free_hbm": self.free_hbm,
            "free_chips": self.free_chips,
            "total_core": self.total_core,
            "total_hbm": self.total_hbm,
            "largest": self.largest,
            "band": self.band,
            "frag": self.frag,
        }


def topo_class(topo_key: tuple) -> str:
    """Flatten an entry's ``(dims, wrap)`` topology key to the routable
    class name the federation tier shards by (``4x4``, ``4x4x4``, a
    trailing ``t`` for torus wrap).  THE one spelling: the federation
    shard key is (region, generation, topo class) — the same triple
    ``IndexEntry.bucket()`` groups on, minus the volatile band — so a
    node's index bucket and its owning shard can never disagree."""
    dims, wrap = topo_key
    cls = "x".join(str(d) for d in dims)
    if any(wrap):
        cls += "t"
    return cls


def entry_from_chips(name: str, generation: str, cs) -> IndexEntry:
    """Derive a node's entry from its (locked) ChipSet — THE one
    derivation, shared by the live fold, ``verify()``, and the journal
    replay's offline rebuild so the three can never drift."""
    free_n = cs.free_count()
    largest = cs.largest_free_box() if free_n else 0
    frag = round(1.0 - largest / free_n, 4) if free_n else 0.0
    return IndexEntry(
        name=name,
        generation=generation,
        topo_key=(cs.topo.dims, cs.topo.wrap),
        free_core=cs.avail_core(),
        free_hbm=cs.avail_hbm(),
        free_chips=free_n,
        total_core=cs.total_core(),
        total_hbm=cs.total_hbm(),
        largest=largest,
        band=band_of(largest),
        frag=frag,
        plan_key=cs.plan_key(),
        version=getattr(cs, "version", 0),
    )


class CapacityIndex:
    """The cluster-wide incremental index (one per scheduler engine)."""

    MEMO_MAX = 8192  # probe-memo entries; state changes rotate keys out

    def __init__(self):
        # plain dicts: writes are GIL-atomic, mark_dirty takes NO lock
        # (it runs under node locks via the on_change hook)
        self.entries: dict[str, IndexEntry] = {}
        self._allocs: dict[str, object] = {}  # name → NodeAllocator
        self._dirty: dict[str, bool] = {}  # fold consumer
        self._frag_dirty: dict[str, bool] = {}  # gauge-refresh consumer
        self._lock = threading.Lock()  # buckets + memo only
        self._buckets: dict[tuple, set] = {}
        # (units, containers, plan_key) → (feasible, score) — one fresh
        # probe per congruence class per state, shared across candidates
        self._memo: dict[tuple, tuple] = {}
        # telemetry: candidate evaluations answered by the index (reject
        # or memo) vs sent to the full per-node search
        self.hits = 0
        self.misses = 0
        self.folds = 0

    # -- maintenance ---------------------------------------------------------

    def note_node(self, name: str, na) -> None:
        """Register (or re-register) a node; lock-free."""
        self._allocs[name] = na
        self.mark_dirty(name)

    def drop_node(self, name: str) -> None:
        self._allocs.pop(name, None)
        self.mark_dirty(name)

    def mark_dirty(self, name: str) -> None:
        """O(1), lock-free, safe under any lock — the allocator mutation
        hook.  Feeds BOTH consumers (fold + frag-gauge refresh)."""
        self._dirty[name] = True
        self._frag_dirty[name] = True

    def fold(self) -> None:
        """Recompute entries for every dirty node (reader-side; the
        mutation path pays one dict write).  Entry computation takes the
        node's own lock; bucket installation takes the index lock; the
        two are never held together."""
        if not self._dirty:
            return
        self.folds += 1
        for name in list(self._dirty.keys()):
            self._dirty.pop(name, None)
            na = self._allocs.get(name)
            if na is None:
                old = self.entries.pop(name, None)
                if old is not None:
                    with self._lock:
                        self._buckets.get(old.bucket(), set()).discard(name)
                continue
            old = self.entries.get(name)
            if old is not None and na.chips.version == old.version:
                # spuriously-marked node: the mutation stamp hasn't moved
                # (stamps are globally unique, so this also can't be a
                # swapped-out ChipSet) — skip the lock + box scan.  An
                # in-flight mutation stamped BEFORE mutating under the
                # node lock, so equality can never mask one.
                continue
            with na.lock:
                entry = entry_from_chips(name, na.generation, na.chips)
            old = self.entries.get(name)
            self.entries[name] = entry
            with self._lock:
                if old is not None and old.bucket() != entry.bucket():
                    self._buckets.get(old.bucket(), set()).discard(name)
                self._buckets.setdefault(entry.bucket(), set()).add(name)

    def take_frag_dirty(self) -> list:
        """Drain the fragmentation consumer's dirty set (gauge refresh /
        frag_snapshot): nodes whose mesh-health numbers may have moved
        since the last drain.  Callers fold() first so entries are
        fresh."""
        names = list(self._frag_dirty.keys())
        for n in names:
            self._frag_dirty.pop(n, None)
        return names

    # -- queries (callers fold() first) --------------------------------------

    def entry(self, name: str) -> Optional[IndexEntry]:
        return self.entries.get(name)

    def can_fit(self, e: IndexEntry, demand: tuple[int, int, int]) -> bool:
        core, hbm, whole = demand
        return (
            e.free_core >= core
            and e.free_hbm >= hbm
            and e.free_chips >= whole
        )

    def free_core_map(self, names: Iterable[str]) -> dict:
        """name → free core units, exact as of the last committed
        mutation (the gang-plan prefilter's input; replaces one lock
        acquisition + sum read per node)."""
        entries = self.entries
        out = {}
        for n in names:
            e = entries.get(n)
            if e is not None:
                out[n] = e.free_core
        return out

    def memo_get(self, key: tuple):
        with self._lock:
            return self._memo.get(key, _MISS)

    def memo_put(self, key: tuple, value: tuple) -> None:
        with self._lock:
            if len(self._memo) >= self.MEMO_MAX:
                # state churn rotated the live keys out from under the
                # old ones; dropping the oldest half keeps this O(1)/put
                for k in list(self._memo.keys())[: self.MEMO_MAX // 2]:
                    self._memo.pop(k, None)
            self._memo[key] = value

    def top_fragmented(self, k: int = 10) -> list[dict]:
        """The k most fragmented nodes that still hold free chips —
        the status summary's 'where is defrag owed' view."""
        ranked = sorted(
            (e for e in self.entries.values() if e.free_chips),
            key=lambda e: (-e.frag, -e.free_chips, e.name),
        )[:k]
        return [
            {
                "node": e.name,
                "fragmentation_index": e.frag,
                "largest_free_submesh_chips": e.largest,
                "free_chips": e.free_chips,
            }
            for e in ranked
        ]

    def bucket_stats(self) -> list[dict]:
        """Aggregate view per (generation, topology class, largest-free-
        box band) bucket — O(buckets), the /scheduler/status?summary=1
        capacity panorama."""
        with self._lock:
            buckets = {k: set(v) for k, v in self._buckets.items() if v}
        out = []
        for (gen, topo_key, band), names in sorted(
            buckets.items(), key=lambda kv: (kv[0][0], str(kv[0][1]), kv[0][2])
        ):
            free_core = sum(
                self.entries[n].free_core for n in names if n in self.entries
            )
            out.append(
                {
                    "generation": gen,
                    "topology": "x".join(map(str, topo_key[0])),
                    "largest_free_band": band,
                    "nodes": len(names),
                    "free_core": free_core,
                }
            )
        return out

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "nodes": len(self.entries),
            "buckets": len(self._buckets),
            "dirty": len(self._dirty),
            "folds": self.folds,
            "hits": self.hits,
            "misses": self.misses,
            "hit_pct": round(100.0 * self.hits / total, 2) if total else 0.0,
        }

    def snapshot(self) -> dict[str, dict]:
        """Full comparable dump (parity suite / replay rebuild diff)."""
        self.fold()
        return {n: e.snapshot() for n, e in sorted(self.entries.items())}

    def verify(self) -> list[str]:
        """Recompute every entry from live chip state and diff against
        the maintained one — the index/oracle divergence audit the
        check-cluster-scale gate hard-fails on.  Empty list = clean."""
        self.fold()
        problems: list[str] = []
        for name, na in list(self._allocs.items()):
            with na.lock:
                fresh = entry_from_chips(name, na.generation, na.chips)
            cur = self.entries.get(name)
            if cur is None:
                problems.append(f"{name}: no index entry for live node")
                continue
            if cur.snapshot() != fresh.snapshot():
                problems.append(
                    f"{name}: index entry diverged: "
                    f"indexed={cur.snapshot()} live={fresh.snapshot()}"
                )
        for name in self.entries:
            if name not in self._allocs:
                problems.append(f"{name}: index entry for unknown node")
        return problems
