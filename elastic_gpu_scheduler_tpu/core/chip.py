"""Per-chip capacity state.

TPU analogue of the reference's GPU device model (reference:
pkg/scheduler/gpu.go:9-56): a chip exposes 100 core units (fractional
TensorCore duty share — the ``elasticgpu.io/tpu-chip`` resource) and an HBM
budget in GiB (``elasticgpu.io/tpu-hbm``).  Whole-chip allocation zeroes both
availabilities; fractional allocation subtracts.  Unlike the reference, every
chip carries its ICI mesh coordinate so placements are topology-addressable.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .topology import Coord

CORE_PER_CHIP = 100  # 100 units = one whole chip (reference: pkg/utils/types.go:6)


@dataclass
class Chip:
    coord: Coord
    core_total: int = CORE_PER_CHIP
    hbm_total: int = 0  # GiB
    core_avail: int = field(default=-1)
    hbm_avail: int = field(default=-1)

    def __post_init__(self):
        if self.core_avail < 0:
            self.core_avail = self.core_total
        if self.hbm_avail < 0:
            self.hbm_avail = self.hbm_total

    @property
    def is_free(self) -> bool:
        return self.core_avail == self.core_total and self.hbm_avail == self.hbm_total

    @property
    def is_untouched(self) -> bool:
        """No fractional tenant — whole-chip allocation requires this."""
        return self.is_free

    def can_fit(self, core: int, hbm: int) -> bool:
        return self.core_avail >= core and self.hbm_avail >= hbm

    def take(self, core: int, hbm: int) -> None:
        if not self.can_fit(core, hbm):
            raise ValueError(
                f"chip {self.coord}: cannot take core={core} hbm={hbm} "
                f"(avail core={self.core_avail} hbm={self.hbm_avail})"
            )
        self.core_avail -= core
        self.hbm_avail -= hbm

    def give(self, core: int, hbm: int) -> None:
        self.core_avail = min(self.core_total, self.core_avail + core)
        self.hbm_avail = min(self.hbm_total, self.hbm_avail + hbm)

    def take_whole(self) -> None:
        if not self.is_free:
            raise ValueError(f"chip {self.coord}: not free for whole-chip take")
        self.core_avail = 0
        self.hbm_avail = 0

    def give_whole(self) -> None:
        self.core_avail = self.core_total
        self.hbm_avail = self.hbm_total

    def clone(self) -> "Chip":
        return Chip(
            self.coord, self.core_total, self.hbm_total, self.core_avail, self.hbm_avail
        )

    def record(self) -> list:
        """Journal wire form of the chip's CAPACITY (totals only —
        availability is derived by replaying the mutation stream)."""
        return [list(self.coord), self.core_total, self.hbm_total]

    @classmethod
    def from_record(cls, rec) -> "Chip":
        coord, core_total, hbm_total = rec
        return cls(
            coord=tuple(coord), core_total=int(core_total),
            hbm_total=int(hbm_total),
        )


class ChipRef:
    """Live view of one chip inside a ``ChipSet``'s packed arrays.

    The ChipSet keeps chip state in parallel arrays plus free/partial
    bitsets (so ``clone()`` is O(words), not O(chips) Python objects);
    this ref exposes the classic per-chip surface — ``core_avail``,
    ``take()``, ``take_whole()`` and friends — reading and writing
    through to the owning set so external mutation (tests, capacity
    refresh) keeps the bitsets coherent.  API-compatible with ``Chip``.
    """

    __slots__ = ("_cs", "_i")

    def __init__(self, cs, i: int):
        self._cs = cs
        self._i = i

    @property
    def coord(self) -> Coord:
        return self._cs._coords[self._i]

    @property
    def core_total(self) -> int:
        return self._cs._core_total[self._i]

    @core_total.setter
    def core_total(self, v: int) -> None:
        self._cs._set_total(self._i, core_total=v)

    @property
    def hbm_total(self) -> int:
        return self._cs._hbm_total[self._i]

    @hbm_total.setter
    def hbm_total(self, v: int) -> None:
        self._cs._set_total(self._i, hbm_total=v)

    @property
    def core_avail(self) -> int:
        return self._cs._core_avail[self._i]

    @core_avail.setter
    def core_avail(self, v: int) -> None:
        cs = self._cs
        cs._set_slot(self._i, v, cs._hbm_avail[self._i])

    @property
    def hbm_avail(self) -> int:
        return self._cs._hbm_avail[self._i]

    @hbm_avail.setter
    def hbm_avail(self, v: int) -> None:
        cs = self._cs
        cs._set_slot(self._i, cs._core_avail[self._i], v)

    @property
    def is_free(self) -> bool:
        return bool(self._cs._free_bits >> self._i & 1)

    @property
    def is_untouched(self) -> bool:
        return self.is_free

    def can_fit(self, core: int, hbm: int) -> bool:
        cs = self._cs
        return cs._core_avail[self._i] >= core and cs._hbm_avail[self._i] >= hbm

    def take(self, core: int, hbm: int) -> None:
        cs = self._cs
        if not self.can_fit(core, hbm):
            raise ValueError(
                f"chip {self.coord}: cannot take core={core} hbm={hbm} "
                f"(avail core={self.core_avail} hbm={self.hbm_avail})"
            )
        cs._set_slot(
            self._i, cs._core_avail[self._i] - core, cs._hbm_avail[self._i] - hbm
        )

    def give(self, core: int, hbm: int) -> None:
        cs = self._cs
        cs._set_slot(
            self._i,
            min(cs._core_total[self._i], cs._core_avail[self._i] + core),
            min(cs._hbm_total[self._i], cs._hbm_avail[self._i] + hbm),
        )

    def take_whole(self) -> None:
        if not self.is_free:
            raise ValueError(f"chip {self.coord}: not free for whole-chip take")
        self._cs._set_slot(self._i, 0, 0)

    def give_whole(self) -> None:
        cs = self._cs
        cs._set_slot(self._i, cs._core_total[self._i], cs._hbm_total[self._i])

    def clone(self) -> Chip:
        """Detached value copy (a plain ``Chip``)."""
        return Chip(
            self.coord, self.core_total, self.hbm_total,
            self.core_avail, self.hbm_avail,
        )

    def __repr__(self) -> str:  # mirrors the Chip dataclass repr fields
        return (
            f"ChipRef(coord={self.coord}, core_total={self.core_total}, "
            f"hbm_total={self.hbm_total}, core_avail={self.core_avail}, "
            f"hbm_avail={self.hbm_avail})"
        )
