"""Per-chip capacity state.

TPU analogue of the reference's GPU device model (reference:
pkg/scheduler/gpu.go:9-56): a chip exposes 100 core units (fractional
TensorCore duty share — the ``elasticgpu.io/tpu-chip`` resource) and an HBM
budget in GiB (``elasticgpu.io/tpu-hbm``).  Whole-chip allocation zeroes both
availabilities; fractional allocation subtracts.  Unlike the reference, every
chip carries its ICI mesh coordinate so placements are topology-addressable.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .topology import Coord

CORE_PER_CHIP = 100  # 100 units = one whole chip (reference: pkg/utils/types.go:6)


@dataclass
class Chip:
    coord: Coord
    core_total: int = CORE_PER_CHIP
    hbm_total: int = 0  # GiB
    core_avail: int = field(default=-1)
    hbm_avail: int = field(default=-1)

    def __post_init__(self):
        if self.core_avail < 0:
            self.core_avail = self.core_total
        if self.hbm_avail < 0:
            self.hbm_avail = self.hbm_total

    @property
    def is_free(self) -> bool:
        return self.core_avail == self.core_total and self.hbm_avail == self.hbm_total

    @property
    def is_untouched(self) -> bool:
        """No fractional tenant — whole-chip allocation requires this."""
        return self.is_free

    def can_fit(self, core: int, hbm: int) -> bool:
        return self.core_avail >= core and self.hbm_avail >= hbm

    def take(self, core: int, hbm: int) -> None:
        if not self.can_fit(core, hbm):
            raise ValueError(
                f"chip {self.coord}: cannot take core={core} hbm={hbm} "
                f"(avail core={self.core_avail} hbm={self.hbm_avail})"
            )
        self.core_avail -= core
        self.hbm_avail -= hbm

    def give(self, core: int, hbm: int) -> None:
        self.core_avail = min(self.core_total, self.core_avail + core)
        self.hbm_avail = min(self.hbm_total, self.hbm_avail + hbm)

    def take_whole(self) -> None:
        if not self.is_free:
            raise ValueError(f"chip {self.coord}: not free for whole-chip take")
        self.core_avail = 0
        self.hbm_avail = 0

    def give_whole(self) -> None:
        self.core_avail = self.core_total
        self.hbm_avail = self.hbm_total

    def clone(self) -> "Chip":
        return Chip(
            self.coord, self.core_total, self.hbm_total, self.core_avail, self.hbm_avail
        )
