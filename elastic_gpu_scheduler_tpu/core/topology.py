"""ICI mesh topology model.

The reference models a node's GPUs as a flat list with anonymous integer indices
(reference: pkg/scheduler/node.go:32-40, pkg/scheduler/gpu.go:193-202) and is
therefore blind to interconnect locality. On TPU, chips in a slice form an ICI
mesh/torus (2D for v5e, 3D for v4/v5p) and collective performance depends on
allocations being *contiguous sub-slices* of that mesh. This module is the
coordinate space everything else speaks:

- ``Topology``: an N-D mesh with per-axis wraparound (torus) flags.
- ``Coord``: a chip's position, serialized as "x.y.z" in pod annotations.
- sub-box enumeration: all axis-aligned placements of a requested shape,
  including torus wraparound — the candidate set for contiguous placement.
- shape factorization: ways to realize "N chips" as a box inside the mesh.

GKE exposes slice topology via node labels (``cloud.google.com/gke-tpu-topology``
style, e.g. "4x4x8"); we mirror that with ``elasticgpu.io/tpu-topology`` plus a
per-host offset label so each Kubernetes node (one TPU host) knows which
coordinates of the slice it owns. See k8s/objects.py for the label names.
"""

from __future__ import annotations

import functools
import itertools
from dataclasses import dataclass
from typing import Iterator, Sequence

import numpy as np

Coord = tuple[int, ...]


def parse_topology(spec: str) -> tuple[int, ...]:
    """Parse "4x4x8" → (4, 4, 8). Accepts 1-4 axes."""
    try:
        dims = tuple(int(p) for p in spec.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"bad topology spec {spec!r}") from e
    if not (1 <= len(dims) <= 4) or any(d <= 0 for d in dims):
        raise ValueError(f"bad topology spec {spec!r}")
    return dims


def format_topology(dims: Sequence[int]) -> str:
    return "x".join(str(d) for d in dims)


def format_coord(c: Coord) -> str:
    """Wire format for one chip coordinate: "x.y.z"."""
    return ".".join(str(v) for v in c)


def parse_coord(s: str) -> Coord:
    return tuple(int(p) for p in s.split("."))


# Accelerator families.  cores_per_chip is informational (v5p/v4 chips have two
# TensorCores fused as one "megacore" device under XLA; v5e has one).  A torus
# axis on v4/v5p exists when the full-slice axis length is a multiple of 4
# (wrap-around ICI links); v5e slices are plain 2D meshes.
ACCELERATOR_FAMILIES = {
    "v4": {"ndim": 3, "cores_per_chip": 2, "chips_per_host": 4, "torus_multiple": 4},
    "v5e": {"ndim": 2, "cores_per_chip": 1, "chips_per_host": 4, "torus_multiple": 0},
    "v5p": {"ndim": 3, "cores_per_chip": 2, "chips_per_host": 4, "torus_multiple": 4},
    "v6e": {"ndim": 2, "cores_per_chip": 1, "chips_per_host": 4, "torus_multiple": 0},
}


def default_wrap(family: str, dims: Sequence[int]) -> tuple[bool, ...]:
    info = ACCELERATOR_FAMILIES.get(family, {"torus_multiple": 0})
    m = info.get("torus_multiple", 0)
    return tuple(bool(m) and d % m == 0 and d >= m for d in dims)


@dataclass(frozen=True)
class Topology:
    """An N-D ICI mesh with optional per-axis wraparound."""

    dims: tuple[int, ...]
    wrap: tuple[bool, ...] = ()

    def __post_init__(self):
        if not self.wrap:
            object.__setattr__(self, "wrap", (False,) * len(self.dims))
        if len(self.wrap) != len(self.dims):
            raise ValueError("wrap length must match dims")
        n = 1
        for d in self.dims:
            n *= d
        # cached: num_chips sits on the allocator's DFS hot path (range
        # checks in coord_of), where a per-call np.prod dominated profiles
        object.__setattr__(self, "_num_chips", n)

    @classmethod
    def from_spec(cls, spec: str, family: str = "v5e") -> "Topology":
        dims = parse_topology(spec)
        return cls(dims, default_wrap(family, dims))

    @property
    def ndim(self) -> int:
        return len(self.dims)

    @property
    def num_chips(self) -> int:
        return self._num_chips

    def spec(self) -> str:
        return format_topology(self.dims)

    def coords(self) -> Iterator[Coord]:
        """All coordinates in row-major order (the canonical chip order)."""
        return itertools.product(*(range(d) for d in self.dims))

    def index(self, c: Coord) -> int:
        """Row-major linear index of a coordinate."""
        idx = 0
        for v, d in zip(c, self.dims):
            idx = idx * d + v
        return idx

    def coord_of(self, idx: int) -> Coord:
        if not (0 <= idx < self.num_chips):
            raise ValueError(f"index {idx} out of range for topology {self.dims}")
        c = []
        for d in reversed(self.dims):
            c.append(idx % d)
            idx //= d
        return tuple(reversed(c))

    def contains(self, c: Coord) -> bool:
        return len(c) == self.ndim and all(0 <= v < d for v, d in zip(c, self.dims))

    def neighbors(self, c: Coord) -> Iterator[Coord]:
        """ICI neighbors (mesh edges, plus torus edges on wrapped axes)."""
        for ax in range(self.ndim):
            for step in (-1, 1):
                v = c[ax] + step
                if self.wrap[ax]:
                    v %= self.dims[ax]
                elif not (0 <= v < self.dims[ax]):
                    continue
                n = c[:ax] + (v,) + c[ax + 1 :]
                if n != c:
                    yield n

    # -- sub-box placement ---------------------------------------------------

    def placements(self, shape: Sequence[int]) -> Iterator[tuple[Coord, ...]]:
        """All placements of an axis-aligned `shape` box: yields coord tuples.

        On wrapped (torus) axes the box may wrap around; on mesh axes it must
        fit inside.  `shape` must have self.ndim axes.
        """
        origin_ranges = [
            range(d) if (w and s < d) else range(d - s + 1)
            for s, d, w in zip(shape, self.dims, self.wrap)
        ]
        yield from self.placements_at(
            shape, itertools.product(*origin_ranges)
        )

    def placements_at(
        self, shape: Sequence[int], origins: Sequence[Coord]
    ) -> Iterator[tuple[Coord, ...]]:
        """``placements(shape)`` restricted to the given candidate origins.

        Because a box always contains its own origin cell (offset 0), every
        all-free box's origin is a free cell — so enumerating origins from
        the free set alone yields the SAME valid boxes as a full-mesh scan,
        in the same canonical order when ``origins`` is sorted by row-major
        index, at O(|free|·|shape|) instead of O(|mesh|·|shape|).  Origins
        outside ``placements``'s origin ranges are skipped identically.
        ``placements`` itself delegates here (one copy of the wrap/offset
        geometry).
        """
        if len(shape) != self.ndim:
            raise ValueError(f"shape {shape} has wrong rank for {self.dims}")
        if any(s > d for s, d in zip(shape, self.dims)):
            return
        lims = tuple(
            d if (w and s < d) else d - s + 1
            for s, d, w in zip(shape, self.dims, self.wrap)
        )
        offs_all = list(itertools.product(*(range(s) for s in shape)))
        for origin in origins:
            if any(o >= lim for o, lim in zip(origin, lims)):
                continue
            yield tuple(
                tuple(
                    (o + f) % d if w else o + f
                    for o, f, d, w in zip(origin, offs, self.dims, self.wrap)
                )
                for offs in offs_all
            )

    def box_shapes(self, count: int, max_shapes: int = 64) -> list[tuple[int, ...]]:
        """Axis-aligned box shapes with `count` chips that fit in this mesh.

        Sorted most-compact-first (minimal surface area → minimal ICI hop
        diameter).  This is the canonical sub-slice enumeration replacing the
        reference's "take the first N free cards" (pkg/scheduler/gpu.go:95-108).
        """
        return _box_shapes_cached(self.dims, count, max_shapes)


@functools.lru_cache(maxsize=4096)
def _box_shapes_cached(
    dims: tuple[int, ...], count: int, max_shapes: int
) -> list[tuple[int, ...]]:
    ndim = len(dims)
    shapes: set[tuple[int, ...]] = set()

    def rec(prefix: tuple[int, ...], remaining: int, ax: int):
        if ax == ndim - 1:
            if remaining <= dims[ax]:
                shapes.add(prefix + (remaining,))
            return
        for f in _divisors(remaining):
            if f <= dims[ax]:
                rec(prefix + (f,), remaining // f, ax + 1)

    rec((), count, 0)

    def compactness(shape: tuple[int, ...]) -> tuple:
        # surface area of the box (lower = more compact), then max dim, then
        # the dims themselves — the FULL key, so equal-compactness ties are
        # deterministic and identical to the native enumerator's ordering
        vol = int(np.prod(shape))
        surf = sum(
            2 * vol // s for s in shape
        )  # proportional surface; exact enough for ordering
        return (surf, max(shape), shape)

    out = sorted(shapes, key=compactness)
    return out[:max_shapes]


def _divisors(n: int) -> list[int]:
    out = []
    i = 1
    while i * i <= n:
        if n % i == 0:
            out.append(i)
            if i != n // i:
                out.append(n // i)
        i += 1
    return sorted(out)


def bounding_box(coords: Sequence[Coord]) -> tuple[int, ...]:
    """Bounding-box shape of a coordinate set (ignoring wraparound)."""
    if not coords:
        return ()
    lo = [min(c[i] for c in coords) for i in range(len(coords[0]))]
    hi = [max(c[i] for c in coords) for i in range(len(coords[0]))]
    return tuple(h - l + 1 for l, h in zip(lo, hi))


def is_contiguous(coords: Sequence[Coord], topo: Topology) -> bool:
    """True if the coordinate set is connected in the ICI graph (BFS)."""
    if not coords:
        return True
    cs = set(coords)
    seen = {next(iter(cs))}
    frontier = [next(iter(cs))]
    while frontier:
        cur = frontier.pop()
        for n in topo.neighbors(cur):
            if n in cs and n not in seen:
                seen.add(n)
                frontier.append(n)
    return len(seen) == len(cs)


def reference_free_boxes(topo: Topology, free_set, count: int, max_out: int):
    """Deliberately-NAIVE reference enumeration of fully-free contiguous
    boxes: the canonical compact-first candidate stream
    (``box_shapes`` × ``placements``) filtered by the free mask, deduped,
    truncated at ``max_out`` — each result a frozenset of coords.

    This is the parity ORACLE for the native kernel and its Python
    fallback.  tests/test_native.py and tools/check_native_san.py both
    assert bit-identical results against this ONE definition, so a
    change to the enumeration contract reaches the curated tests and
    the sanitizer fuzz gate together, never one of them."""
    out: list = []
    seen: set = set()
    for shape in topo.box_shapes(count):
        for box in topo.placements(shape):
            if len(out) >= max_out:
                return out
            if all(c in free_set for c in box):
                key = frozenset(box)
                if key in seen:
                    continue
                seen.add(key)
                out.append(key)
    return out
