"""Pod → TPU resource request model.

TPU analogue of the reference's request parsing (reference:
pkg/scheduler/allocate.go:15-58):

- per container: ``TPUUnit(core, hbm, chip_count)``
- ``core == 0 and hbm == 0``  → NOT_NEEDED (container takes no TPU)
- ``core >= 100``             → whole chips, ``chip_count = core // 100``
                                 (must be an exact multiple; the reference
                                 silently floors, allocate.go:46-49 — we reject)
- ``0 < core < 100``          → fractional share of one chip (+ hbm)
- ``core == 0 and hbm > 0``   → hbm-only fractional share (gpushare-by-memory)

The request hash keys the assume→score→bind memoization cache.  Unlike the
reference — whose hash is shape-only and collides across identically-shaped
pending pods (allocate.go:30-33; quirk documented in SURVEY §5) — ours mixes in
the pod UID so each pending pod gets its own cached placement.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Mapping, Sequence

from ..utils import consts

NOT_NEEDED = -1  # container requests no TPU (reference: allocate.go:15-18)


@dataclass(frozen=True)
class TPUUnit:
    """One container's demand."""

    core: int = NOT_NEEDED  # core units on ONE chip, or NOT_NEEDED
    hbm: int = 0  # GiB on that chip
    chip_count: int = 0  # >0 → that many WHOLE chips (core/hbm then unused)

    @property
    def needs_tpu(self) -> bool:
        return self.chip_count > 0 or self.core > 0 or self.hbm > 0

    @property
    def wants_whole_chips(self) -> bool:
        return self.chip_count > 0


@dataclass(frozen=True)
class TPURequest:
    """Parsed per-pod request: one TPUUnit per container, in spec order."""

    pod_uid: str
    pod_key: str  # namespace/name
    units: tuple[TPUUnit, ...]
    container_names: tuple[str, ...]
    gang_name: str = ""
    gang_size: int = 0

    @property
    def needs_tpu(self) -> bool:
        return any(u.needs_tpu for u in self.units)

    @property
    def total_chips_equiv(self) -> float:
        """Demand in whole-chip equivalents (for packing-efficiency math)."""
        t = 0.0
        for u in self.units:
            if u.wants_whole_chips:
                t += u.chip_count
            elif u.needs_tpu:
                t += max(u.core, 0) / consts.CORE_PER_CHIP
        return t

    def hash(self) -> str:
        h = hashlib.sha256()
        h.update(self.pod_uid.encode())
        for name, u in zip(self.container_names, self.units):
            h.update(f"|{name}:{u.core}:{u.hbm}:{u.chip_count}".encode())
        return h.hexdigest()[:16]


_QUANTITY_RE = None  # compiled lazily (module import stays cheap)
_QUANTITY_SUFFIX = {
    "": 1, "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15,
    "E": 10**18, "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
    "Pi": 2**50, "Ei": 2**60,
}


def parse_quantity(v: object) -> int:
    """A Kubernetes ``resource.Quantity`` to its integer value, rounding UP
    — the semantics of Go's ``Quantity.Value()``, which is what the
    reference reads resources through (pod.go:140-149 ``.Value()``).

    The apiserver marshals every quantity as a STRING ("2", "200m", "1Gi",
    "2e3"); builder-authored fixtures and tests often use plain ints.  Both
    must parse identically or the first real kube-scheduler request with a
    canonical quantity crashes the verb (VERDICT r2 #6 wire fidelity)."""
    import math

    if isinstance(v, bool):
        raise ValueError(f"boolean is not a quantity: {v!r}")
    if isinstance(v, int):
        return v
    if isinstance(v, float):
        return math.ceil(v)
    global _QUANTITY_RE
    if _QUANTITY_RE is None:
        import re

        # suffixes: milli "m"; decimal k M G T P E (lowercase k only);
        # binary Ki Mi Gi Ti Pi Ei (uppercase + i) — the exact
        # resource.Quantity grammar, nothing looser: an exponent and a
        # suffix are mutually exclusive ("2e3Ki" is malformed in Go's
        # parser and must 400, not parse)
        _QUANTITY_RE = re.compile(
            r"^([+-]?[0-9]+(?:\.[0-9]*)?|[+-]?\.[0-9]+)"
            r"(?:[eE]([+-]?[0-9]+)|(m|[KMGTPE]i|[kMGTPE]))?$"
        )
    s = str(v).strip()
    mt = _QUANTITY_RE.match(s)
    if mt is None:
        raise ValueError(f"malformed resource quantity {s!r}")
    from decimal import Decimal

    num = Decimal(mt.group(1)) * (Decimal(10) ** int(mt.group(2) or 0))
    suffix = mt.group(3) or ""
    if suffix == "m":
        num /= 1000
    else:
        num *= _QUANTITY_SUFFIX[suffix]
    return math.ceil(num)


def _get_quantity(resources: Mapping[str, object], names: Sequence[str]) -> int:
    total = 0
    for n in names:
        v = resources.get(n)
        if v is None:
            continue
        total += parse_quantity(v)
    return total


def unit_from_resources(resources: Mapping[str, object]) -> TPUUnit:
    """Parse one container's resource map (limits merged over requests)."""
    core = _get_quantity(resources, consts.RESOURCE_TPU_CORE_ALIASES)
    hbm = _get_quantity(resources, consts.RESOURCE_TPU_HBM_ALIASES)
    if core == 0 and hbm == 0:
        return TPUUnit(core=NOT_NEEDED, hbm=0, chip_count=0)
    if core >= consts.CORE_PER_CHIP:
        if core % consts.CORE_PER_CHIP != 0:
            raise ValueError(
                f"{consts.RESOURCE_TPU_CORE}={core}: multi-chip requests must be "
                f"an exact multiple of {consts.CORE_PER_CHIP}"
            )
        return TPUUnit(core=0, hbm=hbm, chip_count=core // consts.CORE_PER_CHIP)
    return TPUUnit(core=core, hbm=hbm, chip_count=0)


def pod_gang_key(pod) -> "str | None":
    """``namespace/gang-name`` for a gang-annotated pod, else None — THE
    gang identity every consumer (planning, preemption accounting, victim
    expansion) must agree on."""
    name = (pod.metadata.annotations or {}).get(consts.ANNOTATION_GANG_NAME)
    return f"{pod.metadata.namespace}/{name}" if name else None


def request_from_pod(pod) -> TPURequest:
    """Build a TPURequest from a k8s Pod object (see k8s/objects.py)."""
    units = []
    names = []
    for c in pod.spec.containers:
        res = dict(c.resources.requests or {})
        res.update(c.resources.limits or {})
        units.append(unit_from_resources(res))
        names.append(c.name)
    ann = pod.metadata.annotations or {}
    gang = ann.get(consts.ANNOTATION_GANG_NAME, "")
    try:
        gang_size = int(ann.get(consts.ANNOTATION_GANG_SIZE, "0"))
    except ValueError:
        gang_size = 0
    return TPURequest(
        pod_uid=pod.metadata.uid,
        pod_key=f"{pod.metadata.namespace}/{pod.metadata.name}",
        units=tuple(units),
        container_names=tuple(names),
        gang_name=gang,
        gang_size=gang_size,
    )
