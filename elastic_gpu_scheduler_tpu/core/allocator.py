"""Placement search and allocation state.

TPU-native rebuild of the reference's allocation engine:

- ``GPUs.Trade`` exhaustive DFS over anonymous card indices
  (reference: pkg/scheduler/gpu.go:65-129)  →  ``ChipSet.trade``: a DFS over
  containers whose whole-chip candidates are *contiguous ICI sub-boxes*
  (compact-first canonical enumeration, topology.box_shapes/placements) with a
  non-contiguous fallback, and whose complete assignments are scored by a
  pluggable ``Rater``.
- ``GPUs.Transact/Cancel`` (gpu.go:153-191)  →  ``ChipSet.transact/cancel``.
- ``NodeAllocator`` (pkg/scheduler/node.go)  →  same name; caches the assume
  result per request hash for reuse by score/bind, with two reference bugs
  fixed: the hash is pod-unique (node.go:63-64 collides across same-shaped
  pods) and ``score`` never dereferences a missing option (node.go:78-84
  nil-deref).

``ChipSet`` is deliberately node-agnostic: a host view (4-8 chips of a slice)
and a slice view (all chips, for gang placement) are the same type, so the
gang scheduler reuses this search unchanged at slice scale.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator, Mapping, Optional

from .chip import Chip, ChipRef
from .request import TPURequest
from .topology import Coord, Topology, bounding_box

# Search budget: max complete assignments rated per trade() call.  The
# reference's DFS is unbounded (gpu.go:65-129) and explodes combinatorially;
# we keep best-so-far semantics under a budget so worst-case latency is capped.
DEFAULT_SEARCH_BUDGET = 4096

# Globally-unique, monotone mutation stamps for ChipSet.version: every
# committed mutation (and every fresh ChipSet) draws a new value, so equal
# versions mean "the very same object, untouched since" — even across a
# refresh_from_node that swapped the ChipSet out wholesale.  next() on an
# itertools.count is a single GIL-atomic C call.
import itertools as _itertools

_VERSIONS = _itertools.count(1)


@dataclass(frozen=True)
class ContainerAlloc:
    """One container's placement: which chips, and how much of each."""

    container: str
    coords: tuple[Coord, ...]
    whole: bool  # True → whole chips (all core+hbm of each coord)
    core: int = 0  # per-chip core units when fractional
    hbm: int = 0  # per-chip HBM GiB when fractional
    contiguous: bool = True  # whole-chip: did we get an ICI-contiguous box?

    @property
    def needs_tpu(self) -> bool:
        return bool(self.coords)


@dataclass
class Option:
    """A complete placement decision for one pod on one node/slice.

    Mirrors the reference's GPUOption (pkg/scheduler/allocate.go:60-93) with
    coordinates instead of flat indices.
    """

    request_hash: str
    allocs: tuple[ContainerAlloc, ...]
    score: float = 0.0

    def coords_by_container(self) -> dict[str, tuple[Coord, ...]]:
        return {a.container: a.coords for a in self.allocs}


def option_demand(option: Option) -> tuple:
    """Per-container demand signature — what a placement CONSUMES,
    independent of WHERE it lands: (container, chip count, whole, core,
    hbm) per alloc.  A live migration must preserve this exactly; the
    journal replay's chip-conservation invariant and the scheduler's
    ``migrate_pod`` guard both compare through this one function so the
    accounting can never diverge."""
    return tuple(
        (a.container, len(a.coords), bool(a.whole), a.core, a.hbm)
        for a in option.allocs
    )


class Rater:
    """Placement policy: rate a complete assignment (reference: rater.go:8-10).

    ``rate`` is called with the ChipSet *after* the option has been applied,
    matching the reference's rate-post-assignment convention (rater.go:30-50).
    Scores are floats in [0, 100]; the extender layer normalizes to 0-10.
    """

    name = "rater"

    # True → the score depends only on the RELATIVE geometry of the chips
    # touched plus candidate-invariant aggregates, never on absolute mesh
    # coordinates — the gang planner may then replay a memoized placement
    # found on one node onto a congruent node (option_from_template) without
    # re-rating.  Default False: an unknown custom rater silently losing its
    # absolute-position signal would be a correctness bug, so subclasses
    # must opt in (rater.py sets it on the stock policies).
    translation_invariant = False
    # True → for a single whole-chip container every non-locality score term
    # is identical across candidate boxes (the box consumes the same totals
    # whichever free chips it lands on), so argmax(rate) == argmax(locality
    # bonus) with first-wins ties.  Lets the gang planner use the native
    # plan_gang kernel instead of the per-member trade DFS.  Same opt-in
    # stance as translation_invariant.
    whole_chip_compact_first = False

    def rate(self, chips: "ChipSet", option: Option) -> float:
        raise NotImplementedError


def iter_bits(bits: int) -> Iterator[int]:
    """Indices of set bits, ascending."""
    while bits:
        low = bits & -bits
        yield low.bit_length() - 1
        bits ^= low


def iter_contiguous_boxes(
    topo: Topology,
    sorted_free: list[Coord],
    free_set: set,
    count: int,
    max_candidates: int,
) -> Iterator[tuple[Coord, ...]]:
    """THE canonical contiguous-candidate stream: compact-first shapes ×
    free-anchored origins, fully-free boxes only, deduped, capped at
    ``max_candidates``.  The one Python copy — shared by
    ``ChipSet._whole_chip_candidates`` and ``plan_gang_fallback`` so the
    per-container search and the whole-gang kernel can never walk different
    streams (native/placement.cc replicates it in C++; tests/test_native.py
    asserts equality)."""
    emitted = 0
    seen: set[frozenset] = set()
    for shape in topo.box_shapes(count):
        for box in topo.placements_at(shape, sorted_free):
            if emitted >= max_candidates:
                return
            if all(c in free_set for c in box):
                key = frozenset(box)
                if key in seen:
                    continue
                seen.add(key)
                emitted += 1
                yield box


class _ChipsView(Mapping):
    """Read/write mapping view over a ChipSet's packed chip state.

    Keeps the classic ``cs.chips[coord].take_whole()`` surface working on
    top of the array/bitset representation; yields ``ChipRef`` views whose
    mutations write through to the owning set.
    """

    __slots__ = ("_cs",)

    def __init__(self, cs: "ChipSet"):
        self._cs = cs

    def __getitem__(self, coord: Coord) -> ChipRef:
        return ChipRef(self._cs, self._cs._slot[coord])

    def __iter__(self) -> Iterator[Coord]:
        return iter(self._cs._coords)

    def __len__(self) -> int:
        return len(self._cs._coords)

    def __contains__(self, coord: object) -> bool:
        return coord in self._cs._slot


class ChipSet:
    """A set of TPU chips addressed by coordinates in a (possibly larger) mesh.

    ``topo`` describes the full mesh the coordinates live in; ``chips`` may
    cover only part of it (a host's chips within a slice).

    State is packed: parallel total/avail arrays in canonical (row-major)
    coordinate order plus a ``_free_bits`` bitset (untouched chips)
    maintained incrementally by the single ``_set_slot`` choke point.  ``clone()`` is
    therefore a handful of list copies and int assignments (O(words)), not
    O(chips) Python objects: the gang planner clones per-node state for
    every candidate node of every plan, which made object-graph cloning a
    measurable slice of the 1024-member plan wall.  ``chips`` remains a
    mapping view (`ChipRef` values) for compatibility.
    """

    def __init__(self, topo: Topology, chips: Iterable[Chip]):
        self.topo = topo
        entries: dict[Coord, Chip] = {}
        for ch in chips:
            if not topo.contains(ch.coord):
                raise ValueError(f"chip coord {ch.coord} outside topology {topo.dims}")
            if ch.coord in entries:
                raise ValueError(f"duplicate chip coord {ch.coord}")
            entries[ch.coord] = ch
        ordered = sorted(entries.values(), key=lambda c: topo.index(c.coord))
        self._coords: tuple[Coord, ...] = tuple(c.coord for c in ordered)
        self._slot: dict[Coord, int] = {c: i for i, c in enumerate(self._coords)}
        self._mesh_idx: tuple[int, ...] = tuple(
            topo.index(c) for c in self._coords
        )
        self._core_total: list[int] = [c.core_total for c in ordered]
        self._hbm_total: list[int] = [c.hbm_total for c in ordered]
        self._core_avail: list[int] = [c.core_avail for c in ordered]
        self._hbm_avail: list[int] = [c.hbm_avail for c in ordered]
        self._geom = None  # lazy relative-geometry token (plan_key)
        # mutation stamp: refreshed (from the global counter) by every
        # _set_slot/_set_total and at construction, copied by clone() (a
        # clone's mutations never touch the parent).  The capacity index
        # (core/index.py) records it per entry and skips re-deriving a
        # node whose stamp hasn't moved — a GIL-atomic int read replaces
        # a lock + box scan for spuriously-dirtied nodes.
        self._version = 0
        self._resync()

    @property
    def version(self) -> int:
        return self._version

    def _resync(self) -> None:
        """Rebuild bitsets + sums from the arrays (construction / refresh)."""
        self._version = next(_VERSIONS)
        free = 0
        for i in range(len(self._coords)):
            if (
                self._core_avail[i] == self._core_total[i]
                and self._hbm_avail[i] == self._hbm_total[i]
            ):
                free |= 1 << i
        self._free_bits = free
        self._avail_core_sum = sum(self._core_avail)
        self._avail_hbm_sum = sum(self._hbm_avail)
        self._total_core_sum = sum(self._core_total)
        self._total_hbm_sum = sum(self._hbm_total)

    def _set_slot(self, i: int, core_avail: int, hbm_avail: int) -> None:
        """THE mutation choke point: every chip-state change lands here so
        the bitsets and sums can never drift from the arrays."""
        self._version = next(_VERSIONS)
        self._avail_core_sum += core_avail - self._core_avail[i]
        self._avail_hbm_sum += hbm_avail - self._hbm_avail[i]
        self._core_avail[i] = core_avail
        self._hbm_avail[i] = hbm_avail
        if core_avail == self._core_total[i] and hbm_avail == self._hbm_total[i]:
            self._free_bits |= 1 << i
        else:
            self._free_bits &= ~(1 << i)

    def _set_total(self, i: int, core_total: int | None = None,
                   hbm_total: int | None = None) -> None:
        if core_total is not None:
            self._total_core_sum += core_total - self._core_total[i]
            self._core_total[i] = core_total
        if hbm_total is not None:
            self._total_hbm_sum += hbm_total - self._hbm_total[i]
            self._hbm_total[i] = hbm_total
        # re-derive this chip's free/partial bits under the new totals
        self._set_slot(i, self._core_avail[i], self._hbm_avail[i])

    # -- introspection -------------------------------------------------------

    @property
    def chips(self) -> _ChipsView:
        return _ChipsView(self)

    @property
    def num_chips(self) -> int:
        return len(self._coords)

    def free_count(self) -> int:
        """Untouched-chip count in O(1) (popcount of the free bitset)."""
        return self._free_bits.bit_count()

    def free_chips(self) -> list[ChipRef]:
        """Untouched chips in canonical (row-major) coordinate order."""
        return [ChipRef(self, i) for i in iter_bits(self._free_bits)]

    def total_core(self) -> int:
        return self._total_core_sum

    def avail_core(self) -> int:
        return self._avail_core_sum

    def total_hbm(self) -> int:
        return self._total_hbm_sum

    def avail_hbm(self) -> int:
        return self._avail_hbm_sum

    def clone(self) -> "ChipSet":
        new = ChipSet.__new__(ChipSet)
        new.topo = self.topo
        new._version = self._version
        # immutable identity: shared across the whole clone lineage
        new._coords = self._coords
        new._slot = self._slot
        new._mesh_idx = self._mesh_idx
        new._geom = self._geom
        # mutable state: flat int-list copies + bitset ints — O(words)
        new._core_total = self._core_total[:]
        new._hbm_total = self._hbm_total[:]
        new._core_avail = self._core_avail[:]
        new._hbm_avail = self._hbm_avail[:]
        new._free_bits = self._free_bits
        new._avail_core_sum = self._avail_core_sum
        new._avail_hbm_sum = self._avail_hbm_sum
        new._total_core_sum = self._total_core_sum
        new._total_hbm_sum = self._total_hbm_sum
        return new

    def inventory(self) -> dict:
        """Journal wire form of the set's capacity: topology + per-chip
        totals (``journal`` node_add/node_resync records; availability is
        derived by replaying the mutation stream, never snapshotted)."""
        return {
            "dims": list(self.topo.dims),
            "wrap": [bool(w) for w in self.topo.wrap],
            "chips": [
                [list(co), self._core_total[i], self._hbm_total[i]]
                for i, co in enumerate(self._coords)
            ],
        }

    def largest_free_box(self, max_candidates: int = 16) -> int:
        """Chip count of the largest fully-free contiguous axis-aligned
        sub-box.  Scans candidate volumes descending, first hit wins —
        O(free²·shapes) worst case, intended for HOST-sized views (4-8
        chips); slice-wide sets should not call this per mutation."""
        free_n = self._free_bits.bit_count()
        if free_n == 0:
            return 0
        sorted_free = [self._coords[i] for i in iter_bits(self._free_bits)]
        free_set = set(sorted_free)
        for k in range(free_n, 1, -1):
            for _box in iter_contiguous_boxes(
                self.topo, sorted_free, free_set, k, max_candidates
            ):
                return k
        return 1  # any free chip is a 1-box

    def fragmentation(self) -> tuple[float, int, int]:
        """(fragmentation_index, largest_free_box, free_chips) for the
        scrape-time mesh gauges: index = 1 - largest/free (0 = the free
        set IS one contiguous sub-box or the set is fully busy)."""
        free_n = self._free_bits.bit_count()
        if free_n == 0:
            return 0.0, 0, 0
        largest = self.largest_free_box()
        return round(1.0 - largest / free_n, 4), largest, free_n

    def status(self) -> dict:
        return {
            "topology": self.topo.spec(),
            "chips": {
                ".".join(map(str, co)): {
                    "core_avail": self._core_avail[i],
                    "core_total": self._core_total[i],
                    "hbm_avail": self._hbm_avail[i],
                    "hbm_total": self._hbm_total[i],
                }
                for i, co in enumerate(self._coords)
            },
        }

    # -- plan memoization keys ----------------------------------------------

    def _geometry(self) -> tuple:
        """Translation-normalized geometry token: two ChipSets with equal
        tokens own congruent coordinate sets (same relative positions in the
        same mesh), so a placement found on one maps slot-for-slot onto the
        other.  A set that straddles a torus seam on a wrapped axis contains
        both 0 and dims-1 there, forcing base 0 — such sets only compare
        equal to absolutely-identical ones, so wrapping candidate boxes can
        never be mis-translated."""
        g = self._geom
        if g is None:
            if not self._coords:
                g = (self.topo.dims, self.topo.wrap, ())
            else:
                nd = len(self.topo.dims)
                base = tuple(
                    min(c[a] for c in self._coords) for a in range(nd)
                )
                rel = tuple(
                    tuple(v - b for v, b in zip(c, base)) for c in self._coords
                )
                g = (self.topo.dims, self.topo.wrap, rel)
            self._geom = g
        return g

    def plan_key(self) -> tuple:
        """Hashable token of relative geometry + full chip state.  Equal
        keys → ``trade`` walks an identical candidate stream and (for
        translation-invariant raters) scores candidates identically, so the
        winning placement can be replayed by local slot index
        (``option_from_template``) without re-running the DFS."""
        return (
            self._geometry(),
            tuple(self._core_total),
            tuple(self._hbm_total),
            tuple(self._core_avail),
            tuple(self._hbm_avail),
        )

    def option_template(self, option: Option) -> tuple:
        """Strip an Option to slot indices (coordinate-free, memoizable)."""
        return (
            option.score,
            tuple(
                (
                    a.container,
                    tuple(self._slot[c] for c in a.coords),
                    a.whole,
                    a.core,
                    a.hbm,
                    a.contiguous,
                )
                for a in option.allocs
            ),
        )

    def option_from_template(self, tmpl: tuple, request_hash: str) -> Option:
        """Rehydrate a memoized placement onto THIS set's coordinates."""
        score, allocs = tmpl
        return Option(
            request_hash,
            tuple(
                ContainerAlloc(
                    container=name,
                    coords=tuple(self._coords[i] for i in slots),
                    whole=whole,
                    core=core,
                    hbm=hbm,
                    contiguous=contiguous,
                )
                for name, slots, whole, core, hbm, contiguous in allocs
            ),
            score,
        )

    # -- candidate generation ------------------------------------------------

    # meshes at/above this size route box enumeration to the C++ extension
    NATIVE_THRESHOLD = 16

    def _free_mask(self) -> bytes:
        """Row-major 0/1 mask over the FULL mesh (unowned coords = 0)."""
        mask = bytearray(self.topo.num_chips)
        mesh_idx = self._mesh_idx
        for i in iter_bits(self._free_bits):
            mask[mesh_idx[i]] = 1
        return bytes(mask)

    def _whole_chip_candidates(
        self, count: int, max_candidates: int
    ) -> Iterator[tuple[tuple[Coord, ...], bool]]:
        """Candidate coord-sets for a `count`-whole-chip container.

        Yields (coords, contiguous).  Contiguous axis-aligned sub-boxes first
        (most compact shapes first), then one non-contiguous fallback taking
        free chips in canonical order — so a fragmented mesh still schedules,
        just with a locality penalty applied by the rater.

        Large meshes use the native C++ enumerator (core/native.py); results
        are identical to the Python path (tests/test_native.py).
        """
        if self._free_bits.bit_count() < count:
            return
        # slots are canonical (row-major) order, so free coords come out
        # already sorted by mesh index
        sorted_free = [self._coords[i] for i in iter_bits(self._free_bits)]
        free = set(sorted_free)
        emitted = 0
        # the C++ mask scan is O(mesh); it wins only when this set OWNS a
        # large share of the mesh.  A host view (4-8 chips of a 1024-chip
        # slice) enumerates faster from its own free cells (placements_at)
        # than by scanning the full mesh — keying the threshold on owned
        # chips, not mesh size, was the 1024-member gang-plan hot fix.
        if len(self._coords) >= self.NATIVE_THRESHOLD:
            from .native import get_placement

            native = get_placement()
            if native is not None:
                boxes = native.enumerate_free_boxes(
                    self.topo.dims,
                    self.topo.wrap,
                    self._free_mask(),
                    count,
                    max_candidates,
                )
                for idx_box in boxes:
                    emitted += 1
                    yield tuple(self.topo.coord_of(i) for i in idx_box), True
                if emitted == 0:
                    yield tuple(sorted_free[:count]), False
                return
        for box in iter_contiguous_boxes(
            self.topo, sorted_free, free, count, max_candidates
        ):
            emitted += 1
            yield box, True
        if emitted == 0:
            yield tuple(sorted_free[:count]), False

    def _fractional_candidates(self, core: int, hbm: int) -> Iterator[Coord]:
        for i, coord in enumerate(self._coords):
            if self._core_avail[i] >= core and self._hbm_avail[i] >= hbm:
                yield coord

    # -- the search ----------------------------------------------------------

    def trade(
        self,
        request: TPURequest,
        rater: Rater,
        search_budget: int = DEFAULT_SEARCH_BUDGET,
        max_candidates_per_container: int = 64,
    ) -> Optional[Option]:
        """Find the best-scoring placement for all containers, or None.

        DFS over containers; each complete assignment is applied, rated, and
        rolled back.  Best score wins; ties keep the FIRST found (deterministic
        — the reference keeps the last due to a strict `>` guard, gpu.go:85;
        deviation documented in SURVEY §5).
        """
        units = list(zip(request.container_names, request.units))
        chosen: list[ContainerAlloc] = []
        best: list[Optional[Option]] = [None]
        budget = [search_budget]
        req_hash = request.hash()  # invariant across the whole search

        def dfs(i: int) -> None:
            if budget[0] <= 0:
                return
            if i == len(units):
                budget[0] -= 1
                opt = Option(req_hash, tuple(chosen))
                score = rater.rate(self, opt)
                opt.score = score
                if best[0] is None or score > best[0].score:
                    best[0] = opt
                return
            name, unit = units[i]
            if not unit.needs_tpu:
                chosen.append(
                    ContainerAlloc(container=name, coords=(), whole=False)
                )
                dfs(i + 1)
                chosen.pop()
                return
            if unit.wants_whole_chips:
                for coords, contiguous in self._whole_chip_candidates(
                    unit.chip_count, max_candidates_per_container
                ):
                    alloc = ContainerAlloc(
                        container=name, coords=coords, whole=True,
                        contiguous=contiguous,
                    )
                    self._apply(alloc)
                    chosen.append(alloc)
                    dfs(i + 1)
                    chosen.pop()
                    self._revert(alloc)
                    if budget[0] <= 0:
                        return
            else:
                core = max(unit.core, 0)
                hbm = unit.hbm
                n = 0
                for coord in self._fractional_candidates(core, hbm):
                    alloc = ContainerAlloc(
                        container=name, coords=(coord,), whole=False,
                        core=core, hbm=hbm,
                    )
                    self._apply(alloc)
                    chosen.append(alloc)
                    dfs(i + 1)
                    chosen.pop()
                    self._revert(alloc)
                    n += 1
                    if n >= max_candidates_per_container or budget[0] <= 0:
                        return

        dfs(0)
        return best[0]

    # -- state transitions ---------------------------------------------------

    def _apply(self, alloc: ContainerAlloc) -> None:
        slot = self._slot
        if alloc.whole:
            for c in alloc.coords:
                i = slot[c]
                if not (self._free_bits >> i & 1):
                    raise ValueError(f"chip {c}: not free for whole-chip take")
                self._set_slot(i, 0, 0)
        else:
            core, hbm = alloc.core, alloc.hbm
            for c in alloc.coords:
                i = slot[c]
                ca, ha = self._core_avail[i], self._hbm_avail[i]
                if ca < core or ha < hbm:
                    raise ValueError(
                        f"chip {c}: cannot take core={core} hbm={hbm} "
                        f"(avail core={ca} hbm={ha})"
                    )
                self._set_slot(i, ca - core, ha - hbm)

    def _revert(self, alloc: ContainerAlloc) -> None:
        slot = self._slot
        if alloc.whole:
            for c in alloc.coords:
                i = slot[c]
                self._set_slot(i, self._core_total[i], self._hbm_total[i])
        else:
            core, hbm = alloc.core, alloc.hbm
            for c in alloc.coords:
                i = slot[c]
                self._set_slot(
                    i,
                    min(self._core_total[i], self._core_avail[i] + core),
                    min(self._hbm_total[i], self._hbm_avail[i] + hbm),
                )

    def _tally(
        self, option: Option
    ) -> Optional[tuple[set[Coord], dict[Coord, int], dict[Coord, int]]]:
        """Aggregate an option's per-chip demand: (whole-chip coords,
        fractional core by coord, fractional hbm by coord).  None if any
        coord is unknown or a whole-chip coord repeats — shared by
        ``can_transact`` and ``can_cancel`` so the accounting can't
        diverge."""
        core: dict[Coord, int] = {}
        hbm: dict[Coord, int] = {}
        whole: set[Coord] = set()
        for a in option.allocs:
            if not a.needs_tpu:
                continue
            for c in a.coords:
                if c not in self._slot:
                    return None
                if a.whole:
                    if c in whole:
                        return None
                    whole.add(c)
                else:
                    core[c] = core.get(c, 0) + a.core
                    hbm[c] = hbm.get(c, 0) + a.hbm
        return whole, core, hbm

    def can_transact(self, option: Option) -> bool:
        """Check the whole option fits the current state without mutating it."""
        tally = self._tally(option)
        if tally is None:
            return False
        whole_need, core_need, hbm_need = tally
        for c in whole_need:
            if not (self._free_bits >> self._slot[c] & 1) or c in core_need:
                return False
        for c, need in core_need.items():
            i = self._slot[c]
            if self._core_avail[i] < need or self._hbm_avail[i] < hbm_need.get(c, 0):
                return False
        return True

    def transact(self, option: Option) -> None:
        """Commit an option (reference: gpu.go:153-175).  All-or-nothing:
        the option is validated in full before any chip is touched, so a
        mid-apply failure can never leak partial allocations."""
        if not self.can_transact(option):
            raise ValueError(f"option {option.request_hash} no longer fits")
        for a in option.allocs:
            if a.needs_tpu:
                self._apply(a)

    def can_cancel(self, option: Option) -> bool:
        """Check the option is plausibly CHARGED to the current state — i.e.
        cancelling it frees only capacity that is actually in use.  Needed
        because ``Chip.give`` clamps at total (a double-free would otherwise
        silently inflate capacity): callers holding options of uncertain
        provenance (e.g. preemption victims' annotations) must validate
        before cancelling."""
        tally = self._tally(option)
        if tally is None:
            return False
        whole_free, core_free, hbm_free = tally
        for c in whole_free:
            i = self._slot[c]
            # a whole-chip holder has the chip exclusively and fully taken
            if self._core_avail[i] != 0 or self._hbm_avail[i] != 0 or c in core_free:
                return False
        for c, freed in core_free.items():
            i = self._slot[c]
            if (self._core_total[i] - self._core_avail[i]) < freed:
                return False
            if (self._hbm_total[i] - self._hbm_avail[i]) < hbm_free.get(c, 0):
                return False
        return True

    def cancel(self, option: Option) -> None:
        """Roll back a committed option (reference: gpu.go:177-191)."""
        for a in option.allocs:
            if a.needs_tpu:
                self._revert(a)


# -- gang-plan kernel (Python fallback of native plan_gang) -------------------


def whole_box_bonus(coords: tuple[Coord, ...]) -> float:
    """Locality bonus of ONE contiguous whole-chip box: fill of the
    bounding box, penalized by elongation.  The single Python copy of this
    formula — rater._locality_bonus calls it per alloc, the gang-plan
    kernels use it for candidate argmax, and native/placement.cc replicates
    it bit-for-bit in C++ (including the single-chip literal shortcut:
    1.0 - 0.3 is one ulp away from the 0.7 literal in IEEE doubles)."""
    if len(coords) == 1:
        # bb=(1,..), fill=1, elong=1 → 1·(1-0.3) exactly; skipping
        # bounding_box here halves gang-plan rating cost
        return 0.7
    bb = bounding_box(coords)
    vol = 1
    for d in bb:
        vol *= d
    fill = len(coords) / vol if vol else 0.0
    elong = max(bb) / max(1, len(coords))
    return max(0.0, min(1.0, fill * (1.0 - 0.3 * elong)))


def plan_gang_fallback(
    topo: Topology,
    free_lists: list[tuple[int, ...]],
    count: int,
    members: int,
    max_candidates: int = 64,
) -> list[tuple[int, tuple[int, ...], bool]]:
    """Pure-Python gang-plan kernel: greedily place up to ``members``
    identical ``count``-whole-chip members onto per-node free sets.

    ``free_lists[n]`` holds node n's free cells as row-major mesh indices
    (ascending).  Nodes are consumed with a forward-only cursor (members are
    identical: a node full for one is full for all).  Per member the node's
    candidate stream is the canonical compact-first enumeration of
    ``ChipSet._whole_chip_candidates`` and the winner is the highest
    ``whole_box_bonus`` with first-wins ties — exactly the choice
    ``ChipSet.trade`` makes for a single whole-chip container under any
    rater whose non-locality terms are candidate-invariant (Binpack /
    Spread / ICILocality; see Rater.whole_chip_compact_first).

    Returns ``[(node_idx, sorted_mesh_indices, contiguous), ...]`` — one
    entry per placed member, possibly fewer than ``members`` when capacity
    runs out.  The native kernel (native/placement.cc plan_gang) is
    bit-identical; tests/test_native.py asserts it.
    """
    out: list[tuple[int, tuple[int, ...], bool]] = []
    remaining: list[list[int]] = [sorted(f) for f in free_lists]
    cursor = 0
    while len(out) < members and cursor < len(remaining):
        free_idx = remaining[cursor]
        if len(free_idx) < count:
            cursor += 1
            continue
        free_coords = [topo.coord_of(i) for i in free_idx]
        free_set = set(free_coords)
        best: Optional[tuple[tuple[Coord, ...], bool]] = None
        best_bonus = -1.0
        for box in iter_contiguous_boxes(
            topo, free_coords, free_set, count, max_candidates
        ):
            bonus = whole_box_bonus(box)
            if bonus > best_bonus:
                best_bonus = bonus
                best = (box, True)
        if best is None:  # no contiguous box: non-contiguous fallback
            best = (tuple(free_coords[:count]), False)
        box, contiguous = best
        idxs = tuple(sorted(topo.index(c) for c in box))
        taken = set(idxs)
        remaining[cursor] = [i for i in free_idx if i not in taken]
        out.append((cursor, idxs, contiguous))
    return out


def plan_gang_batch_fallback(
    topo: Topology,
    free_lists: list[tuple[int, ...]],
    specs: list[tuple[int, int]],
    max_candidates: int = 64,
) -> list[list[tuple[int, tuple[int, ...], bool]]]:
    """Pure-Python batch gang-plan kernel: plan a QUEUE of gangs — one
    ``(count, members)`` spec per gang, in arrival order — against one set
    of per-node free lists, each gang consuming what the previous placed.

    Semantics are EXACTLY sequential ``plan_gang`` calls with the free
    lists carried forward, all-or-nothing per spec: a spec that cannot
    place every member consumes NOTHING (its partial placements are
    discarded), is returned as an empty list, and — because later gangs'
    placements must not be derived from capacity an earlier failed gang
    would have consumed in a sequential replan — processing STOPS there:
    every later spec is returned empty and unconsumed, for the caller to
    re-plan through the general path.  The native kernel
    (native/placement.cc plan_gang_batch) is bit-identical;
    tests/test_cluster_index.py asserts it.
    """
    remaining: list[tuple[int, ...]] = [tuple(sorted(f)) for f in free_lists]
    out: list[list[tuple[int, tuple[int, ...], bool]]] = []
    failed = False
    for count, members in specs:
        if failed:
            out.append([])
            continue
        placed = plan_gang_fallback(
            topo, list(remaining), count, members, max_candidates
        )
        if len(placed) < members:
            out.append([])
            failed = True
            continue
        for node_i, idxs, _contig in placed:
            taken = set(idxs)
            remaining[node_i] = tuple(
                i for i in remaining[node_i] if i not in taken
            )
        out.append(placed)
    return out
