"""Placement search and allocation state.

TPU-native rebuild of the reference's allocation engine:

- ``GPUs.Trade`` exhaustive DFS over anonymous card indices
  (reference: pkg/scheduler/gpu.go:65-129)  →  ``ChipSet.trade``: a DFS over
  containers whose whole-chip candidates are *contiguous ICI sub-boxes*
  (compact-first canonical enumeration, topology.box_shapes/placements) with a
  non-contiguous fallback, and whose complete assignments are scored by a
  pluggable ``Rater``.
- ``GPUs.Transact/Cancel`` (gpu.go:153-191)  →  ``ChipSet.transact/cancel``.
- ``NodeAllocator`` (pkg/scheduler/node.go)  →  same name; caches the assume
  result per request hash for reuse by score/bind, with two reference bugs
  fixed: the hash is pod-unique (node.go:63-64 collides across same-shaped
  pods) and ``score`` never dereferences a missing option (node.go:78-84
  nil-deref).

``ChipSet`` is deliberately node-agnostic: a host view (4-8 chips of a slice)
and a slice view (all chips, for gang placement) are the same type, so the
gang scheduler reuses this search unchanged at slice scale.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator, Optional

from .chip import Chip
from .request import TPURequest
from .topology import Coord, Topology

# Search budget: max complete assignments rated per trade() call.  The
# reference's DFS is unbounded (gpu.go:65-129) and explodes combinatorially;
# we keep best-so-far semantics under a budget so worst-case latency is capped.
DEFAULT_SEARCH_BUDGET = 4096


@dataclass(frozen=True)
class ContainerAlloc:
    """One container's placement: which chips, and how much of each."""

    container: str
    coords: tuple[Coord, ...]
    whole: bool  # True → whole chips (all core+hbm of each coord)
    core: int = 0  # per-chip core units when fractional
    hbm: int = 0  # per-chip HBM GiB when fractional
    contiguous: bool = True  # whole-chip: did we get an ICI-contiguous box?

    @property
    def needs_tpu(self) -> bool:
        return bool(self.coords)


@dataclass
class Option:
    """A complete placement decision for one pod on one node/slice.

    Mirrors the reference's GPUOption (pkg/scheduler/allocate.go:60-93) with
    coordinates instead of flat indices.
    """

    request_hash: str
    allocs: tuple[ContainerAlloc, ...]
    score: float = 0.0

    def coords_by_container(self) -> dict[str, tuple[Coord, ...]]:
        return {a.container: a.coords for a in self.allocs}


class Rater:
    """Placement policy: rate a complete assignment (reference: rater.go:8-10).

    ``rate`` is called with the ChipSet *after* the option has been applied,
    matching the reference's rate-post-assignment convention (rater.go:30-50).
    Scores are floats in [0, 100]; the extender layer normalizes to 0-10.
    """

    name = "rater"

    def rate(self, chips: "ChipSet", option: Option) -> float:
        raise NotImplementedError


class ChipSet:
    """A set of TPU chips addressed by coordinates in a (possibly larger) mesh.

    ``topo`` describes the full mesh the coordinates live in; ``chips`` may
    cover only part of it (a host's chips within a slice).
    """

    def __init__(self, topo: Topology, chips: Iterable[Chip]):
        self.topo = topo
        self.chips: dict[Coord, Chip] = {}
        for ch in chips:
            if not topo.contains(ch.coord):
                raise ValueError(f"chip coord {ch.coord} outside topology {topo.dims}")
            if ch.coord in self.chips:
                raise ValueError(f"duplicate chip coord {ch.coord}")
            self.chips[ch.coord] = ch

    # -- introspection -------------------------------------------------------

    @property
    def num_chips(self) -> int:
        return len(self.chips)

    def free_chips(self) -> list[Chip]:
        """Untouched chips in canonical (row-major) coordinate order."""
        return sorted(
            (c for c in self.chips.values() if c.is_free),
            key=lambda c: self.topo.index(c.coord),
        )

    def total_core(self) -> int:
        return sum(c.core_total for c in self.chips.values())

    def avail_core(self) -> int:
        return sum(c.core_avail for c in self.chips.values())

    def total_hbm(self) -> int:
        return sum(c.hbm_total for c in self.chips.values())

    def avail_hbm(self) -> int:
        return sum(c.hbm_avail for c in self.chips.values())

    def clone(self) -> "ChipSet":
        return ChipSet(self.topo, (c.clone() for c in self.chips.values()))

    def status(self) -> dict:
        return {
            "topology": self.topo.spec(),
            "chips": {
                ".".join(map(str, co)): {
                    "core_avail": ch.core_avail,
                    "core_total": ch.core_total,
                    "hbm_avail": ch.hbm_avail,
                    "hbm_total": ch.hbm_total,
                }
                for co, ch in sorted(
                    self.chips.items(), key=lambda kv: self.topo.index(kv[0])
                )
            },
        }

    # -- candidate generation ------------------------------------------------

    # meshes at/above this size route box enumeration to the C++ extension
    NATIVE_THRESHOLD = 16

    def _free_mask(self) -> bytes:
        """Row-major 0/1 mask over the FULL mesh (unowned coords = 0)."""
        mask = bytearray(self.topo.num_chips)
        for c in self.chips.values():
            if c.is_free:
                mask[self.topo.index(c.coord)] = 1
        return bytes(mask)

    def _whole_chip_candidates(
        self, count: int, max_candidates: int
    ) -> Iterator[tuple[tuple[Coord, ...], bool]]:
        """Candidate coord-sets for a `count`-whole-chip container.

        Yields (coords, contiguous).  Contiguous axis-aligned sub-boxes first
        (most compact shapes first), then one non-contiguous fallback taking
        free chips in canonical order — so a fragmented mesh still schedules,
        just with a locality penalty applied by the rater.

        Large meshes use the native C++ enumerator (core/native.py); results
        are identical to the Python path (tests/test_native.py).
        """
        free = {co for co, ch in self.chips.items() if ch.is_free}
        if len(free) < count:
            return
        emitted = 0
        # the C++ mask scan is O(mesh); it wins only when this set OWNS a
        # large share of the mesh.  A host view (4-8 chips of a 1024-chip
        # slice) enumerates faster from its own free cells (placements_at)
        # than by scanning the full mesh — keying the threshold on owned
        # chips, not mesh size, was the 1024-member gang-plan hot fix.
        if len(self.chips) >= self.NATIVE_THRESHOLD:
            from .native import get_placement

            native = get_placement()
            if native is not None:
                boxes = native.enumerate_free_boxes(
                    self.topo.dims,
                    self.topo.wrap,
                    self._free_mask(),
                    count,
                    max_candidates,
                )
                for idx_box in boxes:
                    emitted += 1
                    yield tuple(self.topo.coord_of(i) for i in idx_box), True
                if emitted == 0:
                    fallback = tuple(sorted(free, key=self.topo.index)[:count])
                    yield fallback, False
                return
        seen: set[frozenset] = set()
        sorted_free = sorted(free, key=self.topo.index)
        for shape in self.topo.box_shapes(count):
            for box in self.topo.placements_at(shape, sorted_free):
                if emitted >= max_candidates:
                    break
                if all(c in free for c in box):
                    key = frozenset(box)
                    if key in seen:
                        continue
                    seen.add(key)
                    emitted += 1
                    yield box, True
            if emitted >= max_candidates:
                break
        if emitted == 0:
            yield tuple(sorted_free[:count]), False

    def _fractional_candidates(self, core: int, hbm: int) -> Iterator[Coord]:
        for ch in sorted(self.chips.values(), key=lambda c: self.topo.index(c.coord)):
            if ch.can_fit(core, hbm):
                yield ch.coord

    # -- the search ----------------------------------------------------------

    def trade(
        self,
        request: TPURequest,
        rater: Rater,
        search_budget: int = DEFAULT_SEARCH_BUDGET,
        max_candidates_per_container: int = 64,
    ) -> Optional[Option]:
        """Find the best-scoring placement for all containers, or None.

        DFS over containers; each complete assignment is applied, rated, and
        rolled back.  Best score wins; ties keep the FIRST found (deterministic
        — the reference keeps the last due to a strict `>` guard, gpu.go:85;
        deviation documented in SURVEY §5).
        """
        units = list(zip(request.container_names, request.units))
        chosen: list[ContainerAlloc] = []
        best: list[Optional[Option]] = [None]
        budget = [search_budget]
        req_hash = request.hash()  # invariant across the whole search

        def dfs(i: int) -> None:
            if budget[0] <= 0:
                return
            if i == len(units):
                budget[0] -= 1
                opt = Option(req_hash, tuple(chosen))
                score = rater.rate(self, opt)
                opt.score = score
                if best[0] is None or score > best[0].score:
                    best[0] = opt
                return
            name, unit = units[i]
            if not unit.needs_tpu:
                chosen.append(
                    ContainerAlloc(container=name, coords=(), whole=False)
                )
                dfs(i + 1)
                chosen.pop()
                return
            if unit.wants_whole_chips:
                for coords, contiguous in self._whole_chip_candidates(
                    unit.chip_count, max_candidates_per_container
                ):
                    alloc = ContainerAlloc(
                        container=name, coords=coords, whole=True,
                        contiguous=contiguous,
                    )
                    self._apply(alloc)
                    chosen.append(alloc)
                    dfs(i + 1)
                    chosen.pop()
                    self._revert(alloc)
                    if budget[0] <= 0:
                        return
            else:
                core = max(unit.core, 0)
                hbm = unit.hbm
                n = 0
                for coord in self._fractional_candidates(core, hbm):
                    alloc = ContainerAlloc(
                        container=name, coords=(coord,), whole=False,
                        core=core, hbm=hbm,
                    )
                    self._apply(alloc)
                    chosen.append(alloc)
                    dfs(i + 1)
                    chosen.pop()
                    self._revert(alloc)
                    n += 1
                    if n >= max_candidates_per_container or budget[0] <= 0:
                        return

        dfs(0)
        return best[0]

    # -- state transitions ---------------------------------------------------

    def _apply(self, alloc: ContainerAlloc) -> None:
        if alloc.whole:
            for c in alloc.coords:
                self.chips[c].take_whole()
        else:
            for c in alloc.coords:
                self.chips[c].take(alloc.core, alloc.hbm)

    def _revert(self, alloc: ContainerAlloc) -> None:
        if alloc.whole:
            for c in alloc.coords:
                self.chips[c].give_whole()
        else:
            for c in alloc.coords:
                self.chips[c].give(alloc.core, alloc.hbm)

    def _tally(
        self, option: Option
    ) -> Optional[tuple[set[Coord], dict[Coord, int], dict[Coord, int]]]:
        """Aggregate an option's per-chip demand: (whole-chip coords,
        fractional core by coord, fractional hbm by coord).  None if any
        coord is unknown or a whole-chip coord repeats — shared by
        ``can_transact`` and ``can_cancel`` so the accounting can't
        diverge."""
        core: dict[Coord, int] = {}
        hbm: dict[Coord, int] = {}
        whole: set[Coord] = set()
        for a in option.allocs:
            if not a.needs_tpu:
                continue
            for c in a.coords:
                if c not in self.chips:
                    return None
                if a.whole:
                    if c in whole:
                        return None
                    whole.add(c)
                else:
                    core[c] = core.get(c, 0) + a.core
                    hbm[c] = hbm.get(c, 0) + a.hbm
        return whole, core, hbm

    def can_transact(self, option: Option) -> bool:
        """Check the whole option fits the current state without mutating it."""
        tally = self._tally(option)
        if tally is None:
            return False
        whole_need, core_need, hbm_need = tally
        for c in whole_need:
            if not self.chips[c].is_free or c in core_need:
                return False
        for c, need in core_need.items():
            ch = self.chips[c]
            if ch.core_avail < need or ch.hbm_avail < hbm_need.get(c, 0):
                return False
        return True

    def transact(self, option: Option) -> None:
        """Commit an option (reference: gpu.go:153-175).  All-or-nothing:
        the option is validated in full before any chip is touched, so a
        mid-apply failure can never leak partial allocations."""
        if not self.can_transact(option):
            raise ValueError(f"option {option.request_hash} no longer fits")
        for a in option.allocs:
            if a.needs_tpu:
                self._apply(a)

    def can_cancel(self, option: Option) -> bool:
        """Check the option is plausibly CHARGED to the current state — i.e.
        cancelling it frees only capacity that is actually in use.  Needed
        because ``Chip.give`` clamps at total (a double-free would otherwise
        silently inflate capacity): callers holding options of uncertain
        provenance (e.g. preemption victims' annotations) must validate
        before cancelling."""
        tally = self._tally(option)
        if tally is None:
            return False
        whole_free, core_free, hbm_free = tally
        for c in whole_free:
            ch = self.chips[c]
            # a whole-chip holder has the chip exclusively and fully taken
            if ch.core_avail != 0 or ch.hbm_avail != 0 or c in core_free:
                return False
        for c, freed in core_free.items():
            ch = self.chips[c]
            if (ch.core_total - ch.core_avail) < freed:
                return False
            if (ch.hbm_total - ch.hbm_avail) < hbm_free.get(c, 0):
                return False
        return True

    def cancel(self, option: Option) -> None:
        """Roll back a committed option (reference: gpu.go:177-191)."""
        for a in option.allocs:
            if a.needs_tpu:
                self._revert(a)
