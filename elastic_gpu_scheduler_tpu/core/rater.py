"""Placement policies (raters).

Reference: pkg/scheduler/rater.go.  Differences by design:

- scores are bounded floats in [0, 100] (the reference's binpack formula
  routinely exceeds its own declared 0-10 range, rater.go:3-6,49 — SURVEY §5);
  the extender layer maps to the 0-10 integer range.
- ``Spread`` is implemented (the reference's is a ``// TODO`` stub returning 0
  despite being selectable, rater.go:56-59).
- ``ICILocality`` is net-new: rewards topologically compact whole-chip
  placements so XLA collectives ride short ICI paths.
- ``Random`` gives deterministic-per-option pseudo-random scores (useful to
  break pathological herd behavior across scheduler replicas).

All raters rate the ChipSet state *after* the option is applied, matching the
reference's convention (rater.go:30-50).
"""

from __future__ import annotations

import hashlib

from ..utils import consts
from .allocator import ChipSet, ContainerAlloc, Option, Rater, whole_box_bonus


def _consumed_view(chips: ChipSet, alloc: ContainerAlloc):
    """Yield (chip, core_before_assignment) for an applied alloc."""
    for coord in alloc.coords:
        ch = chips.chips[coord]
        before = ch.core_total if alloc.whole else ch.core_avail + alloc.core
        yield ch, before


def _locality_bonus(chips: ChipSet, option: Option) -> float:
    """0..1: how compact the whole-chip placements are.

    The per-box math lives in ``allocator.whole_box_bonus`` — the ONE copy
    the gang-plan kernels (native + fallback) replicate bit-for-bit, so the
    kernels' argmax can never drift from what trade would have rated."""
    scores = []
    for a in option.allocs:
        if not a.whole or not a.coords:
            continue
        scores.append(whole_box_bonus(a.coords) if a.contiguous else 0.0)
    if not scores:
        return 1.0
    return sum(scores) / len(scores)


def _node_used_before(chips: ChipSet, option: Option) -> float:
    """Node-level core utilization BEFORE this option was applied, in [0,1].

    The cross-node signal: the extender scores each node independently, so a
    policy can only steer placement across nodes if the score encodes how
    loaded this node already was (the reference's per-card formula has no
    such term — its binpack cannot consolidate across nodes either)."""
    consumed = 0
    for a in option.allocs:
        if not a.needs_tpu:
            continue
        for c in a.coords:
            ch = chips.chips[c]
            consumed += ch.core_total if a.whole else a.core
    total = max(1, chips.total_core())
    used_after = total - chips.avail_core()
    used_before = used_after - consumed
    return max(0.0, min(1.0, used_before / total))


def _chip_used_before(chips: ChipSet, option: Option) -> float:
    """Mean pre-assignment utilization of the chips this option touches
    (fractional allocs only) — the within-node consolidation signal."""
    vals = []
    for a in option.allocs:
        if a.whole or not a.needs_tpu:
            continue
        for ch, before in _consumed_view(chips, a):
            vals.append(1.0 - before / max(1, ch.core_total))
    return sum(vals) / len(vals) if vals else 0.0


class Binpack(Rater):
    """Consolidate: prefer already-loaded nodes, already-shared chips, and
    placements that preserve fully-free chips (reference intent,
    rater.go:15-51, with a bounded formula and a working cross-node term)."""

    name = consts.PRIORITY_BINPACK
    translation_invariant = True
    whole_chip_compact_first = True

    def rate(self, chips: ChipSet, option: Option) -> float:
        total = max(1, chips.num_chips)
        untouched = chips.free_count()  # O(1) popcount of the free bitset
        preserve = untouched / total  # after assignment: free chips kept whole
        return (
            35.0 * _node_used_before(chips, option)
            + 30.0 * _chip_used_before(chips, option)
            + 25.0 * preserve
            + 10.0 * _locality_bonus(chips, option)
        )


class Spread(Rater):
    """Balance: prefer the emptiest node and the freest chips (the
    reference's Spread is a TODO stub, rater.go:56-59; this is a real one)."""

    name = consts.PRIORITY_SPREAD
    translation_invariant = True
    whole_chip_compact_first = True

    def rate(self, chips: ChipSet, option: Option) -> float:
        # NOTE: no post-assignment variance term — per-node variance rewards
        # both empty and completely-full nodes (var=0), defeating the spread.
        node_free = 1.0 - _node_used_before(chips, option)
        chip_free = 1.0 - _chip_used_before(chips, option)
        return (
            50.0 * node_free
            + 35.0 * chip_free
            + 15.0 * _locality_bonus(chips, option)
        )


class ICILocality(Rater):
    """Topology-first: maximize ICI compactness of whole-chip placements,
    binpack-like otherwise.  This is the default for multi-chip SPMD jobs."""

    name = consts.PRIORITY_ICI
    translation_invariant = True
    whole_chip_compact_first = True

    def rate(self, chips: ChipSet, option: Option) -> float:
        total = max(1, chips.num_chips)
        untouched = chips.free_count()  # O(1) popcount of the free bitset
        return 70.0 * _locality_bonus(chips, option) + 30.0 * (untouched / total)


class Random(Rater):
    """Deterministic pseudo-random per option (seeded by the option's coords).

    Scores hash ABSOLUTE coordinates, so neither planner shortcut applies:
    a memoized placement translated to another node would get a different
    score there (translation_invariant stays False), and the best candidate
    is not the most compact one (whole_chip_compact_first stays False).
    """

    name = consts.PRIORITY_RANDOM

    def rate(self, chips: ChipSet, option: Option) -> float:
        h = hashlib.sha256(option.request_hash.encode())
        for a in option.allocs:
            for c in a.coords:
                h.update(str(c).encode())
        return int.from_bytes(h.digest()[:4], "big") / 0xFFFFFFFF * 100.0


RATERS = {r.name: r for r in (Binpack(), Spread(), ICILocality(), Random())}


def get_rater(name: str) -> Rater:
    try:
        return RATERS[name]
    except KeyError:
        raise ValueError(
            f"unknown priority policy {name!r}; choose from {sorted(RATERS)}"
        ) from None


def to_extender_score(score: float) -> int:
    """Map [0,100] → the extender's declared 0-10 integer range (the reference
    declares the range then violates it, rater.go:3-6; we honor it)."""
    return max(consts.SCORE_MIN, min(consts.SCORE_MAX, round(score / 10.0)))
