"""Per-node allocation view with assume/score/allocate memoization.

TPU rebuild of the reference's NodeAllocator (reference: pkg/scheduler/node.go):

- built from a Node object, not a client — keeps the core unit-testable
  without an API server (the pattern the reference's lone test gestures at,
  pkg/scheduler/scheduler_test.go:11-24).
- ``assume`` caches its Option under the request hash so filter→score→bind
  reuse one placement (node.go:64-72); ``allocate`` consumes the cached option
  (node.go:87-104).
- Fixed vs reference: ``score`` on a cache miss re-assumes and then reads the
  *fresh* option (node.go:78-84 dereferences nil); the hash is pod-unique
  (see core/request.py); capacity is re-readable via ``refresh_from_node``
  instead of being frozen at first sight (scheduler.go:62-64 caches forever).

Chip inventory derivation: the node's allocatable ``elasticgpu.io/tpu-chip``
(core units, 100/chip) gives the chip count; HBM is split evenly across chips
(the reference does the same even split for gpu memory, node.go:33-40, with the
same uniformity caveat).  Coordinates come from the node's topology labels
(host box + offset within the slice); absent labels fall back to a 1-D mesh —
so plain "N chips" nodes work with zero topology configuration.
"""

from __future__ import annotations

from typing import Optional

from ..journal import JOURNAL
from ..metrics import TimedLock
from ..utils import consts
from .allocator import ChipSet, Option, Rater
from .chip import CORE_PER_CHIP, Chip
from .request import TPURequest
from .topology import Coord, Topology, default_wrap, parse_coord, parse_topology


def chips_from_node(node) -> tuple[Topology, list[Chip]]:
    """Derive (slice topology, this host's chips) from a k8s Node object."""
    alloc = node.status.allocatable or {}
    core_units = int(str(alloc.get(consts.RESOURCE_TPU_CORE, "0")))
    hbm_total = int(str(alloc.get(consts.RESOURCE_TPU_HBM, "0")))
    chip_count = core_units // CORE_PER_CHIP
    if chip_count <= 0:
        return Topology((0,)), []
    hbm_per_chip = hbm_total // chip_count

    labels = node.metadata.labels or {}
    family = labels.get(consts.LABEL_TPU_ACCELERATOR, "v5e")
    slice_spec = labels.get(consts.LABEL_TPU_TOPOLOGY)
    host_spec = labels.get(consts.LABEL_TPU_HOST_TOPOLOGY)
    offset_spec = labels.get(consts.LABEL_TPU_HOST_OFFSET)

    if slice_spec:
        slice_dims = parse_topology(slice_spec)
        topo = Topology(slice_dims, default_wrap(family, slice_dims))
        host_dims = parse_topology(host_spec) if host_spec else None
        offset = parse_coord(offset_spec) if offset_spec else (0,) * len(slice_dims)
        if host_dims is None:
            # host owns a row-major prefix of the slice starting at offset
            coords = []
            start = topo.index(offset)
            for i in range(start, start + chip_count):
                coords.append(topo.coord_of(i))
        else:
            host_topo = Topology(host_dims)
            coords = [
                tuple(o + l for o, l in zip(offset, local))
                for local in host_topo.coords()
            ][:chip_count]
    else:
        topo = Topology((chip_count,))
        coords = [(i,) for i in range(chip_count)]

    chips = [Chip(coord=c, hbm_total=hbm_per_chip) for c in coords]
    return topo, chips


class NodeAllocator:
    """One node's chips + the per-request option cache."""

    # assume() cache entries for pods that never reach bind would otherwise
    # live forever (the reference's `allocated` map has the same leak,
    # node.go:64-72); entries older than this are evicted opportunistically.
    OPTION_TTL_S = 300.0

    def __init__(self, node):
        self.node_name = node.metadata.name
        # TPU generation (v4|v5e|v5p|v6e) from the accelerator label —
        # the key the profile observatory's per-generation throughput
        # tables (Gavel-style) aggregate under
        labels = node.metadata.labels or {}
        self.generation = labels.get(consts.LABEL_TPU_ACCELERATOR, "v5e")
        topo, chips = chips_from_node(node)
        self.chips = ChipSet(topo, chips)
        self._init_shared()

    @classmethod
    def from_state(
        cls, node_name: str, generation: str, chips: ChipSet
    ) -> "NodeAllocator":
        """Adopt an already-built ChipSet — the HA warm-takeover path
        (scheduler/ha.py): a journal-shipping follower's replayed chip
        state becomes this node's live allocator WITHOUT a get_node /
        list_pods round-trip per node (the whole cost a cold failover
        pays 10k times).  The ChipSet is adopted, not cloned: the
        follower stops consuming it before takeover swaps it in."""
        self = cls.__new__(cls)
        self.node_name = node_name
        self.generation = generation or "v5e"
        self.chips = chips
        self._init_shared()
        return self

    def _init_shared(self) -> None:
        self.allocated: dict[str, Option] = {}  # request hash → assumed option
        self._allocated_at: dict[str, float] = {}  # request hash → monotonic
        # the mutation shard of the scheduler's lock hierarchy: gang
        # coordinator (10) → engine registry lock (20) → per-node allocator
        # locks (30).  Ranked so an inversion raises instead of deadlocking,
        # and wait-time-instrumented under one shared LOCK_WAIT label
        # ("node") so /metrics shows how long binds queue on node state.
        self.lock = TimedLock("node", rank=30)
        # fired after EVERY committed chip-state mutation (allocate /
        # forget / add / refresh_from_node), while the node lock is still
        # held — the capacity index's dirty-mark hook.  Must be lock-free
        # and O(1) (CapacityIndex.mark_dirty is a GIL-atomic dict write);
        # None costs one truthiness check per mutation.
        self.on_change = None
        # journal handle for resync records: the process-global JOURNAL
        # unless the owning engine injects its own (federation shards —
        # scheduler._create_allocator points this at the shard's stream)
        self.JOURNAL = JOURNAL

    def _notify_change(self) -> None:
        cb = self.on_change
        if cb is not None:
            try:
                cb(self.node_name)
            except Exception:  # a broken hook must never fail a commit
                pass

    def _evict_stale_locked(self) -> None:
        import time

        now = time.monotonic()
        stale = [
            h
            for h, t in self._allocated_at.items()
            if now - t > self.OPTION_TTL_S
        ]
        for h in stale:
            self.allocated.pop(h, None)
            self._allocated_at.pop(h, None)

    # -- verbs (reference: node.go:61-160) -----------------------------------

    def assume(self, request: TPURequest, rater: Rater) -> Optional[Option]:
        import time

        with self.lock:
            self._evict_stale_locked()
            h = request.hash()
            cached = self.allocated.get(h)
            if cached is not None:
                return cached
            opt = self.chips.trade(request, rater)
            if opt is not None:
                self.allocated[h] = opt
                self._allocated_at[h] = time.monotonic()
            return opt

    def score(self, request: TPURequest, rater: Rater) -> Optional[float]:
        opt = self.assume(request, rater)
        return None if opt is None else opt.score

    def allocate(self, request: TPURequest, rater: Rater) -> Option:
        """Pop the cached option (re-assuming if evicted or stale) and commit.

        A cached option can go stale: assume() doesn't reserve chips, so an
        earlier pod's commit may have taken them.  In that case we re-trade
        against current state instead of failing (the reference crashes or
        mis-fails here; SURVEY §5 request-hash/cache quirks).
        """
        with self.lock:
            h = request.hash()
            opt = self.allocated.pop(h, None)
            self._allocated_at.pop(h, None)
            if opt is not None and not self.chips.can_transact(opt):
                opt = None  # stale — placement taken since assume
            if opt is None:
                opt = self.chips.trade(request, rater)
            if opt is None:
                raise RuntimeError(
                    f"node {self.node_name}: cannot find option for {request.pod_key}"
                )
            self.chips.transact(opt)
            self._notify_change()
            return opt

    def probe(self, request: TPURequest, rater: Rater) -> Optional[Option]:
        """Fresh placement search against CURRENT state — no per-request
        cache read or write.  The capacity index's class-representative
        probe: its result is memoized by (shape, plan_key) and must be a
        pure function of the node's state, which the assume() cache (keyed
        by pod, possibly stale across state changes) is not."""
        with self.lock:
            return self.chips.trade(request, rater)

    def forget(self, option: Option) -> None:
        """Free a committed allocation (reference: node.go:129-140)."""
        with self.lock:
            self.chips.cancel(option)
            self._notify_change()

    def add(self, option: Option) -> None:
        """Learn an externally-committed allocation (restart rebuild or a bind
        by another replica; reference: node.go:148-160)."""
        with self.lock:
            self.chips.transact(option)
            self._notify_change()

    def drop_assumed(self, request_hash: str) -> None:
        """Evict a cached (not committed) option — e.g. gang rollback."""
        with self.lock:
            self.allocated.pop(request_hash, None)
            self._allocated_at.pop(request_hash, None)

    def refresh_from_node(self, node) -> None:
        """Re-derive capacity if the node's allocatable changed (the reference
        never does this; SURVEY §5 'node allocator cached forever')."""
        with self.lock:
            labels = node.metadata.labels or {}
            self.generation = labels.get(
                consts.LABEL_TPU_ACCELERATOR, self.generation
            )
            topo, chips = chips_from_node(node)
            same_shape = topo.dims == self.chips.topo.dims and set(
                c.coord for c in chips
            ) == set(self.chips.chips)
            if not same_shape:
                self.chips = ChipSet(topo, chips)
                self.allocated.clear()
                self._allocated_at.clear()
                self._notify_change()
                if self.JOURNAL.enabled:
                    # reset=True: the rebuild WIPED chip usage (unlike the
                    # same-shape branch below, which preserves it) — replay
                    # must not re-charge live pods onto the fresh set
                    self.JOURNAL.record(
                        "node_resync", node=self.node_name, reset=True,
                        generation=self.generation,
                        **self.chips.inventory(),
                    )
                return
            # Same chip layout: apply per-chip total changes (e.g. HBM resize)
            # while preserving live usage.
            changed = False
            for fresh in chips:
                live = self.chips.chips[fresh.coord]
                if fresh.hbm_total != live.hbm_total:
                    used = live.hbm_total - live.hbm_avail
                    live.hbm_total = fresh.hbm_total
                    live.hbm_avail = max(0, fresh.hbm_total - used)
                    changed = True
                if fresh.core_total != live.core_total:
                    used = live.core_total - live.core_avail
                    live.core_total = fresh.core_total
                    live.core_avail = max(0, fresh.core_total - used)
                    changed = True
            if changed:
                self._notify_change()
            if changed and self.JOURNAL.enabled:
                self.JOURNAL.record(
                    "node_resync", node=self.node_name,
                    generation=self.generation,
                    **self.chips.inventory(),
                )

    def status(self) -> dict:
        with self.lock:
            s = self.chips.status()
            s["node"] = self.node_name
            s["pending_options"] = len(self.allocated)
            return s
