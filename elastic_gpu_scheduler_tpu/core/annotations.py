"""Allocation ↔ pod-annotation codec: the durable ledger.

The reference persists every allocation as pod annotations and rebuilds all
in-memory state from them on restart (reference: pkg/scheduler/pod.go:57-78
writes; pkg/scheduler/allocate.go:75-93 reads back).  Same design here, with
mesh *coordinates* on the wire instead of flat card indices:

    elasticgpu.io/assumed: "true"              (annotation AND label)
    elasticgpu.io/node: <node name>
    elasticgpu.io/container-<name>: "0.0.0,0.1.0"   (chip coords, row-major)
    elasticgpu.io/allocated-topology: "2x1x1"       (bounding box, informational)

Amounts (whole vs fractional, core units, HBM) are NOT in the annotations —
they are recovered from the pod's own resource requests, exactly as the
reference does, so the pod spec + annotations together are the full record.
"""

from __future__ import annotations

from typing import Optional

from ..utils import consts
from .allocator import ContainerAlloc, Option
from .request import TPURequest, request_from_pod
from .topology import Topology, bounding_box, format_coord, format_topology, is_contiguous, parse_coord


def annotations_for_option(option: Option, node_name: str) -> dict[str, str]:
    ann = {
        consts.ANNOTATION_ASSUMED: "true",
        consts.ANNOTATION_NODE: node_name,
    }
    all_coords = []
    for a in option.allocs:
        if not a.needs_tpu:
            continue
        ann[consts.ANNOTATION_CONTAINER_PREFIX + a.container] = ",".join(
            format_coord(c) for c in a.coords
        )
        all_coords.extend(a.coords)
    if all_coords:
        ann[consts.ANNOTATION_TOPOLOGY] = format_topology(bounding_box(all_coords))
    return ann


def labels_for_option() -> dict[str, str]:
    return {consts.ANNOTATION_ASSUMED: "true"}


def is_assumed(pod) -> bool:
    """Reference: pkg/scheduler/pod.go:80-82."""
    ann = pod.metadata.annotations or {}
    return ann.get(consts.ANNOTATION_ASSUMED) == "true"


def workload_class(pod) -> str:
    """The pod's profiling class (``elasticgpu.io/workload-class``
    annotation; profile/ aggregates measured behavior under it).  Pods
    without the annotation share the default class."""
    ann = pod.metadata.annotations or {}
    return (
        ann.get(consts.ANNOTATION_WORKLOAD_CLASS)
        or consts.DEFAULT_WORKLOAD_CLASS
    )


def assigned_node(pod) -> Optional[str]:
    ann = pod.metadata.annotations or {}
    return ann.get(consts.ANNOTATION_NODE) or (pod.spec.node_name or None)


def option_from_pod(pod, topo: Topology) -> Optional[Option]:
    """Reconstruct the committed Option from a bound pod's annotations —
    the restart-recovery path (reference: allocate.go:75-93).

    Returns None if the pod has no TPU allocation annotations.
    """
    ann = pod.metadata.annotations or {}
    request = request_from_pod(pod)
    allocs: list[ContainerAlloc] = []
    found = False
    for name, unit in zip(request.container_names, request.units):
        key = consts.ANNOTATION_CONTAINER_PREFIX + name
        raw = ann.get(key)
        if raw is None or not unit.needs_tpu:
            allocs.append(ContainerAlloc(container=name, coords=(), whole=False))
            continue
        found = True
        coords = tuple(parse_coord(p) for p in raw.split(",") if p)
        if unit.wants_whole_chips:
            allocs.append(
                ContainerAlloc(
                    container=name,
                    coords=coords,
                    whole=True,
                    contiguous=is_contiguous(coords, topo),
                )
            )
        else:
            allocs.append(
                ContainerAlloc(
                    container=name,
                    coords=coords,
                    whole=False,
                    core=max(unit.core, 0),
                    hbm=unit.hbm,
                )
            )
    if not found:
        return None
    return Option(request_hash=request.hash(), allocs=tuple(allocs))
