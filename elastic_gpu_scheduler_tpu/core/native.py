"""Lazy loader/builder for the native placement extension.

Builds native/placement.cc into a CPython extension on first use (g++ is in
the image; pybind11/grpcio-tools are not, so the module uses the raw CPython
API and is compiled with a single g++ invocation).  Every caller must treat
``get_placement() is None`` as "use the Python fallback" — results of the two
paths are bit-identical (tests/test_native.py asserts it).

Two kernels: ``enumerate_free_boxes`` (contiguous sub-box candidates for one
container) and ``plan_gang`` (whole-gang greedy placement over per-node free
sets — the 1024-member hot loop).  A rebuilt source gains functions lazily:
callers probe with ``hasattr(mod, "plan_gang")`` so a stale in-process module
degrades to the Python fallback instead of crashing.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sysconfig
import threading

log = logging.getLogger("tpu-scheduler")

_lock = threading.Lock()
_loaded = False
_module = None


def _build_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(__file__)), "_native_build")


def _source_path() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    return os.path.join(repo, "native", "placement.cc")


def build(force: bool = False) -> str | None:
    """Compile the extension; returns the .so path or None on failure."""
    src = _source_path()
    if not os.path.exists(src):
        return None
    out_dir = _build_dir()
    os.makedirs(out_dir, exist_ok=True)
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so = os.path.join(out_dir, f"_placement{suffix}")
    if (
        not force
        and os.path.exists(so)
        and os.path.getmtime(so) >= os.path.getmtime(src)
    ):
        return so
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", src, "-o", so,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return so
    except Exception as e:  # missing toolchain, etc. → Python fallback
        log.debug("native placement build failed: %s", e)
        return None


def get_placement():
    """The _placement module, or None if unavailable."""
    global _loaded, _module
    if _loaded:
        return _module
    with _lock:
        if _loaded:
            return _module
        try:
            so = build()
            if so is not None:
                import importlib.util

                spec = importlib.util.spec_from_file_location("_placement", so)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                _module = mod
                log.info("native placement search loaded (%s)", so)
        except Exception as e:  # pragma: no cover
            log.debug("native placement unavailable: %s", e)
            _module = None
        _loaded = True
        return _module
