"""Lazy loader/builder for the native placement extension.

Builds native/placement.cc into a CPython extension on first use (g++ is in
the image; pybind11/grpcio-tools are not, so the module uses the raw CPython
API and is compiled with a single g++ invocation).  Every caller must treat
``get_placement() is None`` as "use the Python fallback" — results of the two
paths are bit-identical (tests/test_native.py asserts it).

Two kernels: ``enumerate_free_boxes`` (contiguous sub-box candidates for one
container) and ``plan_gang`` (whole-gang greedy placement over per-node free
sets — the 1024-member hot loop).  A rebuilt source gains functions lazily:
callers probe with ``hasattr(mod, "plan_gang")`` so a stale in-process module
degrades to the Python fallback instead of crashing.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sysconfig
import threading

log = logging.getLogger("tpu-scheduler")

_lock = threading.Lock()
_loaded = False
_module = None


def _build_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(__file__)), "_native_build")


def _source_path() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    return os.path.join(repo, "native", "placement.cc")


def _compile(so_name: str, extra_flags: list, force: bool, timeout: float) -> str | None:
    """Shared compile path for the production and sanitized variants —
    ONE place owns the mtime-freshness check, suffix/include discovery,
    and failure handling, so a fix to either never desynchronizes the
    CI sanitizer build from the production one."""
    src = _source_path()
    if not os.path.exists(src):
        return None
    out_dir = _build_dir()
    os.makedirs(out_dir, exist_ok=True)
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so = os.path.join(out_dir, f"{so_name}{suffix}")
    if (
        not force
        and os.path.exists(so)
        and os.path.getmtime(so) >= os.path.getmtime(src)
    ):
        return so
    include = sysconfig.get_paths()["include"]
    # compile to a per-pid temp path and rename into place: the warm
    # thread makes every stack-building process race this build on a
    # fresh checkout, and a sibling dlopening a partially-written .so
    # would pin itself to the Python fallback for its whole lifetime.
    # rename is atomic on the same filesystem (the compilecache
    # subsystem's entry-write discipline, applied here)
    tmp = os.path.join(out_dir, f".{so_name}.{os.getpid()}.tmp{suffix}")
    cmd = [
        "g++", *extra_flags, "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", src, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=timeout)
        os.replace(tmp, so)
        return so
    except Exception as e:  # missing toolchain, etc. → Python fallback
        log.debug("native placement build failed (%s): %s", so_name, e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def build(force: bool = False) -> str | None:
    """Compile the extension; returns the .so path or None on failure."""
    return _compile("_placement", ["-O2"], force, timeout=120)


def build_sanitized(force: bool = False) -> str | None:
    """Compile placement.cc with ASan+UBSan into a SEPARATE extension
    (``_placement_san``).  The differential fuzz gate
    (tools/check_native_san.py, ``make check-native-san``) loads it in a
    child process with libasan LD_PRELOADed and hammers
    plan_gang/plan_gang_batch against the Python fallback — memory
    errors and UB abort the child, parity breaks fail the diff.  Never
    loaded by the scheduler itself."""
    return _compile(
        "_placement_san",
        ["-O1", "-g", "-fsanitize=address,undefined",
         "-fno-sanitize-recover=all", "-fno-omit-frame-pointer"],
        force, timeout=240,
    )


def sanitizer_preload() -> str | None:
    """Path to libasan.so for LD_PRELOAD (ASan must be the first loaded
    runtime when the instrumented code lives in a dlopen()ed extension)."""
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=libasan.so"],
            capture_output=True, timeout=30, check=True,
        ).stdout.decode().strip()
    except Exception:
        return None
    return out if out and os.path.sep in out and os.path.exists(out) else None


def get_placement():
    """The _placement module, or None if unavailable."""
    global _loaded, _module
    if _loaded:
        return _module
    with _lock:
        if _loaded:
            return _module
        try:
            so = build()
            if so is not None:
                import importlib.util

                spec = importlib.util.spec_from_file_location("_placement", so)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                _module = mod
                log.info("native placement search loaded (%s)", so)
        except Exception as e:  # pragma: no cover
            log.debug("native placement unavailable: %s", e)
            _module = None
        _loaded = True
        return _module
