"""Protocol constants: resource names, annotation/label keys, policies.

TPU retarget of the reference's protocol strings (reference:
pkg/utils/types.go:3-17).  The ``elasticgpu.io`` prefix is kept so existing
tooling conventions carry over; the resources become TPU-shaped.
"""

# Extended resource names (pod spec `resources.limits` / node `allocatable`).
RESOURCE_TPU_CORE = "elasticgpu.io/tpu-chip"  # 100 units = 1 physical chip
RESOURCE_TPU_HBM = "elasticgpu.io/tpu-hbm"  # GiB
# Unimplemented-in-reference analogues kept for request recognition parity
# (reference recognizes qgpu/pgpu names it never schedules, pkg/scheduler/pod.go:27-34).
RESOURCE_TPU_CORE_ALIASES = (RESOURCE_TPU_CORE, "elasticgpu.io/tpu-core")
RESOURCE_TPU_HBM_ALIASES = (RESOURCE_TPU_HBM, "elasticgpu.io/tpu-memory")

CORE_PER_CHIP = 100

# Annotation / label keys — the durable allocation ledger lives on the pod
# (reference: pkg/utils/types.go:8-10, pkg/scheduler/pod.go:57-78).
ANNOTATION_ASSUMED = "elasticgpu.io/assumed"  # "true" once scheduled (label too)
ANNOTATION_CONTAINER_PREFIX = "elasticgpu.io/container-"  # + name → "x.y.z,x.y.z"
ANNOTATION_NODE = "elasticgpu.io/node"  # node the allocation belongs to
ANNOTATION_TOPOLOGY = "elasticgpu.io/allocated-topology"  # box shape, e.g. "2x2"

# Gang scheduling (net-new vs reference).
ANNOTATION_GANG_NAME = "elasticgpu.io/gang-name"
ANNOTATION_GANG_SIZE = "elasticgpu.io/gang-size"  # min members for all-or-nothing
# DCN boundary (written only when a gang STRADDLES slices — last-resort
# placement): the member's own slice, and the gang's ordered slice list.
# The launcher builds a hierarchical mesh from these (outer DCN data axis
# × inner ICI axes, parallel/mesh.py hierarchical_mesh).
ANNOTATION_SLICE = "elasticgpu.io/slice"
ANNOTATION_GANG_SLICES = "elasticgpu.io/gang-slices"  # "sliceA,sliceB,..."
# Multi-host SPMD gang identity (written at gang commit for EVERY gang):
# the member's deterministic rank in the gang's sorted member order, and
# the ordered peer list ("ns/name,ns/name,...").  parallel/mesh.py's
# gang_mesh derives jax.distributed process ids from the rank and the
# coordinator host from peer 0, turning a multi-node gang into ONE
# cross-host jax.sharding.Mesh.
ANNOTATION_GANG_RANK = "elasticgpu.io/gang-rank"
ANNOTATION_GANG_PEERS = "elasticgpu.io/gang-peers"

# Scheduling-trace propagation (tracing/__init__.py): written with the
# bind-time allocation ledger so the on-node side (device plugin, launcher)
# can continue the pod's scheduling trace.  W3C traceparent format.
ANNOTATION_TRACEPARENT = "elasticgpu.io/traceparent"

# Workload profiling (profile/): the class key under which this pod's
# measured behavior (throughput, latency, interference) aggregates.
# Pods without the annotation profile under DEFAULT_WORKLOAD_CLASS.
ANNOTATION_WORKLOAD_CLASS = "elasticgpu.io/workload-class"
DEFAULT_WORKLOAD_CLASS = "default"

# Node labels describing TPU topology (mirrors GKE's
# cloud.google.com/gke-tpu-topology convention).
LABEL_TPU_ACCELERATOR = "elasticgpu.io/tpu-accelerator"  # v4|v5e|v5p|v6e
LABEL_TPU_TOPOLOGY = "elasticgpu.io/tpu-topology"  # slice topology "4x4x8"
LABEL_TPU_SLICE = "elasticgpu.io/tpu-slice"  # slice id this host belongs to
LABEL_TPU_HOST_TOPOLOGY = "elasticgpu.io/tpu-host-topology"  # host-local box "2x2x1"
LABEL_TPU_HOST_OFFSET = "elasticgpu.io/tpu-host-offset"  # host origin in slice "0.0.4"

# Placement policies.
PRIORITY_BINPACK = "binpack"
PRIORITY_SPREAD = "spread"
PRIORITY_RANDOM = "random"
PRIORITY_ICI = "ici-locality"

# The apiserver optimistic-concurrency conflict is matched *structurally*
# (HTTP 409 / reason Conflict), not by error-string compare as the reference
# does (reference: pkg/utils/types.go:15, pkg/scheduler/scheduler.go:201).
CONFLICT_REASON = "Conflict"

SCORE_MIN = 0
SCORE_MAX = 10  # extender priority range; raters normalize into it
