"""Shared jittered-exponential backoff / deadline utility.

Before this module every retry loop in the tree rolled its own policy:
the REST watch reconnected on a FIXED delay (a fleet-wide apiserver flap
re-connects every watcher in lockstep), the router's circuit breaker
re-probed exactly ``cooldown_s`` after opening (all breakers opened by
one outage close in the same instant — the synchronized-retry-storm
failure mode), and the autoscaler executor's drain wait busy-polled at a
constant 20ms.  One policy object now covers all of them:

- **Exponential with full-ish jitter.**  Attempt ``n`` sleeps a uniform
  draw from ``[d*(1-jitter), d]`` where ``d = min(max_s, base_s *
  factor**n)`` — the AWS "equal jitter" family: retries spread over a
  window that doubles per failure, so a thousand clients knocked over by
  one event come back as a smear, not a thundering herd.
- **Deadline.**  An optional wall budget; ``sleep()`` returns False once
  the budget is exhausted instead of sleeping past it, so callers write
  ``while backoff.sleep(): retry()`` and get bounded total latency.
- **Deterministic under test.**  The RNG is injectable; the fault plane's
  chaos soak seeds it so failure schedules replay exactly.

``Retry-After`` interop: HTTP 503s from a leaderless scheduler carry a
``Retry-After`` header; ``next_delay(floor_s=...)`` lets the caller
respect the server's floor while keeping the jittered growth above it.
"""

from __future__ import annotations

import random
import time
from typing import Optional

__all__ = ["Backoff", "retry_call"]


class Backoff:
    """Jittered exponential backoff with an optional wall deadline.

    Not thread-safe: each retry loop owns its instance (a shared
    instance would interleave attempt counters across loops, which is
    never what a caller means)."""

    def __init__(
        self,
        base_s: float = 0.1,
        factor: float = 2.0,
        max_s: float = 30.0,
        jitter: float = 0.5,
        deadline_s: Optional[float] = None,
        rng: Optional[random.Random] = None,
    ):
        self.base_s = max(0.0, float(base_s))
        self.factor = max(1.0, float(factor))
        self.max_s = max(self.base_s, float(max_s))
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        self.deadline_s = deadline_s
        self._rng = rng if rng is not None else random
        self.attempts = 0
        self._deadline_mono = (
            time.monotonic() + deadline_s if deadline_s is not None else None
        )

    def reset(self) -> None:
        """Back to attempt 0 (a success ends the failure run); the
        deadline — a budget for ONE operation, not per try — restarts."""
        self.attempts = 0
        if self.deadline_s is not None:
            self._deadline_mono = time.monotonic() + self.deadline_s

    def expired(self) -> bool:
        return (
            self._deadline_mono is not None
            and time.monotonic() >= self._deadline_mono
        )

    def next_delay(self, floor_s: float = 0.0) -> float:
        """The next jittered delay (advances the attempt counter).
        ``floor_s``: a server-imposed minimum (HTTP Retry-After) the
        jitter must not dip below."""
        d = min(self.max_s, self.base_s * (self.factor ** self.attempts))
        self.attempts += 1
        d = d * (1.0 - self.jitter * self._rng.random())
        return max(d, min(floor_s, self.max_s))

    def sleep(self, floor_s: float = 0.0) -> bool:
        """Sleep the next delay, clamped to the remaining deadline.
        Returns False — WITHOUT sleeping the full delay — when the
        deadline is exhausted, so retry loops terminate on time."""
        d = self.next_delay(floor_s=floor_s)
        if self._deadline_mono is not None:
            remaining = self._deadline_mono - time.monotonic()
            if remaining <= 0:
                return False
            d = min(d, remaining)
        if d > 0:
            time.sleep(d)
        return not self.expired()


def retry_call(
    fn,
    *,
    attempts: int = 5,
    retry_on: tuple = (OSError,),
    backoff: Optional[Backoff] = None,
    on_error=None,
):
    """Call ``fn()`` with up to ``attempts`` tries under ``backoff``.
    The LAST failure re-raises (a retry wrapper must never convert an
    error into silence); ``on_error(exc, attempt)`` observes each
    intermediate failure (logging/metrics)."""
    bo = backoff if backoff is not None else Backoff()
    last: Optional[BaseException] = None
    for i in range(max(1, attempts)):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by definition
            last = e
            if on_error is not None:
                try:
                    on_error(e, i)
                except Exception:
                    pass
            if i == attempts - 1 or not bo.sleep():
                raise
    raise last  # pragma: no cover — unreachable (loop raises)
