"""Live TPU-relay health signal: the ``tpu_relay_up`` gauge.

The bench/validation tooling has probed the TPU relay since BENCH_r02
(and fail-fasts when it is down), but *live* ``/metrics`` carried no
signal an operator could alert on — a down relay was only discoverable
by running the bench.  :class:`RelayMonitor` closes that gap: a daemon
thread probes the relay on a slow interval (subprocess ``jax.devices()``
with a hard timeout — a downed relay hangs an in-process probe forever,
same reason bench.probe_tpu subprocesses) and publishes:

    tpu_relay_up  1 = the last probe reached a TPU backend
                  0 = probe failed / timed out / non-TPU backend

``GET /debug/relay`` (scheduler server) serves the full state: last
probe time, latency, and the failure detail.  The monitor is OFF by
default (zero scrape cost, zero subprocesses in tests); the scheduler
CLI starts it via ``--relay-probe-interval`` and operators can alert on
``tpu_relay_up == 0``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from ..metrics import REGISTRY, Gauge

__all__ = ["RELAY_UP", "RELAY_MONITOR", "RelayMonitor", "probe_relay"]

RELAY_UP = REGISTRY.register(
    Gauge(
        "tpu_relay_up",
        "TPU probe-relay reachability from this process: 1 = the last "
        "periodic probe reached a TPU backend, 0 = it failed or timed "
        "out (bench on-chip sections will fail-fast; alert on 0).  "
        "Absent until a RelayMonitor runs (--relay-probe-interval)",
    )
)


def probe_relay(timeout: float = 20.0) -> tuple[bool, str]:
    """(up, detail): probe the TPU relay in a SUBPROCESS — a downed relay
    makes in-process ``jax.devices()`` hang indefinitely, so the timeout
    must bound a child we can kill.  ``detail`` is the chip kind when
    up, the failure reason otherwise."""
    try:
        p = subprocess.run(
            [
                sys.executable, "-c",
                "import jax; d = jax.devices(); "
                "assert jax.default_backend() == 'tpu', "
                "'NOT_TPU:' + jax.default_backend(); "
                "print(d[0].device_kind)",
            ],
            timeout=timeout, capture_output=True,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout:.0f}s (relay down?)"
    except OSError as e:
        return False, f"probe spawn failed: {e}"
    if p.returncode == 0:
        return True, p.stdout.decode().strip()
    return False, p.stderr.decode(errors="replace")[-200:]


class RelayMonitor:
    """Background relay prober feeding ``tpu_relay_up``.

    Probes on its OWN daemon thread at ``interval_s`` — never on the
    scrape path (a scrape-time probe would add seconds to /metrics and
    fan out one jax subprocess per scraper).  ``probe`` is injectable
    for tests."""

    def __init__(
        self,
        interval_s: float = 300.0,
        timeout_s: Optional[float] = None,
        probe: Callable[[float], tuple[bool, str]] = probe_relay,
    ):
        self.interval_s = max(5.0, float(interval_s))
        self.timeout_s = (
            float(timeout_s)
            if timeout_s is not None
            else float(os.environ.get("TPU_RELAY_PROBE_TIMEOUT", "20"))
        )
        self.probe = probe
        self.up: Optional[bool] = None  # None = never probed
        self.detail = ""
        self.probes = 0
        self.last_probe_at = 0.0  # time.time of the last completed probe
        self.last_probe_ms = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def probe_once(self) -> bool:
        t0 = time.perf_counter()
        up, detail = self.probe(self.timeout_s)
        self.last_probe_ms = round((time.perf_counter() - t0) * 1e3, 1)
        self.up, self.detail = up, detail
        self.probes += 1
        self.last_probe_at = time.time()
        RELAY_UP.set(value=1.0 if up else 0.0)
        return up

    def start(self) -> "RelayMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.probe_once()
                except Exception:  # the monitor must outlive any probe bug
                    RELAY_UP.set(value=0.0)
                if self._stop.wait(self.interval_s):
                    return

        self._thread = threading.Thread(
            target=loop, name="tpu-relay-probe", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def debug_state(self) -> dict:
        """The /debug/relay payload."""
        return {
            "running": self._thread is not None,
            "up": self.up,
            "detail": self.detail,
            "probes": self.probes,
            "interval_s": self.interval_s,
            "timeout_s": self.timeout_s,
            "last_probe_at": round(self.last_probe_at, 3),
            "last_probe_ms": self.last_probe_ms,
        }


# Process-global instance (same pattern as TRACER/JOURNAL/PROFILER): the
# CLI starts it; /debug/relay reads it whether or not it ever ran.
RELAY_MONITOR = RelayMonitor()
