"""Rolling BLAKE2b prefix-digest chain — the prefix-cache content address.

One definition, two consumers that MUST agree byte-for-byte:

- the serving engine's automatic prefix cache (models/serving.py) keys
  cached K/V pages by this chain (PR 4 replaced the nested-tuple hash
  with it), and
- the fleet front-door router (fleet/router.py) computes the same chain
  over an incoming prompt to find the replica whose cache already holds
  the longest matching prefix.

The router lives in the scheduler plane (smoke tier — it must never
import jax or numpy), while the engine hashes numpy int32 page slices;
both reduce to the same raw little-int32 native byte layout, so
``page_digests`` here and ``_match_prefix``/``_record_prefix`` in the
engine produce identical digests for identical (adapter, token-prefix)
pairs.  That identity is what makes router affinity an actual cache hit
rather than a heuristic.
"""

from __future__ import annotations

import hashlib
from array import array
from typing import Iterable

__all__ = ["prefix_seed", "prefix_page_key", "page_digests"]


def prefix_seed(adapter_id: int) -> bytes:
    """Chain seed: K/V content depends on the adapter (wk/wv deltas), so
    pages cached under one adapter must never match another's prompts."""
    return b"lora:" + int(adapter_id).to_bytes(4, "little")


def prefix_page_key(prev: bytes, toks_bytes: bytes) -> bytes:
    """One link of the chain: a 16-byte BLAKE2b digest over (previous
    link, this page's raw int32 token bytes).  128-bit digests make
    accidental collisions (which would alias cached K/V — or misroute a
    session) negligible."""
    return hashlib.blake2b(prev + toks_bytes, digest_size=16).digest()


def token_bytes(tokens: Iterable[int]) -> bytes:
    """Native int32 byte layout — identical to ``np.int32 row.tobytes()``
    on the engine side (both are the platform's native 32-bit ints)."""
    return array("i", tokens).tobytes()


def page_digests(
    tokens, page_size: int, adapter_id: int = 0, max_pages: int = 0,
    seed: bytes = b"",
) -> list[bytes]:
    """The digest chain for a token sequence: one digest per FULL page
    (partial trailing pages are never cacheable, so they get no digest —
    same rule as the engine's ``_record_prefix`` plen-1 cap caller).
    ``max_pages`` > 0 bounds the work for very long prompts (the router
    needs only enough links to discriminate replicas).  ``seed``
    overrides the adapter-id seed — the router keys by adapter NAME
    (it never sees bank indices); only equality semantics matter on its
    side of the chain."""
    ps = int(page_size)
    if ps <= 0:
        return []
    toks = list(tokens)
    n_pages = len(toks) // ps
    if max_pages > 0:
        n_pages = min(n_pages, max_pages)
    key = seed or prefix_seed(adapter_id)
    out: list[bytes] = []
    for j in range(n_pages):
        key = prefix_page_key(key, token_bytes(toks[j * ps:(j + 1) * ps]))
        out.append(key)
    return out
