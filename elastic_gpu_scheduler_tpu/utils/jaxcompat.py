"""Version-portable JAX API shims.

``jax.experimental.shard_map`` is deprecated as of JAX v0.8.0 in favor of
top-level ``jax.shard_map``, whose ``check_rep`` flag was also renamed to
``check_vma``.  The repo pins its JAX, but pins get bumped — and older
pins (0.4.x) predate ``jax.shard_map`` entirely.  Every call site goes
through this one shim so a pin bump in either direction is a no-op:

- prefer ``jax.shard_map`` when the installed JAX has it (non-deprecated
  path, no DeprecationWarning in the suite);
- translate ``check_rep`` → ``check_vma`` when the new API renamed it;
- fall back to ``jax.experimental.shard_map.shard_map`` on old pins.

Import this module only from JAX-plane code (models/ops/parallel); the
scheduler plane must stay importable without JAX installed.
"""

from __future__ import annotations

import inspect

import jax

_API = getattr(jax, "shard_map", None)
if _API is not None:
    _PARAMS = frozenset(inspect.signature(_API).parameters)
else:  # pre-0.6 pin: the experimental module is the only spelling
    from jax.experimental.shard_map import shard_map as _API  # noqa: N813

    _PARAMS = frozenset(inspect.signature(_API).parameters)


def shard_map(f, mesh, in_specs, out_specs, check_rep: bool = True,
              axis_names=None):
    """``shard_map`` across JAX versions.

    - replication checking is passed under whichever name the installed
      API uses (``check_vma`` / ``check_rep``);
    - ``axis_names`` (partial-manual: manual ONLY over these axes) maps
      to the old API's complementary ``auto`` set on pins that predate
      the rename.
    """
    kw = {}
    if "check_vma" in _PARAMS:
        kw["check_vma"] = check_rep
    elif "check_rep" in _PARAMS:
        kw["check_rep"] = check_rep
    if axis_names is not None:
        if "axis_names" in _PARAMS:
            kw["axis_names"] = set(axis_names)
        elif "auto" in _PARAMS:
            # old spelling: list the axes the body does NOT shard over
            kw["auto"] = frozenset(mesh.axis_names) - set(axis_names)
        else:
            # axis_names carries SEMANTICS (partial-manual); silently
            # dropping it would compile the body fully-manual over every
            # mesh axis and corrupt collectives far from the cause
            raise RuntimeError(
                "installed jax.shard_map supports neither axis_names nor "
                "auto; cannot express partial-manual semantics"
            )
    return _API(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def pcast(x, axis_name, to: str = "varying"):
    """``lax.pcast`` where the installed JAX has varying-axis types
    (the VMA system that came with ``check_vma``); identity on pins
    that predate it — pcast only adjusts the type-level variance
    annotation, never the value, and pre-VMA JAX has no annotation to
    adjust."""
    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axis_name, to=to)
