"""KV-page wire format: replica-to-replica shipping of paged K/V cache
content (the disaggregated serving data plane's byte-level contract).

One bundle carries an ordered run of FULL pages — each page is the raw
token ids it covers plus the engine's serialized K/V payload for those
positions — framed with the journal's durability conventions:
length-prefixed records, a CRC32 per record, and a 16-byte BLAKE2b
digest-chain link per page (utils/prefixdigest — the SAME chain the
engine's prefix cache and the fleet router key by).  Three consumers:

- ``/v1/kv/export`` / ``/v1/kv/adopt`` — a replica pulls another
  replica's cached prefix pages instead of re-prefilling (the fleet
  prefix-cache index's "move the KV, not the request" path);
- ``/v1/migrate/out`` → ``/v1/migrate/in`` — live session migration: a
  ``kind="session"`` bundle adds the full request state (prompt, output
  so far, sampling params, seed) so the destination resumes
  token-identically;
- the prefill/decode split — a prefill-role replica exports the pages
  its chunked prefill produced and a decode-role replica imports them
  before running the token loop.

This module is deliberately jax/numpy-free (the router — scheduler
plane, smoke tier — must parse headers without the model stack);
payload bytes are opaque here.  The engine owns producing/consuming
them (models/serving.py ``export_prefix_pages``/``import_pages``) and
guards geometry compatibility via the header fields.

Integrity model: the receiver re-derives the digest chain from the
SHIPPED token bytes and the header's seed — a flipped token byte, a
reordered page or a truncated run fails loudly before any K/V lands in
a pool.  Payload corruption is caught by the per-page CRC32.  (Same
trust stance as the journal reader: bytes are only believed after the
frame checks pass.)
"""

from __future__ import annotations

import json
import struct
import zlib

from . import prefixdigest

__all__ = [
    "KV_SOURCE_HEADER", "MAGIC", "WireError",
    "decode_bundle", "encode_bundle",
]

MAGIC = b"TPUKV1\n"
# router → backend HTTP header naming the replica to pull this prompt's
# prefix pages from before admission (the adoption path); defined here
# so the jax-free router and the serving HTTP layer share one spelling
KV_SOURCE_HEADER = "X-KV-Source"
_U32 = struct.Struct("<I")


class WireError(ValueError):
    """A malformed / corrupt / truncated KV bundle.  Always safe to
    surface as a 400 — nothing was imported when this raises."""


def _u32(data: bytes, off: int) -> tuple[int, int]:
    if off + 4 > len(data):
        raise WireError("truncated bundle (length field)")
    return _U32.unpack_from(data, off)[0], off + 4


def encode_bundle(
    header: dict, pages: list[tuple[list, bytes]], seed: bytes
) -> bytes:
    """Frame ``pages`` ([(token_ids, payload_bytes), ...], chain order)
    under ``header`` (JSON-serializable geometry + request metadata).
    ``seed`` roots the digest chain; it ships in the header (hex) so the
    receiver verifies the SAME chain — registration keys are re-derived
    receiver-side with the receiver's own adapter seed, so the wire seed
    only needs equality semantics, like the router's."""
    hdr = dict(header)
    hdr["v"] = 1
    hdr["pages"] = len(pages)
    hdr["seed"] = seed.hex()
    hjson = json.dumps(hdr, sort_keys=True).encode()
    out = [MAGIC, _U32.pack(len(hjson)), hjson,
           _U32.pack(zlib.crc32(hjson))]
    key = seed
    for toks, payload in pages:
        tb = prefixdigest.token_bytes(toks)
        key = prefixdigest.prefix_page_key(key, tb)
        out.append(_U32.pack(len(tb)))
        out.append(tb)
        out.append(key)  # 16-byte chain link
        out.append(_U32.pack(len(payload)))
        out.append(payload)
        out.append(_U32.pack(zlib.crc32(tb + key + payload)))
    return b"".join(out)


def decode_bundle(data: bytes) -> tuple[dict, list[tuple[list, bytes]]]:
    """→ (header, [(token_ids, payload_bytes), ...]) after verifying the
    magic, every CRC, and the digest chain.  Raises WireError on ANY
    integrity failure — partial results are never returned."""
    if not data.startswith(MAGIC):
        raise WireError("bad magic (not a KV bundle)")
    off = len(MAGIC)
    hlen, off = _u32(data, off)
    if off + hlen + 4 > len(data):
        raise WireError("truncated bundle (header)")
    hjson = data[off:off + hlen]
    off += hlen
    hcrc, off = _u32(data, off)
    if zlib.crc32(hjson) != hcrc:
        raise WireError("header CRC mismatch")
    try:
        header = json.loads(hjson)
    except ValueError as e:
        raise WireError(f"header not JSON: {e}") from None
    if header.get("v") != 1:
        raise WireError(f"unsupported bundle version {header.get('v')!r}")
    try:
        key = bytes.fromhex(header.get("seed", ""))
    except ValueError:
        raise WireError("malformed chain seed") from None
    n_pages = int(header.get("pages", 0))
    pages: list[tuple[list, bytes]] = []
    for j in range(n_pages):
        tlen, off = _u32(data, off)
        if off + tlen + 16 > len(data):
            raise WireError(f"truncated bundle (page {j} tokens)")
        tb = data[off:off + tlen]
        off += tlen
        link = data[off:off + 16]
        off += 16
        plen, off = _u32(data, off)
        if off + plen + 4 > len(data):
            raise WireError(f"truncated bundle (page {j} payload)")
        payload = data[off:off + plen]
        off += plen
        crc, off = _u32(data, off)
        if zlib.crc32(tb + link + payload) != crc:
            raise WireError(f"page {j} CRC mismatch")
        key = prefixdigest.prefix_page_key(key, tb)
        if key != link:
            raise WireError(
                f"page {j} digest-chain break (corrupt or reordered)"
            )
        if tlen % 4:
            raise WireError(f"page {j} token bytes not int32-aligned")
        toks = list(struct.unpack(f"<{tlen // 4}i", tb))
        pages.append((toks, payload))
    if off != len(data):
        raise WireError(f"{len(data) - off} trailing bytes after last page")
    return header, pages
