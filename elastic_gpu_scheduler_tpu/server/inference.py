"""HTTP front end for the serving engine: completions over the wire.

The engine (models/serving.py) is a library; this module gives it the
network surface a framework user expects:

    POST /v1/completions   → {"prompt": [ids], "max_tokens": N, ...}
                             blocking JSON response, or Server-Sent-Events
                             streaming with {"stream": true}
    GET  /v1/stats         → engine state (slots, pages, prefix hits,
                             registered adapters)
    GET  /healthz          → liveness
    GET  /version          → build version (scheduler-plane parity)

Design notes (mirrors server/routes.py conventions — stdlib HTTP only):

- ONE engine thread (``EngineLoop``) owns all engine state and drives
  fused chunks continuously; HTTP handler threads only enqueue requests
  (``InferenceEngine.submit`` is thread-safe) and wait on per-request
  events/queues — the TPU never blocks on a slow client.
- Streaming uses the engine's ``on_token`` callback to feed a bounded
  per-connection queue; the handler thread drains it into SSE lines
  (``data: {"token": t}``, terminated by ``data: [DONE]``).  A slow or
  dead client only ever stalls its own handler thread.
- The API is TOKEN-level ({"prompt": [ids]}) — the framework is
  tokenizer-agnostic (HF tokenizers plug in client-side), same stance as
  the rest of models/.

The reference has no serving plane at all (SURVEY §2 #19); this completes
the inference story the workload plane opened.
"""

from __future__ import annotations

import http.client
import json
import logging
import math
import queue
import select
import socket as socket_mod
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .. import __version__
from ..faultinject import FAULTS
from ..metrics import (
    KV_MIGRATIONS,
    KV_PAGES_RESIDENT,
    KV_PAGES_SHIPPED,
    KV_PREFIX_ADMISSIONS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
)
from ..policy import POLICIES
from ..profile import PROFILER
from ..slo import SLO
from ..tracing import TRACEPARENT_HEADER, TRACER
from ..models.serving import (
    DRAINING_ERROR,
    QUEUE_FULL_ERROR,
    InferenceEngine,
    Request,
)
from ..utils import kvwire
from ..utils.kvwire import KV_SOURCE_HEADER
from .routes import _REASONS

log = logging.getLogger("tpu-scheduler")

SERVE_REQUESTS = REGISTRY.register(
    Counter(
        "tpu_serve_requests_total",
        "Inference requests by result (ok/error/timeout/cancelled)",
        ("result",),
    )
)
SERVE_TOKENS = REGISTRY.register(
    Counter(
        "tpu_serve_tokens_total",
        "Tokens emitted to clients",
    )
)
SERVE_QUEUE_DEPTH = REGISTRY.register(
    Gauge(
        "tpu_serve_queue_depth",
        "Queued requests per priority class (set at scrape time)",
        ("priority",),
    )
)
_SCRAPE_LOCK = threading.Lock()  # reset+set+expose of scrape-time gauges
SERVE_SPILLS = REGISTRY.register(
    Gauge(
        "tpu_serve_spills",
        "Low-priority slots spilled (pages freed, request requeued for "
        "exact resume) under page pressure — the serving-plane mirror of "
        "the scheduler's preemption verb",
    )
)
SERVE_LATENCY = REGISTRY.register(
    Histogram(
        "tpu_serve_request_seconds",
        "End-to-end request latency (submit to done)",
        buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                 60.0, 120.0),
    )
)
SERVE_HOST_GAP = REGISTRY.register(
    Histogram(
        "tpu_serve_host_gap_ms",
        "Wall time between consecutive fused decode chunk dispatches, in "
        "ms (the window where the accelerator can starve on host "
        "bookkeeping; the overlapped pipeline keeps it near zero).  A "
        "HISTOGRAM of per-chunk samples folded at scrape time — p50/p99 "
        "are real distribution tails, not whichever chunk scraped last "
        "(the old last-value gauge's failure mode)",
        buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                 100.0, 500.0),
    )
)


def choose_kv_victim(eng) -> int:
    """Pick the slot to preempt when the KV page pool is exhausted.

    Routed through the policy registry's ``kv`` verb: the built-in
    ranking is the historic hard-coded choice (lowest-priority slot,
    most pages held as tiebreak); a hot-loaded ``kv`` policy re-ranks
    with the typed inputs priority / pages / tokens / slot / matched
    (HIGHER score = evict first), falling back to the built-in on any
    policy fault.  ``matched`` is the disagg plane's input: tokens the
    slot got from the prefix cache at admission — a slot riding a big
    cached/adopted prefix is the cheapest eviction OR migration victim
    (re-admission re-matches the pages instead of re-prefilling).  Only
    runs on the rare pool-exhausted path and the migration picker —
    never on the per-token loop."""
    return POLICIES.select_kv_victim([
        {
            "slot": float(i),
            "priority": float(eng.priorities[i]),
            "pages": float(len(eng.slot_pages[i])),
            "tokens": float(len(getattr(s, "output", ()) or ())),
            "matched": float(eng.matched_toks[i]),
        }
        for i, s in enumerate(eng.slots)
        # done-but-unreleased slots (released at the next _prepare_step)
        # are not candidates: the migration picker runs before that
        # release, and a 'victim' with nothing left to run would turn
        # into a spurious no-live-session verdict
        if s is not None and not s.done.is_set()
    ])


class EngineLoop:
    """Single thread that owns the engine: admit + step while work exists,
    park on the submit queue when idle."""

    def __init__(self, engine: InferenceEngine, idle_sleep: float = 0.002):
        self.engine = engine
        # retained for API compatibility; the idle path now parks on the
        # engine's work event (submit/stop/drain set it) instead of
        # polling every idle_sleep seconds — an idle pod costs no wakeups
        self.idle_sleep = idle_sleep
        self.idle_parks = 0  # times the loop parked (observability/tests)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # drain support: the LOOP thread (sole mutator of queue/slot
        # state) sets ``drained`` when it observes draining + idle — no
        # TOCTOU against mid-admission or spill-requeue transitions.
        # ``http_inflight`` counts handler threads still writing
        # responses, so drain waits for flushes too (slow SSE clients).
        self.drained = threading.Event()
        self.http_inflight = 0
        self._inflight_lock = threading.Lock()
        # warm-start compilation plane (compilecache/): when serve.py
        # runs a shape-lattice warm-up, its WarmupState lands here and
        # /healthz answers 503 {"warming": true} until it completes —
        # the fleet router keeps the replica out of rotation meanwhile.
        # None = no warm-up phase (the historical boot path).
        self.warmup = None

    def inflight_enter(self) -> None:
        with self._inflight_lock:
            self.http_inflight += 1

    def inflight_exit(self) -> None:
        with self._inflight_lock:
            self.http_inflight -= 1

    def start(self) -> "EngineLoop":
        self._thread = threading.Thread(
            target=self._run, name="engine-loop", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.engine._work.set()  # wake a parked loop so it can exit
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        eng = self.engine
        failures = 0  # consecutive _fail_all rounds, reset on any success
        step_seq = 0  # steps since a traced batch started (span pacing)
        prof_seq = 0  # total steps (journal-flush pacing; never resets)
        while not self._stop.is_set():
            try:
                eng._admit()
                if any(s is not None for s in eng.slots):
                    # a traced request in a slot gets engine.step spans in
                    # its trace (request → engine step → SSE flush) — but
                    # PACED, one span per 32 steps: a single long
                    # generation must not flood the span ring and evict
                    # every other request's trace.  Untraced batches pay
                    # one generator-expression scan only.
                    traced = next(
                        (
                            s.trace_ctx
                            for s in eng.slots
                            if s is not None and s.trace_ctx is not None
                        ),
                        None,
                    )
                    # workload profiling (profile/): bracket the step with
                    # HOST-side counters only — a perf_counter read and
                    # the engine's token count.  Never touches device
                    # state, so steady-state decode stays at zero
                    # additional host→device uploads (the
                    # engine.device_uploads probe pins this).
                    prof = PROFILER.enabled
                    if prof:
                        prof_t0 = time.perf_counter()
                        prof_tok0 = eng.tokens_emitted
                    if traced is not None and step_seq % 32 == 0:
                        with TRACER.span(
                            "engine.step", parent=traced,
                            step=step_seq,
                            slots=sum(
                                1 for s in eng.slots if s is not None
                            ),
                        ) as sp:
                            eng.step()
                            if sp is not None:
                                # per-step host-gap telemetry rides the
                                # paced span: the dispatch-to-dispatch
                                # wall this step left the device idle
                                sp.set_attr(
                                    "host_gap_ms",
                                    round(eng.last_host_gap_ms, 3),
                                )
                                sp.set_attr("overlap", eng.overlap)
                                if prof:
                                    # profile sample rides the paced span
                                    # too — /traces cross-links behavior
                                    # to the decision trail
                                    wall = time.perf_counter() - prof_t0
                                    toks = eng.tokens_emitted - prof_tok0
                                    sp.set_attr(
                                        "tokens_per_sec",
                                        round(toks / wall, 1)
                                        if wall > 0 else 0.0,
                                    )
                    else:
                        eng.step()
                    if prof:
                        PROFILER.record_step(
                            tokens=eng.tokens_emitted - prof_tok0,
                            wall_s=time.perf_counter() - prof_t0,
                            slots_active=sum(
                                1 for s in eng.slots if s is not None
                            ),
                            slots_total=eng.max_batch,
                            host_gap_ms=eng.last_host_gap_ms,
                            queue_depth=eng.queue.qsize(),
                            hbm_pages=(
                                eng.n_pages - 1 - len(eng.free_pages)
                            ),
                        )
                        prof_seq += 1
                        if prof_seq % 256 == 0:
                            # periodic profile records into the flight
                            # recorder, paced by a counter that never
                            # resets (step_seq zeroes on untraced
                            # batches; cheap when not due: one time
                            # compare inside)
                            PROFILER.maybe_journal()
                    step_seq = step_seq + 1 if traced is not None else 0
                else:
                    if eng.draining and eng.queue.empty():
                        # consistent snapshot: this thread just ran
                        # _admit and owns every queue→slot transition
                        self.drained.set()
                    # idle: park on the work event (submit/stop/drain set
                    # it).  clear → re-check → wait is lost-wakeup-safe: a
                    # submit landing after the clear re-sets the event and
                    # the wait returns immediately.
                    eng._work.clear()
                    if (
                        eng.queue.empty()
                        and eng._tasks.empty()
                        and not any(s is not None for s in eng.slots)
                        and not self._stop.is_set()
                    ):
                        self.idle_parks += 1
                        eng._work.wait()
                failures = 0
            except RuntimeError as e:
                if "page pool exhausted" in str(e):
                    # ordinary overload, not a bug: every slot is stalled
                    # for pages (the engine's priority spill found no
                    # lower class to evict).  Preempt ONE victim — the
                    # LOWEST-priority slot, most pages held as tiebreak —
                    # honoring the SLO classes even on this last-resort
                    # path.  First eviction is a requeue (exact resume);
                    # a repeat offender genuinely doesn't fit the pool
                    # and gets the terminal error (no infinite thrash).
                    victim = choose_kv_victim(eng)
                    req = eng.slots[victim]
                    log.warning(
                        "KV page pool exhausted; preempting priority-%d "
                        "slot %d (%d pages held)",
                        int(eng.priorities[victim]), victim,
                        len(eng.slot_pages[victim]),
                    )
                    if req.pool_spills < 1:
                        req.pool_spills += 1
                        eng.spills += 1
                        eng._release_slot(victim)
                        eng._enqueue(req)
                    else:
                        req.error = "preempted: KV page pool exhausted"
                        req.done.set()
                        eng._release_slot(victim)
                else:
                    failures += 1
                    self._fail_all("internal engine error", failures)
            except Exception:
                failures += 1
                self._fail_all("internal engine error", failures)

    def _fail_all(self, msg: str, failures: int = 1) -> None:
        """An engine bug must not kill the loop thread silently: fail every
        in-flight request so clients unblock, then keep serving.  Two
        hardening rules (ADVICE r2): per-slot cleanup is individually
        guarded (a raising ``_release_slot`` must not kill the loop
        thread), and consecutive failures back off exponentially (capped
        at 1s) so a persistent engine bug degrades to a slow error loop
        instead of a hot one."""
        log.exception(
            "engine loop error (consecutive=%d); failing in-flight requests",
            failures,
        )
        for i, req in enumerate(self.engine.slots):
            if req is None:
                continue
            try:
                req.error = msg
                req.done.set()
                self.engine._release_slot(i)
            except Exception:
                log.exception("cleanup of slot %d failed; force-dropping", i)
                self.engine._force_drop_slot(i)
        self._stop.wait(min(1.0, 0.05 * (2 ** min(failures, 10))))


def _queue_wait_ms(req) -> Optional[float]:
    """Queue wait the request perceived (first enqueue → first slot
    admission), or None before admission stamped."""
    if req.t_submit > 0.0 and req.t_admit > 0.0:
        return max(0.0, (req.t_admit - req.t_submit) * 1000.0)
    return None


def _token_ids(x, vocab_size: int, what: str) -> list:
    """Validate a JSON field as a list of in-range token ids.  bool is an
    int subclass in Python, so ``true`` would otherwise slip through; and
    out-of-range ids would silently clamp in the embedding gather and
    produce garbage completions instead of a 400 (ADVICE r2)."""
    if not isinstance(x, list) or not all(
        isinstance(t, int) and not isinstance(t, bool)
        and 0 <= t < vocab_size
        for t in x
    ):
        raise ValueError(
            f"{what!r} must be a list of token ids in [0, {vocab_size})"
        )
    return x


def _strict_seed(v):
    """None, or an int — floats/bools/strings 400 (silent coercion would
    hand two different client values the same completion, the exact
    reproducibility bug seeds exist to prevent)."""
    if v is None:
        return None
    if isinstance(v, bool) or not isinstance(v, int):
        raise ValueError("'seed' must be an integer")
    return v


def _strict_nonneg_int(body: dict, field: str, default: int = 0) -> int:
    """Non-negative JSON integer: bool is an int subclass, and a float
    (e.g. 2.9) would silently truncate — both are client bugs deserving
    a 400, same strictness as _token_ids/seed."""
    v = body.get(field, default)
    if not isinstance(v, int) or isinstance(v, bool) or v < 0:
        raise ValueError(f"'{field}' must be a non-negative integer")
    return v


def _strict_finite_number(body: dict, field: str) -> float:
    """Finite JSON number (int or float, not bool, not NaN/inf) — the
    engine rejects non-finite penalties anyway; catching it here keeps
    validation consistent across the endpoint's fields."""
    v = body.get(field, 0.0)
    if isinstance(v, bool) or not isinstance(v, (int, float)) \
            or not math.isfinite(v):
        raise ValueError(f"'{field}' must be a finite number")
    return float(v)


def _request_from_body(body: dict, vocab_size: int) -> Request:
    prompt = _token_ids(body.get("prompt"), vocab_size, "prompt")
    priority = body.get("priority", 0)
    if isinstance(priority, bool) or not isinstance(priority, int):
        raise ValueError("'priority' must be an integer")
    stop = _token_ids(body.get("stop", []), vocab_size, "stop")
    logprobs = _strict_nonneg_int(body, "logprobs")
    bias_raw = body.get("logit_bias", {})
    if not isinstance(bias_raw, dict):
        raise ValueError("'logit_bias' must be an object of id -> bias")
    bias = {}
    for k, v in bias_raw.items():
        try:
            tid = int(k)  # OpenAI-style string keys (JSON objects)
        except (TypeError, ValueError):
            raise ValueError(f"logit_bias key {k!r} is not a token id")
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            raise ValueError(f"logit_bias value for {k!r} must be a number")
        bias[tid] = float(v)
    return Request(
        prompt=prompt,
        max_new_tokens=int(body.get("max_tokens", 16)),
        temperature=float(body.get("temperature", 0.0)),
        top_k=int(body.get("top_k", 0)),
        top_p=float(body.get("top_p", 1.0)),
        adapter=str(body.get("adapter", "")),
        stop_tokens=tuple(stop),
        logprobs=logprobs,
        logit_bias=bias,
        frequency_penalty=_strict_finite_number(body, "frequency_penalty"),
        presence_penalty=_strict_finite_number(body, "presence_penalty"),
        min_tokens=_strict_nonneg_int(body, "min_tokens"),
        priority=priority,
        seed=_strict_seed(body.get("seed")),
        allowed_tokens=tuple(
            _token_ids(body.get("allowed_tokens", []), vocab_size,
                       "allowed_tokens")
        ),
    )


def _logprobs_payload(req: Request) -> dict:
    return {
        "token_logprobs": req.token_logprobs,
        "top_logprobs": [
            [{"id": t, "logprob": lp} for t, lp in top]
            for top in req.top_logprobs
        ],
    }


def _drain_burst(q: "queue.Queue", first, cap: int = 512) -> list:
    """Burst-drain an SSE token queue: ``first`` plus everything already
    queued, in queue order, bounded by ``cap`` so a pathological backlog
    cannot build an unbounded buffer for one socket write.  The stream
    loop turns the result into ONE HTTP chunk and ONE flush — syscalls
    scale with bursts, not tokens, when the engine outruns the socket."""
    events = [first]
    while len(events) < cap:
        try:
            events.append(q.get_nowait())
        except queue.Empty:
            break
    return events


def _split_hostport(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad replica address {addr!r} (want host:port)")
    return host, int(port)


# Ceiling on the in-request adoption pull (X-KV-Source / /v1/kv/adopt →
# donor /v1/kv/export): adoption is a latency OPTIMIZATION, so a stalled
# donor must cost less than the re-prefill it was meant to save.
ADOPT_PULL_TIMEOUT_S = 5.0


def _backend_post(
    addr: str, path: str, body: bytes, ctype: str, timeout: float = 30.0
) -> tuple[int, bytes]:
    """One replica-to-replica POST (KV export pulls).  Small bodies,
    full read — streaming exchanges go through ``_backend_stream``."""
    host, port = _split_hostport(addr)
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", path, body, {"Content-Type": ctype})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _backend_stream(addr: str, path: str, body: bytes, timeout: float = 300.0):
    """Open a streaming POST to a peer replica; returns (response, conn,
    error) with the connection left open for incremental reads — the
    migration handoff reads the continuation token by token."""
    try:
        host, port = _split_hostport(addr)
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        conn.request(
            "POST", path, body,
            {"Content-Type": "application/octet-stream"},
        )
        return conn.getresponse(), conn, None
    except (OSError, ConnectionError, ValueError) as e:
        return None, None, str(e)


def _relay_migrated(req: Request, resp, conn) -> None:
    """Source-side continuation pump for a migrated session: the
    destination streams the remaining tokens as SSE events; this thread
    feeds them into the ORIGINAL request object (output/logprobs/
    on_token/done) exactly as the engine thread would have — ownership
    of the request passed from the engine to this thread at eviction,
    so nothing else mutates it.  The client's connection never moves;
    only the compute did.  A client cancel propagates by dropping the
    relay connection — the destination sees the disconnect at its next
    write and cancels its side."""
    try:
        while True:
            if req.cancelled:
                break  # closing conn below cancels the destination too
            line = resp.readline()
            if not line:
                if not req.cancelled and not req.error:
                    req.error = "migrated session relay closed early"
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            payload = line[6:]
            if payload == b"[DONE]":
                break
            ev = json.loads(payload)
            if "error" in ev:
                req.error = str(ev["error"])
                continue  # the [DONE] terminator follows
            tok = ev.get("token")
            if tok is None:
                continue
            if req.logprobs > 0:
                req.token_logprobs.append(ev.get("logprob"))
                req.top_logprobs.append([
                    (int(d["id"]), float(d["logprob"]))
                    for d in ev.get("top_logprobs") or []
                ])
            req.output.append(int(tok))
            cb = req.on_token
            if cb is not None:
                try:
                    cb(int(tok))
                except Exception:
                    log.warning(
                        "on_token raised during migration relay; "
                        "streaming disabled", exc_info=True,
                    )
                    req.on_token = None
    except (OSError, ConnectionError, ValueError) as e:
        if not req.error:
            req.error = f"migration relay broke: {e}"
    finally:
        try:
            conn.close()
        except OSError:
            pass
        req.done.set()


def make_handler(loop: EngineLoop, request_timeout: float = 300.0):
    engine = loop.engine

    class InferenceHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "tpu-elastic-inference"

        def log_message(self, fmt, *args):  # route through our logger
            log.debug("inference http: " + fmt, *args)

        def _json(self, code: int, obj: dict,
                  extra_headers: Optional[dict] = None) -> None:
            data = json.dumps(obj).encode()
            self.send_response(code, _REASONS.get(code, ""))
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                if engine.draining:
                    # not-ready during drain: the Service stops routing
                    # new requests here while in-flight ones finish
                    return self._json(503, {"ok": False,
                                            "draining": True})
                wu = loop.warmup
                if wu is not None and wu.warming:
                    # not-ready while the shape lattice pre-lowers: the
                    # fleet router parses the body and holds the replica
                    # in 'warming' (distinct from draining — capacity is
                    # COMING, so the autoscaler must not double-scale)
                    return self._json(503, {
                        "ok": False,
                        "warming": True,
                        "warmup": wu.to_dict(),
                    })
                return self._json(200, {"ok": True})
            if self.path == "/version":
                return self._json(200, {"version": __version__})
            if self.path == "/metrics":
                # scrape-time gauges from live engine state (reset first
                # so a drained priority class doesn't linger stale); the
                # lock makes reset+set+expose atomic across concurrent
                # scrapes — without it one scrape's reset can blank
                # another's series mid-exposition
                with _SCRAPE_LOCK:
                    SERVE_QUEUE_DEPTH.reset()
                    for pri, depth in engine.queue_depths().items():
                        SERVE_QUEUE_DEPTH.set(str(pri), value=float(depth))
                    SERVE_SPILLS.set(value=float(engine.spills))
                    # disaggregated-serving gauges from live engine
                    # state (monotonic counters exposed at scrape time,
                    # the SERVE_SPILLS stance): page residency split,
                    # pages shipped each way, prefix-cache admissions
                    free = len(engine.free_pages)
                    cached = len(engine.page_key)
                    total = engine.n_pages - 1
                    KV_PAGES_RESIDENT.set(
                        "active", value=float(total - free - cached)
                    )
                    KV_PAGES_RESIDENT.set("cached", value=float(cached))
                    KV_PAGES_RESIDENT.set("free", value=float(free))
                    KV_PAGES_SHIPPED.set(
                        "exported", value=float(engine.kv_pages_exported)
                    )
                    KV_PAGES_SHIPPED.set(
                        "imported", value=float(engine.kv_pages_imported)
                    )
                    KV_PREFIX_ADMISSIONS.set(
                        "hit", value=float(engine.prefix_admission_hits)
                    )
                    KV_PREFIX_ADMISSIONS.set(
                        "miss",
                        value=float(
                            engine.prefix_lookups
                            - engine.prefix_admission_hits
                        ),
                    )
                    # fold the engine's buffered per-chunk gap samples
                    # (the scraper pays the bucketing, never the engine)
                    SERVE_HOST_GAP.observe_batch(
                        values=engine.drain_host_gaps()
                    )
                    data = REGISTRY.expose().encode()
                self.send_response(200, "OK")
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            if self.path == "/debug/profiles":
                # the profile observatory's serving-plane surface: this
                # pod's per-class behavior + whatever co-tenancy it knows
                return self._json(200, PROFILER.debug_state())
            if self.path == "/debug/slo":
                # the SLO plane's replica-side surface: this pod's own
                # journey windows (vantage=replica) + loaded objectives
                return self._json(200, SLO.debug_state())
            if self.path.startswith("/debug/trace/"):
                # one trace's spans from THIS process, causally ordered
                # (the fleet router/scheduler serve the cross-process
                # assembly; a replica answers its own ring so the
                # assembler — or an operator — can pull it)
                from ..slo.assembly import local_trace_payload

                tid = self.path[len("/debug/trace/"):].split("?", 1)[0]
                return self._json(200, local_trace_payload(tid))
            if self.path.split("?", 1)[0] == "/traces":
                # serving-plane traces (request → engine step → SSE flush);
                # one response shape shared with the scheduler's /traces
                from urllib.parse import parse_qsl

                from ..tracing import traces_response

                _, _, query = self.path.partition("?")
                params = dict(parse_qsl(query, keep_blank_values=True))
                return self._json(200, traces_response(params))
            if self.path == "/v1/stats":
                eng = engine
                return self._json(200, {
                    "queued_by_priority": {
                        str(k): v for k, v in eng.queue_depths().items()
                    },
                    "max_queue": eng.max_queue,
                    "spills": int(eng.spills),
                    "active_slots": sum(
                        1 for s in eng.slots if s is not None
                    ),
                    "max_batch": eng.max_batch,
                    "queued": eng.queue.qsize(),
                    "free_pages": len(eng.free_pages),
                    "total_pages": eng.n_pages - 1,
                    "prefix_hit_tokens": int(eng.prefix_hit_tokens),
                    "adapters": sorted(
                        a for a in eng.adapter_index if a
                    ),
                    # speculation telemetry: accepted/passes is the mean
                    # extra tokens each verify pass bought
                    "spec_k": eng.spec_k,
                    "spec_passes": int(eng.spec_passes),
                    "spec_accepted": int(eng.spec_accepted),
                    "draft_model": eng.draft is not None,
                    # round-4 engine config, so clients can discover the
                    # feature surface before sending requests
                    "logprobs_k": eng.logprobs_k,
                    "prefill_chunk": eng.prefill_chunk,
                    "paged_kernel": eng.paged_kernel,
                    "vocab_size": eng.cfg.vocab_size,
                    # overlapped decode pipeline: mode + the host-gap
                    # telemetry it exists to shrink (see OPERATIONS.md
                    # "Serving performance")
                    "overlap": eng.overlap,
                    "host_gap": {
                        k: round(v, 4) if isinstance(v, float) else v
                        for k, v in eng.host_gap_stats().items()
                    },
                    "device_uploads": int(eng.device_uploads),
                    # fleet-facing fields: the router aligns its
                    # prefix-affinity digest chain to page_size, and the
                    # autoscaler/resize tooling watches discarded
                    # in-flight chunks (the ≤1-per-moved-pod contract)
                    "page_size": eng.page_size,
                    "chunks_discarded": int(eng.chunks_discarded),
                    "replica": getattr(eng, "replica_name", ""),
                    # disaggregated serving: the replica's role in the
                    # prefill/decode split (the router keeps prefill-role
                    # replicas out of completion rotation) and the KV
                    # shipping/prefix-cache counters the fleet index and
                    # the tpu_kv_* gauges read
                    "role": getattr(eng, "fleet_role", "both"),
                    "kv": {
                        "pages_exported": int(eng.kv_pages_exported),
                        "pages_imported": int(eng.kv_pages_imported),
                        "export_bundles": int(eng.kv_exports),
                        "import_bundles": int(eng.kv_imports),
                        "migrated_out": int(eng.sessions_migrated_out),
                        "migrated_in": int(eng.sessions_migrated_in),
                        "prefix_lookups": int(eng.prefix_lookups),
                        "prefix_hits": int(eng.prefix_admission_hits),
                        "prefix_misses": int(
                            eng.prefix_lookups - eng.prefix_admission_hits
                        ),
                        "resident_pages": int(
                            eng.n_pages - 1 - len(eng.free_pages)
                        ),
                        "cached_pages": len(eng.page_key),
                    },
                    # warm-start compilation plane: warm-up phase state
                    # (router/autoscaler readiness gating) + the AOT
                    # cache's fill/load counters (check-compile-cache
                    # asserts "second start → zero new lowerings" here)
                    "warmup": (
                        loop.warmup.to_dict()
                        if loop.warmup is not None else {"state": "none"}
                    ),
                    "compile_cache": (
                        eng.compile_cache.stats()
                        if getattr(eng, "compile_cache", None) is not None
                        else None
                    ),
                })
            return self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            # drain accounting: the response (incl. a long SSE stream)
            # must fully flush before a draining process may exit
            loop.inflight_enter()
            try:
                return self._do_post()
            finally:
                loop.inflight_exit()

        def _do_post(self):
            # disaggregated serving data plane (OPERATIONS.md
            # "Disaggregated serving"): prefill-only admissions, KV-page
            # export/adopt, and live session migration ride the same
            # server; engine state is only ever touched via
            # ``engine.run_task`` (the engine thread owns it)
            if self.path == "/v1/prefill":
                return self._prefill_only()
            if self.path == "/v1/kv/export":
                return self._kv_export()
            if self.path == "/v1/kv/adopt":
                return self._kv_adopt()
            if self.path == "/v1/migrate/out":
                return self._migrate_out()
            if self.path == "/v1/migrate/in":
                return self._migrate_in()
            if self.path != "/v1/completions":
                return self._json(404, {"error": f"no route {self.path}"})
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                n = body.get("n", 1)
                if (
                    not isinstance(n, int) or isinstance(n, bool)
                    or not 1 <= n <= engine.max_batch
                ):
                    raise ValueError(
                        f"'n' must be an integer in [1, {engine.max_batch}]"
                    )
                reqs = []
                for k in range(n):
                    req = _request_from_body(body, engine.cfg.vocab_size)
                    if n > 1 and req.seed is not None:
                        req.seed = req.seed + k  # choice k's derived seed
                    reqs.append(req)
                req = reqs[0]
            except (
                ValueError, TypeError, OverflowError, json.JSONDecodeError,
            ) as e:
                # OverflowError: float(huge-json-int) — JSON ints are
                # arbitrary-precision, float() of one past 1e308 raises
                # OverflowError (not ValueError) and must still 400
                # TypeError covers non-numeric scalars (null/list for
                # max_tokens, temperature, ...) — a clean 400, not an
                # aborted connection
                return self._json(400, {"error": str(e)})
            if FAULTS.enabled:
                # the SLO plane's latency-injection point: a 'delay'
                # plan here degrades TTFT/e2e without failing anything
                # (check-slo's breach drill); error-family kinds answer
                # 503 like any transient backend failure
                try:
                    FAULTS.maybe_fire("serve.request")
                except OSError as e:
                    return self._json(503, {"error": str(e)})
            kv_src = self.headers.get(KV_SOURCE_HEADER)
            if kv_src and engine.prefix_cache:
                # fleet prefix-index adoption: the router knows another
                # replica holds this prompt's KV pages — pull them
                # before admission so _match_prefix turns the route into
                # skipped prefill.  Strictly best-effort: any failure
                # just re-prefills locally (never fails the request).
                try:
                    self._adopt_from(kv_src, body.get("prompt"),
                                     str(body.get("adapter", "")))
                except Exception:
                    log.warning(
                        "KV adoption from %s failed; re-prefilling",
                        kv_src, exc_info=True,
                    )
            # serving-plane tracing: a client traceparent header joins its
            # trace; otherwise each request roots a fresh one.  The span
            # context rides on the Request so the ENGINE thread can drop
            # queued/admitted/step markers into the same trace.
            with TRACER.span(
                "serve.request",
                parent=self.headers.get(TRACEPARENT_HEADER) or None,
                n=n,
                stream=bool(body.get("stream")),
                prompt_tokens=len(req.prompt),
                max_tokens=req.max_new_tokens,
            ) as sp:
                ctx = sp.context() if sp else None
                for r in reqs:
                    r.trace_ctx = ctx
                if body.get("stream"):
                    return self._stream(reqs)
                if n > 1:
                    return self._multi(reqs, n)
                return self._single(req, sp)

        # -- disaggregated serving data plane ------------------------------

        def _read_json(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            return body

        def _bytes_resp(
            self, code: int, data: bytes,
            ctype: str = "application/octet-stream",
        ) -> None:
            self.send_response(code, _REASONS.get(code, ""))
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _adopt_from(
            self, source: str, tokens, adapter: str, max_pages: int = 0
        ) -> dict:
            """Pull the prefix's cached pages from ``source`` and land
            them locally.  Skips the pull when the local cache already
            covers everything adoptable — the common re-route case must
            not re-ship pages it has."""
            if not isinstance(tokens, list) or not all(
                isinstance(t, int) and not isinstance(t, bool)
                for t in tokens
            ):
                return {"imported": 0, "reason": "no adoptable prompt"}
            ps = engine.page_size
            want = max(0, (len(tokens) - 1) // ps)
            if max_pages > 0:
                want = min(want, max_pages)
            if want == 0:
                return {"imported": 0,
                        "reason": "prompt shorter than one full page"}
            have = engine.run_task(
                lambda: len(engine.cached_prefix_pages(tokens, adapter))
            )
            if have >= want:
                return {"imported": 0, "already": have,
                        "reason": "local cache already covers the prefix"}
            # bounded pull: this runs INSIDE the client's completion
            # request (the X-KV-Source pre-admission path) — a donor
            # that stopped answering (health-drained for unreachability)
            # must cost seconds before the best-effort fallback
            # re-prefills, not the 30s backend default
            status, data = _backend_post(
                source, "/v1/kv/export",
                json.dumps({
                    "tokens": tokens, "adapter": adapter,
                    "max_pages": max_pages,
                }).encode(),
                "application/json",
                timeout=ADOPT_PULL_TIMEOUT_S,
            )
            if status != 200:
                return {"imported": 0,
                        "reason": f"source answered {status}"}
            hdr, pages = kvwire.decode_bundle(data)
            return engine.run_task(
                lambda: engine.import_pages(hdr, pages)
            )

        def _prefill_only(self):
            """Prefill-role admission (the disagg split's first half):
            run the prompt through (chunked) prefill so its pages land
            in THIS replica's prefix cache, ready for export.  Costs one
            emitted-and-discarded token — the exact completion path, so
            every prefill optimization (chunking, prefix hits) applies."""
            if not engine.prefix_cache:
                return self._json(409, {
                    "error": "prefix cache disabled (--prefix-cache)"
                })
            try:
                body = self._read_json()
                prompt = _token_ids(
                    body.get("prompt"), engine.cfg.vocab_size, "prompt"
                )
                adapter = str(body.get("adapter", ""))
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                return self._json(400, {"error": str(e)})
            t0 = time.monotonic()
            req = Request(
                prompt=list(prompt), max_new_tokens=1, adapter=adapter
            )
            engine.submit(req)
            if not req.done.wait(request_timeout):
                req.cancel()
                req.done.wait(10.0)
                return self._json(504, {"error": "prefill timed out"})
            if req.error:
                return self._json(
                    _reject_code(req.error), {"error": req.error}
                )
            return self._json(200, {
                "ok": True,
                "tokens": len(prompt),
                # pages a later admission (or export) can actually use —
                # the chain's plen-1 cap, same as _match_prefix
                "pages": max(0, (len(prompt) - 1) // engine.page_size),
                "replica": getattr(engine, "replica_name", ""),
                "wall_ms": round((time.monotonic() - t0) * 1000, 3),
            })

        def _kv_export(self):
            if not engine.prefix_cache:
                return self._json(409, {
                    "error": "prefix cache disabled (--prefix-cache)"
                })
            try:
                body = self._read_json()
                tokens = _token_ids(
                    body.get("tokens"), engine.cfg.vocab_size, "tokens"
                )
                adapter = str(body.get("adapter", ""))
                max_pages = int(body.get("max_pages", 0))
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                return self._json(400, {"error": str(e)})
            try:
                data = engine.run_task(
                    lambda: engine.export_prefix_pages(
                        tokens, adapter, max_pages
                    )
                )
            except TimeoutError as e:
                return self._json(503, {"error": str(e)})
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            if data is None:
                return self._json(404, {
                    "error": "no cached pages for this prefix"
                })
            return self._bytes_resp(200, data)

        def _kv_adopt(self):
            if not engine.prefix_cache:
                return self._json(409, {
                    "error": "prefix cache disabled (--prefix-cache)"
                })
            try:
                body = self._read_json()
                source = str(body.get("source", ""))
                if not source:
                    raise ValueError("'source' (host:port) is required")
                res = self._adopt_from(
                    source, body.get("tokens"),
                    str(body.get("adapter", "")),
                    int(body.get("max_pages", 0)),
                )
            except kvwire.WireError as e:
                return self._json(502, {"error": f"corrupt bundle: {e}"})
            except (OSError, ConnectionError) as e:
                return self._json(502, {"error": f"source pull failed: {e}"})
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                return self._json(400, {"error": str(e)})
            return self._json(200, res)

        def _migrate_out(self):
            """Live migration, source side: detach a session (chosen by
            the ``kv`` policy verb unless a slot is named), ship the
            bundle to ``dest``, then RELAY the destination's continuation
            into the original request — the client's connection never
            moves, only the compute does.  A refused handoff re-enqueues
            locally (exact resume), so the session is never lost."""
            try:
                body = self._read_json()
                dest = str(body.get("dest", ""))
                if not dest:
                    raise ValueError("'dest' (host:port) is required")
                slot = body.get("slot")
                if slot is not None and (
                    isinstance(slot, bool) or not isinstance(slot, int)
                ):
                    raise ValueError("'slot' must be an integer")
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                return self._json(400, {"error": str(e)})

            def grab():
                i = slot
                if i is None:
                    if not any(
                        s is not None and not s.done.is_set()
                        for s in engine.slots
                    ):
                        return None
                    i = choose_kv_victim(engine)
                elif not 0 <= i < engine.max_batch:
                    return None
                r = engine.slots[i]
                if r is None or r.done.is_set():
                    return None
                before = engine.kv_pages_exported
                data = engine.migrate_out_bundle(i)
                return (i, r, data, engine.kv_pages_exported - before)

            try:
                got = engine.run_task(grab)
            except TimeoutError as e:
                # nothing was detached (the thunk is abandoned): the
                # session never left this replica
                return self._json(503, {"error": str(e)})
            if got is None:
                return self._json(409, {
                    "error": "no live session to migrate"
                })
            i, req, data, n_pages = got
            resp, conn, err = _backend_stream(dest, "/v1/migrate/in", data)
            if resp is None or resp.status != 200:
                if resp is not None:
                    err = f"destination answered {resp.status}"
                    try:
                        conn.close()
                    except OSError:
                        pass
                # the session is OURS again: exact local resume (the
                # spill-requeue path — the client never notices), and
                # the migrate-out stats roll back so fleet-wide
                # sum(migrated_out) keeps matching sum(migrated_in)
                # (the OPERATIONS cross-check) with refused hops
                def resume_local():
                    engine._enqueue(req)
                    engine.sessions_migrated_out -= 1
                    engine.kv_pages_exported -= n_pages

                try:
                    # non-abandonable: a timeout here must NOT drop the
                    # re-enqueue — the thunk still runs when the engine
                    # catches up, so the session is never lost
                    engine.run_task(resume_local, abandon_on_timeout=False)
                except TimeoutError:
                    log.warning(
                        "local resume of refused migration is queued "
                        "behind a busy engine; it will run at the next "
                        "admission pass"
                    )
                KV_MIGRATIONS.inc("out_refused")
                return self._json(502, {
                    "ok": False, "resumed_local": True, "error": err,
                })
            threading.Thread(
                target=_relay_migrated, args=(req, resp, conn),
                name="migrate-relay", daemon=True,
            ).start()
            KV_MIGRATIONS.inc("out")
            return self._json(200, {
                "ok": True, "slot": i, "dest": dest,
                "pages_shipped": n_pages,
                "tokens_done": len(req.output),
            })

        def _migrate_in(self):
            """Live migration, destination side: import the bundle's
            pages, resume the session (prefix-matching what just
            landed), and stream the continuation back to the source as
            SSE events — the source relays them into the original
            client connection."""
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length)
            try:
                hdr, pages = kvwire.decode_bundle(raw)
            except kvwire.WireError as e:
                return self._json(400, {"error": str(e)})
            if hdr.get("kind") != "session":
                return self._json(400, {
                    "error": f"expected a session bundle, "
                             f"got {hdr.get('kind')!r}"
                })
            state = hdr.get("request") or {}
            q: "queue.Queue" = queue.Queue()
            box: dict = {}

            def on_token(tok):
                r = box["req"]
                if r.logprobs > 0:
                    q.put((tok, r.token_logprobs[-1], r.top_logprobs[-1]))
                else:
                    q.put((tok, None, None))

            def setup():
                imported = None
                if pages and engine.prefix_cache:
                    imported = engine.import_pages(hdr, pages)
                r = engine.resume_session(state, on_token=on_token)
                box["req"] = r
                return r, imported

            try:
                req, _imported = engine.run_task(setup)
            except TimeoutError as e:
                # the thunk is abandoned (engine busy): nothing landed,
                # the source keeps the session — a clean refusal, never
                # a session running on both replicas
                return self._json(503, {"error": str(e)})
            except RuntimeError as e:
                return self._json(503, {"error": str(e)})
            except (ValueError, TypeError) as e:
                return self._json(400, {"error": str(e)})
            KV_MIGRATIONS.inc("in")
            self.send_response(200, "OK")
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk_many(payloads: list) -> None:
                payload = b"".join(
                    f"data: {p}\n\n".encode() for p in payloads
                )
                self.wfile.write(
                    f"{len(payload):x}\r\n".encode() + payload + b"\r\n"
                )
                self.wfile.flush()

            def event_json(item) -> str:
                tok, lp, top = item
                ev = {"token": tok}
                if lp is not None:
                    ev["logprob"] = lp
                    ev["top_logprobs"] = [
                        {"id": t, "logprob": l} for t, l in top
                    ]
                return json.dumps(ev)

            deadline = time.monotonic() + request_timeout
            try:
                while time.monotonic() < deadline:
                    try:
                        first = q.get(timeout=0.1)
                    except queue.Empty:
                        if req.done.is_set() and q.empty():
                            break
                        continue
                    chunk_many([
                        event_json(e) for e in _drain_burst(q, first)
                    ])
                if not req.done.is_set():
                    req.cancel()
                    chunk_many([json.dumps(
                        {"error": "migrated session timed out"}
                    )])
                elif req.error:
                    chunk_many([json.dumps({"error": req.error})])
                chunk_many(["[DONE]"])
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                # the source (or its client) went away: stop generating
                req.cancel()

        def _replica_journey(self, sp, ok: bool, e2e_ms: float,
                             queue_ms, tokens: int,
                             ttft_ms=None, tpot_ms=None) -> None:
            """This pod's own vantage on the journey (the router records
            the client-perceived one) — one append when the SLO plane is
            on, nothing otherwise."""
            if not SLO.enabled:
                return
            SLO.record_journey(
                vantage="replica",
                ok=ok,
                ttft_ms=ttft_ms,
                tpot_ms=tpot_ms,
                e2e_ms=round(e2e_ms, 3),
                queue_ms=queue_ms,
                tokens=tokens,
                trace_id=sp.trace_id if sp else "",
                replica=getattr(engine, "replica_name", ""),
            )

        def _single(self, req, sp):
            t0 = time.monotonic()
            engine.submit(req)
            if not req.done.wait(request_timeout):
                req.cancel()  # engine frees the slot at the next boundary
                # wait for the engine's acknowledgement (done) before
                # reading output — the Request thread-ownership rule; the
                # next chunk boundary is normally well under this wait
                acked = req.done.wait(10.0)
                SERVE_REQUESTS.inc("timeout")
                e2e = time.monotonic() - t0
                SERVE_LATENCY.observe(value=e2e)
                if acked:  # partial tokens handed over are emitted work
                    SERVE_TOKENS.inc(value=len(req.output))
                self._replica_journey(
                    sp, ok=False, e2e_ms=e2e * 1000,
                    queue_ms=_queue_wait_ms(req),
                    tokens=len(req.output) if acked else 0,
                )
                return self._json(504, {
                    "error": "generation timed out",
                    # tokens generated before the deadline are real work —
                    # hand them over rather than discarding them (and so
                    # are their logprobs, equally complete after the ack)
                    "tokens": list(req.output) if acked else [],
                    **(
                        {"logprobs": _logprobs_payload(req)}
                        if acked and req.logprobs > 0 else {}
                    ),
                })
            e2e = time.monotonic() - t0
            SERVE_LATENCY.observe(value=e2e)
            queue_ms = _queue_wait_ms(req)
            if req.error:
                SERVE_REQUESTS.inc("error")
                sp.set_attr("error", req.error)
                code = _reject_code(req.error)
                self._replica_journey(
                    sp, ok=False, e2e_ms=e2e * 1000, queue_ms=queue_ms,
                    tokens=0,
                )
                return self._json(code, {"error": req.error})
            SERVE_REQUESTS.inc("ok")
            SERVE_TOKENS.inc(value=len(req.output))
            sp.set_attr("tokens", len(req.output))
            resp = {"tokens": req.output}
            if req.logprobs > 0:
                resp["logprobs"] = _logprobs_payload(req)
            self._replica_journey(
                sp, ok=True, e2e_ms=e2e * 1000, queue_ms=queue_ms,
                tokens=len(req.output),
            )
            # queue wait rides a response header: the router folds it
            # into the client-perceived journey record (a non-streamed
            # response sends headers AFTER generation, so the wait is
            # known here; streams carry it as an SSE comment instead)
            extra = (
                {"X-TPU-Queue-Wait-Ms": f"{queue_ms:.3f}"}
                if queue_ms is not None else None
            )
            return self._json(200, resp, extra_headers=extra)

        def _multi(self, reqs, n: int) -> None:
            """n parallel completions (OpenAI's ``n``): submit every
            choice (identical prompts share prefix-cache pages when the
            engine caches; a given "seed" derives per-choice seeds as
            seed+k), wait for all, return indexed choices."""
            t0 = time.monotonic()
            deadline = t0 + request_timeout
            for r in reqs:
                engine.submit(r)
            timed_out = False
            cancelled_for_err = False
            for r in reqs:
                if not cancelled_for_err and any(x.error for x in reqs):
                    # fail fast: one choice errored (admission rejection
                    # or an engine-side failure) — cancel its siblings
                    # instead of letting them generate toward a response
                    # that is already a 400 (cancel is idempotent and a
                    # no-op on already-done requests)
                    cancelled_for_err = True
                    for s in reqs:
                        s.cancel()
                if not r.done.wait(max(0.0, deadline - time.monotonic())):
                    timed_out = True
                    r.cancel()
            acked = {
                id(r): r.done.wait(10.0) if (timed_out or cancelled_for_err)
                else True
                for r in reqs
            }  # thread-ownership rule: only read output after done
            SERVE_LATENCY.observe(value=time.monotonic() - t0)
            errs = [r.error for r in reqs if r.error]
            if errs:
                # only the actually-errored choices count as errors; the
                # cancelled siblings are exactly that
                SERVE_REQUESTS.inc("error", value=float(len(errs)))
                if len(errs) < len(reqs):
                    SERVE_REQUESTS.inc(
                        "cancelled", value=float(len(reqs) - len(errs))
                    )
                code = _reject_code(errs[0])
                return self._json(code, {"error": errs[0]})
            SERVE_REQUESTS.inc(
                "timeout" if timed_out else "ok", value=float(len(reqs))
            )
            choices = []
            for k, r in enumerate(reqs):
                ok = acked[id(r)]
                c = {"index": k, "tokens": list(r.output) if ok else []}
                if ok:
                    SERVE_TOKENS.inc(value=len(r.output))
                if r.logprobs > 0 and ok:
                    c["logprobs"] = _logprobs_payload(r)
                choices.append(c)
            code = 504 if timed_out else 200
            out = {"choices": choices}
            if timed_out:
                out["error"] = "generation timed out"
            return self._json(code, out)

        def _client_gone(self) -> bool:
            """True when the client socket is closed or half-closed (EOF
            or error on a zero-timeout peek).  Completion clients never
            send bytes mid-stream, so readable-with-EOF IS the
            disconnect signal; readable-with-data is left alone.  This
            is how a stream whose engine is between tokens notices the
            disconnect — the write path only surfaces a broken pipe
            when there is a token to write.  ``poll`` (not ``select``):
            select raises ValueError for fds >= FD_SETSIZE, which on a
            busy server (>1024 open fds) would read as a phantom
            disconnect and cancel healthy streams."""
            try:
                p = select.poll()
                p.register(
                    self.connection, select.POLLIN | select.POLLHUP
                )
                if not p.poll(0):
                    return False
                return self.connection.recv(1, socket_mod.MSG_PEEK) == b""
            except OSError:
                return True

        def _stream(self, reqs: list) -> None:
            # SSE: tokens are pushed from the ENGINE thread into a bounded
            # shared queue; this handler thread drains it to the socket,
            # so a slow client never blocks generation (the queue is
            # sized for every choice's whole response).  Events carry an
            # "index" field when n > 1 (single-choice streams keep the
            # legacy flat shape).
            n = len(reqs)
            q: "queue.Queue" = queue.Queue(
                maxsize=sum(r.max_new_tokens for r in reqs) + 2 * n
            )

            def make_on_token(k, r):
                def on_token(tok):
                    # runs on the ENGINE thread, after _emit appended the
                    # token's logprob entries — reading [-1] here is the
                    # documented ownership-safe window
                    if r.logprobs > 0:
                        q.put((k, tok, r.token_logprobs[-1],
                               r.top_logprobs[-1]))
                    else:
                        q.put((k, tok, None, None))
                return on_token

            for k, r in enumerate(reqs):
                r.on_token = make_on_token(k, r)
            t0 = time.monotonic()
            for r in reqs:
                engine.submit(r)
            # submit() validates synchronously — a rejected request gets
            # the same 400 the non-streaming path returns, not a 200
            # stream carrying an error event
            bad = [r for r in reqs if r.done.is_set() and r.error]
            if bad:
                for r in reqs:
                    r.cancel()
                code = _reject_code(bad[0].error)
                return self._json(code, {"error": bad[0].error})
            self.send_response(200, "OK")
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            # the serve.request span is on THIS thread's stack (the
            # _do_post with-block); flush markers land in the same trace
            sp = TRACER.current() or None
            first_flush = [True]
            flushes = [0]  # socket write+flush count (burst coalescing)

            def chunk_many(payloads: list) -> None:
                # burst drain: every queued event rides ONE HTTP chunk and
                # ONE flush — chunked encoding is transport framing and
                # SSE parses by blank lines, so coalescing is invisible to
                # clients while cutting syscalls from one-per-token to
                # one-per-burst when the engine outruns the socket
                data = b"".join(
                    f"data: {p}\n\n".encode() for p in payloads
                )
                self.wfile.write(
                    f"{len(data):x}\r\n".encode() + data + b"\r\n"
                )
                self.wfile.flush()
                flushes[0] += 1
                if first_flush[0]:
                    first_flush[0] = False
                    if sp is not None:
                        sp.event("sse_first_flush")

            def chunk(payload: str) -> None:
                chunk_many([payload])

            half_closed = [False]  # client did shutdown(SHUT_WR); legal

            def sse_ping() -> None:
                # SSE comment (": ..." line) — spec-ignored by clients;
                # used only to probe socket liveness after a read EOF
                data = b": ping\n\n"
                self.wfile.write(
                    f"{len(data):x}\r\n".encode() + data + b"\r\n"
                )
                self.wfile.flush()

            def event_json(item) -> str:
                k, tok, lp, top = item
                ev = {"token": tok}
                if n > 1:
                    ev["index"] = k
                if lp is not None:
                    ev["logprob"] = lp
                    ev["top_logprobs"] = [
                        {"id": t, "logprob": l} for t, l in top
                    ]
                return json.dumps(ev)

            sent = 0
            t_first_tok = t_last_tok = 0.0
            slo_meta_sent = False
            # pin the stream's trace while it lives: a long SSE
            # generation's engine.step spans must survive span pressure
            # from concurrent requests (FIFO eviction would drop this
            # request's history mid-flight; unpinned in finally)
            pinned_tid = sp.trace_id if sp is not None else ""
            if pinned_tid:
                TRACER.pin(pinned_tid)
            deadline = time.monotonic() + request_timeout
            try:
                while time.monotonic() < deadline:
                    try:
                        first = q.get(timeout=0.1)
                    except queue.Empty:
                        if all(r.done.is_set() for r in reqs) and q.empty():
                            break
                        if not half_closed[0] and self._client_gone():
                            # read-side EOF while IDLE (no token to write
                            # would ever surface a broken pipe).  EOF is
                            # ambiguous: a full close (dead client) or a
                            # LEGAL half-close (shutdown(SHUT_WR), still
                            # reading).  Disambiguate with an SSE comment
                            # probe — invisible to clients, but a fully
                            # closed socket raises by the second write
                            # (the first may land in the send buffer
                            # before the RST comes back).
                            try:
                                sse_ping()
                                time.sleep(0.05)
                                sse_ping()
                                # half-closed but reading: keep streaming
                                # and stop peeking (EOF is permanent)
                                half_closed[0] = True
                            except OSError:
                                raise BrokenPipeError(
                                    "client disconnected"
                                ) from None
                        continue
                    if not slo_meta_sent:
                        slo_meta_sent = True
                        t_first_tok = time.monotonic()
                        # SSE comment (spec-ignored by clients): hands
                        # the router the queue wait for its journey
                        # record — stream headers went out before
                        # admission, so a header can't carry it
                        qw = _queue_wait_ms(reqs[0])
                        if qw is not None:
                            meta = (
                                f': slo {{"queue_ms": {qw:.3f}}}\n\n'
                            ).encode()
                            self.wfile.write(
                                f"{len(meta):x}\r\n".encode()
                                + meta + b"\r\n"
                            )
                    events = _drain_burst(q, first)
                    chunk_many([event_json(e) for e in events])
                    sent += len(events)
                    t_last_tok = time.monotonic()
                timed_out = not all(r.done.is_set() for r in reqs)
                if timed_out:
                    # timed out mid-generation: tell the client the truth
                    # (no clean [DONE]) and cancel engine-side so slots
                    # and KV pages come back at the next chunk boundary
                    for r in reqs:
                        r.cancel()
                    SERVE_REQUESTS.inc("timeout", value=float(n))
                    chunk(json.dumps({"error": "generation timed out"}))
                else:
                    for k, r in enumerate(reqs):
                        if r.error:
                            SERVE_REQUESTS.inc("error")
                            ev = {"error": r.error}
                            if n > 1:
                                ev["index"] = k
                            chunk(json.dumps(ev))
                        else:
                            SERVE_REQUESTS.inc("ok")
                chunk("[DONE]")
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                # dead client: stop generating for it — the engine checks
                # the cancel flag at every chunk boundary
                for r in reqs:
                    r.cancel()
                SERVE_REQUESTS.inc("cancelled", value=float(n))
                log.info("stream client disconnected after %d tokens", sent)
            finally:
                e2e = time.monotonic() - t0
                SERVE_LATENCY.observe(value=e2e)
                SERVE_TOKENS.inc(value=sent)
                if sp is not None:
                    sp.set_attr("sse_chunks", sent)
                    sp.set_attr("sse_flushes", flushes[0])
                if pinned_tid:
                    TRACER.unpin(pinned_tid)
                self._replica_journey(
                    sp,
                    ok=all(
                        r.done.is_set() and not r.error for r in reqs
                    ),
                    e2e_ms=e2e * 1000,
                    queue_ms=_queue_wait_ms(reqs[0]),
                    tokens=sent,
                    ttft_ms=(
                        round((t_first_tok - t0) * 1000, 3)
                        if t_first_tok else None
                    ),
                    tpot_ms=(
                        round(
                            (t_last_tok - t_first_tok) * 1000
                            / (sent - 1), 3,
                        )
                        if sent > 1 and t_last_tok > t_first_tok else None
                    ),
                )

    return InferenceHandler


def _reject_code(error: str) -> int:
    """Map structured engine rejections to retryable statuses: draining →
    503 (pod going away; retry elsewhere), queue full → 429 (back off);
    everything else is a client error (400)."""
    if error == DRAINING_ERROR:
        return 503
    if error == QUEUE_FULL_ERROR:
        return 429
    return 400


def drain(
    loop: EngineLoop, timeout: float = 30.0, poll: float = 0.05
) -> bool:
    """Graceful drain (the k8s SIGTERM contract): stop admitting new
    requests (submit → DRAINING_ERROR → 503, /healthz → 503 so the
    Service pulls this pod), wait for every in-flight request to finish
    — engine-side via the LOOP thread's own idle observation (no race
    against queue→slot transitions), then HTTP-side until handler
    threads have flushed their responses (slow streaming clients).
    Returns True when fully drained, False on timeout (the caller
    decides whether to hard-stop).  The engine loop must keep running
    while draining."""
    engine = loop.engine
    engine.draining = True
    engine._work.set()  # wake a parked loop so it observes the drain
    deadline = time.monotonic() + timeout
    engine_idle = loop.drained.wait(max(0.0, deadline - time.monotonic()))
    while time.monotonic() < deadline and loop.http_inflight > 0:
        time.sleep(poll)
    # final re-check: a timeout=0 call on an idle server must say True
    return (
        engine_idle
        or (
            not any(s is not None for s in engine.slots)
            and engine.queue.empty()
        )
    ) and loop.http_inflight == 0


def serve_inference(
    engine: InferenceEngine,
    port: int = 8000,
    host: str = "0.0.0.0",
    request_timeout: float = 300.0,
) -> tuple[ThreadingHTTPServer, EngineLoop]:
    """Start the engine loop + HTTP server (both daemonized); returns them
    so the caller owns shutdown: ``server.shutdown(); loop.stop()``."""
    loop = EngineLoop(engine).start()
    server = ThreadingHTTPServer(
        (host, port), make_handler(loop, request_timeout)
    )
    t = threading.Thread(
        target=server.serve_forever, name="inference-http", daemon=True
    )
    t.start()
    log.info("inference server on %s:%d", host, server.server_address[1])
    return server, loop
