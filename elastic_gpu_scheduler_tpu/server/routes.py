"""HTTP routing for the extender webhook.

Reference: pkg/routes/routes.go.  Paths kept wire-compatible:

    POST /scheduler/filter      → Predicate
    POST /scheduler/priorities  → Prioritize
    POST /scheduler/bind        → Bind
    POST /scheduler/preemption  → Preemption (net-new; reference has no
                                  preemptVerb — README.md:47-89)
    GET  /scheduler/status      → per-node chip state dump (routes.go:197-218)
    GET  /version               → version JSON (routes.go:165-171)
    GET  /healthz               → liveness
    GET  /metrics               → Prometheus text (net-new; reference has none)
    GET  /debug/stacks          → all-thread stack dump (pprof analogue;
                                  reference mounts net/http/pprof, pprof.go)
    GET  /debug/pprof/mutex     → lock wait-time summary (scheduler/gang)
    GET  /debug/pprof/trace     → per-thread execution timeline, Chrome
                                  trace-event JSON (runtime-trace slot)
    GET  /debug/pprof/heap      → tracemalloc heap report; ?diff=1 = growth
                                  since previous call (leak probe; reference
                                  heap/allocs endpoints, pprof.go:10-64)

Deviation (SURVEY §5 quirk not replicated): the reference's prioritize route
panics on malformed input (routes.go:98,103,109); here every route returns a
structured error with a 4xx/5xx status instead.
"""

from __future__ import annotations

import json
import logging
import queue
import sys
import threading
import time
import traceback
from http.server import ThreadingHTTPServer
from typing import Callable, Optional

from .. import __version__
from ..faultinject import FAULTS
from ..journal import JOURNAL
from ..k8s.extender import (
    ExtenderArgs,
    ExtenderBindingArgs,
    ExtenderPreemptionArgs,
)
from ..metrics import LOCK_WAIT, REGISTRY, VERB_LATENCY, VERB_TOTAL
from ..profile import PROFILER
from ..slo import SLO
from ..tracing import AUDIT, TRACER
from ..utils.tpuprobe import RELAY_MONITOR
from .handlers import Bind, Predicate, Preemption, Prioritize

log = logging.getLogger("tpu-scheduler")

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            429: "Too Many Requests",
            405: "Method Not Allowed", 500: "Internal Server Error",
            503: "Service Unavailable", 504: "Gateway Timeout"}


def sample_cpu_profile(seconds: float, interval: float = 0.005) -> str:
    """Statistical all-thread CPU profile (py-spy style, stdlib-only): sample
    every thread's stack via ``sys._current_frames`` and aggregate collapsed
    stacks by count.  The reference mounts net/http/pprof for this job
    (pprof.go:10-64); cProfile can't see other threads, sampling can."""
    counts: dict[str, int] = {}
    me = threading.get_ident()
    seconds = min(max(seconds, 0.1), 30.0)
    end = time.monotonic() + seconds
    n = 0
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = []
            f = frame
            while f is not None and len(stack) < 50:
                code = f.f_code
                stack.append(
                    f"{code.co_filename.rsplit('/', 1)[-1]}:"
                    f"{f.f_lineno}:{code.co_name}"
                )
                f = f.f_back
            key = ";".join(reversed(stack))
            counts[key] = counts.get(key, 0) + 1
        n += 1
        time.sleep(interval)
    lines = [
        f"# {n} sampling rounds over {seconds}s (interval {interval * 1e3:.0f}ms); "
        "collapsed stacks, hottest first"
    ]
    for k, v in sorted(counts.items(), key=lambda kv: -kv[1])[:300]:
        lines.append(f"{v} {k}")
    return "\n".join(lines) + "\n"


def execution_trace(seconds: float, interval: float = 0.002) -> str:
    """Per-thread execution timeline in Chrome trace-event JSON (open in
    Perfetto / chrome://tracing) — the runtime-trace slot of the
    reference's pprof mount (pprof.go:10-64 serves /debug/pprof/trace).

    Sampling-based like the CPU profile, but shaped as a TIMELINE: each
    thread gets a lane of complete events, one span per contiguous run
    of the same executing function, so lock convoys / phase structure /
    idle gaps are visible in time rather than aggregated away."""
    me = threading.get_ident()
    names = {t.ident: t.name for t in threading.enumerate()}
    seconds = min(max(seconds, 0.1), 10.0)
    events: list[dict] = []
    open_spans: dict[int, tuple[str, float]] = {}
    t0 = time.monotonic()
    end = t0 + seconds

    def close(tid: int, sig: str, start_us: float, now_us: float) -> None:
        events.append({
            "name": sig, "ph": "X", "ts": round(start_us, 1),
            "dur": round(max(now_us - start_us, 1.0), 1),
            "pid": 1, "tid": tid,
        })

    while time.monotonic() < end:
        now_us = (time.monotonic() - t0) * 1e6
        frames = sys._current_frames()
        for tid, frame in frames.items():
            if tid == me:
                continue
            code = frame.f_code
            sig = (
                f"{code.co_name} "
                f"({code.co_filename.rsplit('/', 1)[-1]})"
            )
            cur = open_spans.get(tid)
            if cur is None:
                open_spans[tid] = (sig, now_us)
            elif cur[0] != sig:
                close(tid, cur[0], cur[1], now_us)
                open_spans[tid] = (sig, now_us)
        for tid in list(open_spans):
            if tid not in frames:  # thread exited: close its span
                sig, st = open_spans.pop(tid)
                close(tid, sig, st, now_us)
        time.sleep(interval)
    now_us = (time.monotonic() - t0) * 1e6
    for tid, (sig, st) in open_spans.items():
        close(tid, sig, st, now_us)
    for tid, name in names.items():
        if tid is not None and tid != me:
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": name},
            })
    return json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})


_PARK_NAMES = {
    # threading.py / queue.py / selectors.py primitives a parked thread's
    # INNERMOST frames sit in; the co_name → blocking-kind map drives the
    # per-site attribution below
    "wait": "condition",
    "wait_for": "condition",
    "get": "queue",
    "put": "queue",
    "join": "join",
    "acquire": "lock",
    "select": "io",
    "poll": "io",
}


def sample_block_profile(seconds: float, interval: float = 0.005) -> str:
    """Block-profile analogue (the reference mounts Go's block profile,
    pprof.go:10-64): sample every thread and attribute time spent PARKED
    on queues/condition variables/locks/IO to the innermost application
    frame that called into the wait primitive.

    The mutex profile (/debug/pprof/mutex) only sees TimedLock waits;
    this sees every ``queue.Queue.get``, ``Condition.wait``, executor
    future wait and selector poll — the gang barrier, the controller
    workqueue, the HTTP worker pool and the engine loop all park there."""
    import queue as _queue
    import selectors as _selectors

    park_files = {
        threading.__file__,
        _queue.__file__,
        _selectors.__file__,
    }
    me = threading.get_ident()
    seconds = min(max(seconds, 0.1), 30.0)
    counts: dict[tuple[str, str], int] = {}
    rounds = 0
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            # walk inner → outer: find the innermost park primitive, then
            # the first frame OUTSIDE the primitive files = the park site
            f = frame
            kind = None
            depth = 0
            while f is not None and depth < 50:
                code = f.f_code
                if code.co_filename in park_files:
                    k = _PARK_NAMES.get(code.co_name)
                    if k is not None:
                        kind = k
                elif kind is not None:
                    site = (
                        f"{code.co_filename.rsplit('/', 1)[-1]}:"
                        f"{f.f_lineno}:{code.co_name}"
                    )
                    counts[(site, kind)] = counts.get((site, kind), 0) + 1
                    break
                f = f.f_back
                depth += 1
        rounds += 1
        time.sleep(interval)
    lines = [
        f"# block profile: {rounds} sampling rounds over {seconds}s "
        f"(interval {interval * 1e3:.0f}ms); samples blocked-kind site, "
        "most-parked first"
    ]
    for (site, kind), n in sorted(counts.items(), key=lambda kv: -kv[1])[:200]:
        lines.append(f"{n} {kind} {site}")
    return "\n".join(lines) + "\n"


_DEBUG_INDEX = """\
<html><head><title>/debug/</title></head><body>
<h2>tpu-elastic-scheduler debug index</h2>
<p>Profiles (the reference mounts Go's net/http/pprof index; these are
the Python analogues):</p>
<ul>
<li><a href="/debug/pprof/profile?seconds=2">/debug/pprof/profile</a>
 — sampling CPU profile, collapsed stacks (?seconds=N)</li>
<li><a href="/debug/pprof/heap">/debug/pprof/heap</a>
 — tracemalloc live-allocation sites (?diff=1 → growth since last call)</li>
<li><a href="/debug/pprof/mutex">/debug/pprof/mutex</a>
 — TimedLock wait-time summary (scheduler/gang locks)</li>
<li><a href="/debug/pprof/block?seconds=2">/debug/pprof/block</a>
 — park-site profile: threads blocked on queues/conditions/locks/IO</li>
<li><a href="/debug/pprof/trace?seconds=1">/debug/pprof/trace</a>
 — per-thread execution timeline, Chrome trace-event JSON</li>
<li><a href="/debug/stacks">/debug/stacks</a> — all-thread stack dump</li>
</ul>
<p>Scheduling provenance:</p>
<ul>
<li><a href="/traces">/traces</a> — recent scheduling traces
 (?trace=ID for one trace, ?format=chrome for Perfetto export)</li>
<li>/debug/schedule/&lt;namespace&gt;/&lt;pod&gt;
 — per-node filter verdicts, scores and the bind decision for one pod
 (?format=json adds the pod's journal sequence numbers)</li>
<li><a href="/debug/journal">/debug/journal</a>
 — flight-recorder state: rotation/fsync stats and the record tail
 (?n=N); offline replay via python -m elastic_gpu_scheduler_tpu.journal</li>
<li><a href="/debug/defrag">/debug/defrag</a>
 — defrag planner state + plan preview (?chips=N&amp;members=M simulates
 unblocking that gang shape); POST /defrag/run executes a round
 ({"dry_run": true} to simulate)</li>
<li><a href="/debug/profiles">/debug/profiles</a>
 — workload profiling observatory: per-class throughput/latency
 profiles, the (class, class) interference matrix, chip occupancy and
 the co-tenancy map (--profile-sample gates collection)</li>
<li><a href="/debug/fleet">/debug/fleet</a>
 — elastic serving fleet: replica set health/load, prefix-affinity hit
 rate, autoscaler policy + last decision, resize history
 (--fleet=router|auto starts it; the router's own port serves the same
 payload at /debug/fleet)</li>
<li><a href="/debug/policy">/debug/policy</a>
 — programmable policy plane: active/canary policies per verb, replay-
 gate results, canary decision counters + SLO watchdog state; POST
 /policy/load stages a candidate (compile → replay gate → canary),
 /policy/promote and /policy/rollback drive the state machine</li>
<li><a href="/debug/slo">/debug/slo</a>
 — fleet SLO plane: declared objectives, per-class sliding-window
 latency percentiles (TTFT/TPOT/e2e/queue/hop), error-budget burn
 rates, active breaches with exemplar trace ids, recent request
 journeys (POST /slo/load installs objectives; --slo-config /
 TPU_SLO_CONFIG at start)</li>
<li>/debug/trace/&lt;trace_id&gt;
 — one request end-to-end ACROSS processes: spans pulled from every
 replica's /traces (and this process's ring) merged in causal order —
 the resolution target of an SLO breach record's exemplar ids</li>
<li><a href="/debug/twin">/debug/twin</a>
 — digital twin: last time-warped simulation report (packing scores,
 simulated SLO burn, replay-invariant verdict); POST /twin/run launches
 a scenario ({"mode": "synthetic"|"recorded", "duration_s": N, ...} —
 recorded mode replays this process's own journal through the twin);
 offline CLI: python -m elastic_gpu_scheduler_tpu.twin</li>
<li><a href="/debug/federation">/debug/federation</a>
 — federated control plane: shard inventory (dead/alive, journal seq),
 cross-shard gang decision log, routing counters; the federation front
 door also serves GET /scheduler/status?summary=1 folded across every
 shard with per-shard staleness stamps</li>
<li><a href="/debug/relay">/debug/relay</a>
 — TPU probe-relay health (the tpu_relay_up gauge's source: last probe
 state, latency, failure detail; --relay-probe-interval starts it)</li>
<li><a href="/debug/leader">/debug/leader</a>
 — HA posture: leader-election state (identity, fenced, renew age),
 journal-shipping follower lag (--follow), in-flight verb count;
 GET /journal/stream serves the journal to followers</li>
<li><a href="/debug/faults">/debug/faults</a>
 — deterministic fault-injection plane: loaded plans, per-site call/fire
 counters; POST /faults/load installs a seeded plan, /faults/clear
 disables (chaos drills — see OPERATIONS.md)</li>
<li><a href="/metrics">/metrics</a> — Prometheus text exposition</li>
<li><a href="/scheduler/status">/scheduler/status</a>
 — per-node chip state dump</li>
</ul>
</body></html>
"""


def _parse_query(query: str) -> dict[str, str]:
    """?a=b&c=d → {a: b, c: d} with URL decoding; last value wins."""
    from urllib.parse import parse_qsl

    return dict(parse_qsl(query, keep_blank_values=True))


_heap_state: dict = {"snapshot": None}
_heap_lock = threading.Lock()


def heap_profile(top_n: int = 30, diff: bool = False) -> str:
    """tracemalloc-backed heap report (the reference mounts net/http/pprof's
    heap/allocs endpoints, pprof.go:10-64; this is the Python analogue).

    Plain call: top-N live allocation sites by size.  ``diff=True``:
    growth per site since the PREVIOUS /debug/pprof/heap call — the leak
    probe for a long-lived scheduler (the soak test asserts steady-state
    growth stays bounded).  Tracing starts lazily on first call: ~2x alloc
    overhead while on, zero when never requested."""
    import tracemalloc

    started_now = False
    if not tracemalloc.is_tracing():
        # 1 frame/allocation: every report groups by "lineno" (single
        # frame), so deeper stored stacks would only multiply overhead
        tracemalloc.start(1)
        started_now = True
    snap = tracemalloc.take_snapshot().filter_traces([
        tracemalloc.Filter(False, "<frozen importlib._bootstrap>"),
        tracemalloc.Filter(False, "<frozen importlib._bootstrap_external>"),
        tracemalloc.Filter(False, tracemalloc.__file__),
    ])
    cur, peak = tracemalloc.get_traced_memory()
    lines = [
        f"# tracemalloc: current={cur / 1024:.1f}KiB peak={peak / 1024:.1f}KiB"
        + (
            " (tracing just started; sites cover allocations from now on)"
            if started_now
            else ""
        )
    ]
    with _heap_lock:
        prev = _heap_state["snapshot"]
        _heap_state["snapshot"] = snap
    if diff and prev is not None:
        lines.append(
            "# growth since previous /debug/pprof/heap call, "
            "largest deltas first"
        )
        for st in snap.compare_to(prev, "lineno")[:top_n]:
            lines.append(
                f"{st.size_diff / 1024:+.1f}KiB ({st.count_diff:+d} blocks, "
                f"now {st.size / 1024:.1f}KiB) {st.traceback}"
            )
    else:
        lines.append("# top live allocation sites by size")
        for st in snap.statistics("lineno")[:top_n]:
            lines.append(
                f"{st.size / 1024:.1f}KiB ({st.count} blocks) {st.traceback}"
            )
    return "\n".join(lines) + "\n"


class _HTTPServer(ThreadingHTTPServer):
    """Threading server with an optional PRE-SPAWNED worker pool.

    Gang binds hold N concurrent connections at the barrier.  The stdlib
    spawns (and tears down) one thread per connection — for a 256-member
    gang that is ~45ms of thread creation plus Python 3.12 shutdown-lock
    churn on the commit's critical path.  With ``pool_size`` > 0, workers
    are created once at startup and connections are dispatched over a
    queue instead.
    """

    # stdlib default backlog of 5 resets connections under a 256-member gang
    request_queue_size = 1024

    def __init__(self, addr, handler_cls, pool_size: int = 0):
        super().__init__(addr, handler_cls)
        self._pool_size = pool_size
        self._conn_q: "queue.Queue" = queue.Queue()
        self._idle = pool_size
        self._idle_lock = threading.Lock()
        self._workers: list[threading.Thread] = []
        for i in range(pool_size):
            t = threading.Thread(
                target=self._worker, name=f"http-worker-{i}", daemon=True
            )
            t.start()
            self._workers.append(t)

    def _worker(self) -> None:
        while True:
            item = self._conn_q.get()
            if item is None:
                return
            request, client_address = item
            try:
                self.finish_request(request, client_address)
            except Exception:
                self.handle_error(request, client_address)
            finally:
                self.shutdown_request(request)
                with self._idle_lock:
                    self._idle += 1

    def process_request(self, request, client_address):
        # overflow to a per-connection thread when every pooled worker is
        # occupied (e.g. a gang larger than the pool parked at the barrier,
        # or many idle keep-alive clients) — the pool is an optimization and
        # must never become an admission limit.  Invariant: enqueued
        # connections never exceed workers free to take them (_idle is
        # decremented at enqueue time, incremented when a worker finishes
        # its connection).
        with self._idle_lock:
            dispatch_to_pool = self._pool_size > 0 and self._idle > 0
            if dispatch_to_pool:
                self._idle -= 1
        if dispatch_to_pool:
            self._conn_q.put((request, client_address))
        else:
            super().process_request(request, client_address)

    def server_close(self):
        for _ in self._workers:
            self._conn_q.put(None)
        super().server_close()
        # idle workers exit on the sentinel; join so a stopped server's pool
        # is fully gone (workers mid-connection are daemons and may outlive)
        for t in self._workers:
            t.join(timeout=0.5)


class ExtenderServer:
    def __init__(
        self,
        predicate: Predicate,
        prioritize: Prioritize,
        bind: Bind,
        status_fn: Callable[[], dict],
        preemption: Optional[Preemption] = None,
        host: str = "0.0.0.0",
        port: int = 39999,
        tls_cert: str = "",
        tls_key: str = "",
        workers: int = 0,  # >0: pre-spawned pool sized for gang concurrency
        leader_check=None,  # callable → bool; None = always the leader
        defrag=None,  # optional defrag.DefragPlanner (plan preview + run)
        fleet=None,  # optional fleet state provider (debug_state() dict)
        policy=None,  # optional policy.PolicyPlane (/policy/*, /debug/policy)
        elector=None,  # optional LeaderElector (/debug/leader)
        follower=None,  # optional journal.ship.JournalFollower (HA standby)
        assembler=None,  # optional slo.assembly.TraceAssembler
        federation=None,  # optional federation.FederationFrontDoor
    ):
        self.predicate = predicate
        self.prioritize = prioritize
        self.bind = bind
        self.status_fn = status_fn
        self.preemption = preemption
        self.defrag = defrag
        self.fleet = fleet
        self.policy = policy
        self.elector = elector
        self.follower = follower
        self.assembler = assembler
        self.federation = federation
        self.host = host
        self.port = port
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self.workers = workers
        self.leader_check = leader_check
        # in-flight mutation-verb accounting: the leader's step-down
        # fence (scheduler/leader.py) drains these before surrendering
        # the lease, so a verb that raced the fence commits (and
        # journals) while the lease is still ours — never concurrently
        # with a successor
        self._inflight = 0
        self._inflight_cond = threading.Condition(threading.Lock())
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def wait_verbs_idle(self, timeout_s: float = 5.0) -> bool:
        """Block until no mutation verb is in flight (the step-down
        drain).  Returns False on timeout — the step-down proceeds
        anyway (bounded: a hung handler must not pin the lease)."""
        deadline = time.monotonic() + timeout_s
        with self._inflight_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cond.wait(timeout=remaining)
        return True

    def _maybe_wrap_tls(self, httpd) -> None:
        """Serve HTTPS when a cert/key pair is configured (the extender
        config's enableHTTPS option; the reference is HTTP-only)."""
        if not self.tls_cert:
            return
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.tls_cert, self.tls_key or None)
        httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)

    # -- request plumbing ----------------------------------------------------
    #
    # The handler is a hand-rolled HTTP/1.1 parser, not BaseHTTPRequestHandler:
    # the stdlib parses headers through the email package and formats a Date
    # header per response, which alone costs ~35ms for a 256-member gang's
    # bind burst.  The wire format is unchanged (persistent connections,
    # Content-Length framing) — kube-scheduler's extender client and
    # http.client both speak it.

    def _route_get(self, path: str, query: str = "") -> tuple[int, bytes, str]:
        if path == "/version":
            return 200, json.dumps({"version": __version__}).encode(), "application/json"
        if path == "/healthz":
            # readiness IS leadership under HA: standbys answer 503 so the
            # Service's readiness probe routes kube-scheduler to the leader
            if self.leader_check is not None and not self.leader_check():
                return 503, b"standby (not leader)", "text/plain"
            return 200, b"ok", "text/plain"
        if path == "/metrics":
            return 200, REGISTRY.expose().encode(), "text/plain"
        if path == "/scheduler/status":
            try:
                params = _parse_query(query)
                if params.get("summary") in ("1", "true", "yes"):
                    # fleet-scale mode: aggregate counts + top-K
                    # fragmented nodes, never the full per-node chip dict
                    # (10k nodes × ~4 chips of JSON per poll otherwise).
                    # Closures that predate the summary signature fall
                    # back to the classic dump.
                    try:
                        top_k = max(1, int(params.get("top_k", "10")))
                    except ValueError:
                        top_k = 10
                    gens = params.get("generations") in ("1", "true", "yes")
                    try:
                        payload = self.status_fn(
                            summary=True, top_k=top_k, generations=gens
                        )
                    except TypeError:
                        payload = self.status_fn()
                else:
                    payload = self.status_fn()
                return 200, json.dumps(payload).encode(), "application/json"
            except Exception as e:
                return 500, json.dumps({"error": str(e)}).encode(), "application/json"
        if path == "/traces":
            from ..tracing import traces_response

            return (
                200,
                json.dumps(
                    traces_response(_parse_query(query)), indent=1
                ).encode(),
                "application/json",
            )
        if path.startswith("/debug/schedule/"):
            pod_key = path[len("/debug/schedule/"):]
            if "/" not in pod_key:
                pod_key = f"default/{pod_key}"
            params = _parse_query(query)
            if params.get("format") == "json":
                # machine-readable verdicts alongside the human text, with
                # the pod's flight-recorder sequence numbers when the
                # journal is on (cross-link to /debug/journal + offline
                # replay)
                entry = AUDIT.get(pod_key) or {
                    "pod": pod_key, "trace_id": "", "records": [],
                }
                entry["journal"] = {
                    "enabled": JOURNAL.enabled,
                    "seqs": JOURNAL.pod_seqs(pod_key),
                }
                return (
                    200, json.dumps(entry, indent=1).encode(),
                    "application/json",
                )
            text = AUDIT.explain(pod_key)
            if JOURNAL.enabled:
                seqs = JOURNAL.pod_seqs(pod_key)
                if seqs:
                    text += (
                        f"journal seqs: {seqs}  (see /debug/journal and "
                        "python -m elastic_gpu_scheduler_tpu.journal)\n"
                    )
            return 200, text.encode(), "text/plain"
        if path == "/debug/defrag":
            if self.defrag is None:
                return (
                    404,
                    json.dumps({"error": "defrag planner not configured"}).encode(),
                    "application/json",
                )
            params = _parse_query(query)
            out = self.defrag.status()
            # optional plan preview: ?chips=N[&members=M] simulates an
            # unblocking plan for that gang shape; bare GET previews a
            # threshold-compaction plan.  Pure simulation on clones —
            # live state is never touched, and the try-lock preview never
            # parks behind an executing round (in_flight:true instead).
            try:
                want = None
                if "chips" in params:
                    want = (
                        int(params["chips"]),
                        int(params.get("members", "1")),
                    )
                out["preview"] = self.defrag.preview(want=want)
            except Exception as e:
                out["preview_error"] = str(e)
            return 200, json.dumps(out, indent=1).encode(), "application/json"
        if path == "/debug/fleet":
            if self.fleet is None:
                return (
                    404,
                    json.dumps({"error": "fleet not configured "
                                         "(--fleet=router|auto)"}).encode(),
                    "application/json",
                )
            try:
                out = self.fleet.debug_state()
            except Exception as e:
                return (
                    500, json.dumps({"error": str(e)}).encode(),
                    "application/json",
                )
            return 200, json.dumps(out, indent=1).encode(), "application/json"
        if path == "/debug/profiles":
            # the workload-profiling observatory (profile/): per-class
            # profiles, interference matrix, co-tenancy.  Folding the
            # sample rings happens HERE, on the reader's thread — same
            # stance as the LazyGauge fragmentation scan.
            return (
                200,
                json.dumps(PROFILER.debug_state(), indent=1).encode(),
                "application/json",
            )
        if path == "/debug/slo":
            # the SLO plane: objectives, sliding-window percentiles,
            # burn rates, breaches + exemplars.  Folding happens HERE,
            # on the reader's thread (the /debug/profiles stance).
            return (
                200,
                json.dumps(SLO.debug_state(), indent=1).encode(),
                "application/json",
            )
        if path == "/debug/federation":
            # federated control plane: shard inventory, 2PC decision
            # log, routing counters (the front door's own port serves
            # the same payload; this mirror keeps one /debug/ index)
            if self.federation is None:
                return (
                    200,
                    json.dumps({"federated": False}).encode(),
                    "application/json",
                )
            return (
                200,
                json.dumps(
                    self.federation.debug_state(), indent=1
                ).encode(),
                "application/json",
            )
        if path == "/debug/twin":
            # digital twin: last scenario report (lazy import — the twin
            # package only loads when someone actually asks for it)
            from ..twin import debug_state as twin_debug_state

            return (
                200,
                json.dumps(twin_debug_state(), indent=1).encode(),
                "application/json",
            )
        if path.startswith("/debug/trace/"):
            # one request end-to-end across processes: the assembler
            # (when the fleet wired one) pulls every replica's /traces;
            # otherwise this process's own ring answers, causally
            # ordered either way
            tid = path[len("/debug/trace/"):]
            try:
                if self.assembler is not None:
                    payload = self.assembler.assemble(tid)
                else:
                    from ..slo.assembly import local_trace_payload

                    payload = local_trace_payload(tid)
            except Exception as e:
                return (
                    500, json.dumps({"error": str(e)}).encode(),
                    "application/json",
                )
            return (
                200, json.dumps(payload, indent=1).encode(),
                "application/json",
            )
        if path == "/debug/relay":
            return (
                200,
                json.dumps(RELAY_MONITOR.debug_state(), indent=1).encode(),
                "application/json",
            )
        if path == "/debug/policy":
            if self.policy is None:
                return (
                    404,
                    json.dumps({"error": "policy plane not configured"}).encode(),
                    "application/json",
                )
            try:
                out = self.policy.debug_state()
            except Exception as e:
                return (
                    500, json.dumps({"error": str(e)}).encode(),
                    "application/json",
                )
            return 200, json.dumps(out, indent=1).encode(), "application/json"
        if path == "/debug/journal":
            params = _parse_query(query)
            try:
                n = int(params.get("n", "50"))
            except ValueError:
                n = 50
            return (
                200,
                json.dumps(JOURNAL.debug_state(n), indent=1).encode(),
                "application/json",
            )
        if path == "/journal/stream":
            return self._route_journal_stream(query)
        if path == "/debug/leader":
            # HA posture of THIS replica: elector state (when
            # --leader-elect), shipping-follower state (when --follow),
            # and the verb gate's current answer — the first stop of the
            # failover runbook
            out: dict = {
                "leader_elect": self.elector is not None,
                "leader": (
                    self.leader_check() if self.leader_check is not None
                    else True
                ),
                "inflight_verbs": self._inflight,
            }
            if self.elector is not None:
                out["elector"] = self.elector.debug_state()
            if self.follower is not None:
                out["follower"] = self.follower.debug_state()
            return 200, json.dumps(out, indent=1).encode(), "application/json"
        if path == "/debug/faults":
            return (
                200,
                json.dumps(FAULTS.debug_state(), indent=1).encode(),
                "application/json",
            )
        if path in ("/debug", "/debug/", "/debug/pprof", "/debug/pprof/"):
            return 200, _DEBUG_INDEX.encode(), "text/html"
        if path == "/debug/pprof/block":
            params = _parse_query(query)
            try:
                secs = float(params.get("seconds", "2"))
            except ValueError:
                secs = 2.0
            return 200, sample_block_profile(secs).encode(), "text/plain"
        if path == "/debug/stacks":
            frames = sys._current_frames()
            out = []
            for tid, frame in frames.items():
                out.append(f"--- thread {tid} ---")
                out.extend(traceback.format_stack(frame))
            return 200, "".join(out).encode(), "text/plain"
        if path == "/debug/pprof/profile":
            params = _parse_query(query)
            try:
                secs = float(params.get("seconds", "2"))
            except ValueError:
                secs = 2.0
            return 200, sample_cpu_profile(secs).encode(), "text/plain"
        if path == "/debug/pprof/trace":
            # per-thread execution timeline, Chrome trace-event JSON
            # (the runtime-trace pprof slot; open in Perfetto)
            params = _parse_query(query)
            try:
                secs = float(params.get("seconds", "1"))
            except ValueError:
                secs = 1.0
            return 200, execution_trace(secs).encode(), "application/json"
        if path == "/debug/pprof/mutex":
            # lock-contention profile (reference mounts Go's mutex/block
            # profiles, pkg/routes/pprof.go:10-64): wait-time summary of
            # the TimedLock-instrumented scheduler/gang locks
            return (
                200,
                json.dumps(LOCK_WAIT.summary(), indent=1).encode(),
                "application/json",
            )
        if path == "/debug/pprof/heap":
            params = _parse_query(query)
            try:
                top = int(params.get("top", "30"))
            except ValueError:
                top = 30
            diff = params.get("diff", "0") not in ("0", "", "false")
            try:
                return 200, heap_profile(top, diff).encode(), "text/plain"
            except Exception as e:
                return 500, f"heap profile failed: {e}".encode(), "text/plain"
        return 404, json.dumps({"error": f"no route {path}"}).encode(), "application/json"

    def _route_post(self, path: str, raw: bytes, traceparent: str = ""):
        if path.startswith("/faults/"):
            # fault-plane control is TEST infrastructure and must reach
            # standbys too (chaos drills fault the follower's sites) —
            # the only POST surface outside the leader gate
            return self._route_faults(path, raw)
        # count the request in-flight BEFORE the leader check: the
        # step-down drain (wait_verbs_idle) must never observe zero
        # while a handler that passed the check is still running —
        # check-then-count would leave exactly that window
        with self._inflight_cond:
            self._inflight += 1
        try:
            if self.leader_check is not None and not self.leader_check():
                # a standby (or a fencing leader mid-step-down) must not
                # mutate or answer from possibly-stale caches; the 503
                # carries Retry-After so kube-scheduler/executors retry
                # the leaderless window with a floor instead of
                # hammering — never a silent drop
                VERB_TOTAL.inc(path.rsplit("/", 1)[-1], "not_leader")
                return (
                    503, b'{"Error": "not the leader"}', "application/json",
                    {"Retry-After": "1"},
                )
            return self._route_post_inner(path, raw, traceparent)
        finally:
            with self._inflight_cond:
                self._inflight -= 1
                if self._inflight == 0:
                    self._inflight_cond.notify_all()

    def _route_post_inner(
        self, path: str, raw: bytes, traceparent: str = ""
    ) -> tuple[int, bytes, str]:
        if path == "/defrag/run":
            return self._route_defrag_run(raw)
        if path == "/twin/run":
            return self._route_twin_run(raw)
        if path.startswith("/policy/"):
            return self._route_policy(path, raw)
        if path == "/slo/load":
            return self._route_slo_load(raw)
        # route existence FIRST: unknown paths are 404s regardless of
        # body, and metric labels only ever come from this fixed verb
        # set (an attacker cycling random paths must not grow /metrics)
        known = {
            "/scheduler/filter", "/scheduler/priorities", "/scheduler/bind",
        }
        if self.preemption is not None:
            known.add("/scheduler/preemption")
        if path not in known:
            return (
                404, json.dumps({"error": f"no route {path}"}).encode(),
                "application/json",
            )
        verb = path.rsplit("/", 1)[-1]
        try:
            body = json.loads(raw or b"{}")
        except (ValueError, json.JSONDecodeError):
            VERB_TOTAL.inc(verb, "bad_request")
            return 400, b'{"Error": "malformed JSON body"}', "application/json"
        if not isinstance(body, dict):
            # parses but isn't an object ([], null, 42): a structured 400,
            # never a 500 from from_dict choking downstream
            VERB_TOTAL.inc(verb, "bad_request")
            return (
                400, b'{"Error": "body must be a JSON object"}',
                "application/json",
            )
        def merge_tp(args):
            # HTTP-header form of the W3C trace context; an explicit body
            # Traceparent wins (one precedence rule, applied per verb)
            if traceparent and not args.traceparent:
                args.traceparent = traceparent
            return args

        if path == "/scheduler/filter":
            # the nodeCacheCapable=false (Nodes-list) form is refused by
            # Predicate.handle itself with the reference's 200+Error shape
            # (routes.go:59-64) — no route-level special case needed
            args, err = self._parse("filter", ExtenderArgs.from_dict, body)
            if err is not None:
                return err
            args = merge_tp(args)
            return self._verb(
                "filter", lambda: self.predicate.handle(args).to_dict()
            )
        if path == "/scheduler/priorities":
            args, err = self._parse(
                "priorities", ExtenderArgs.from_dict, body
            )
            if err is not None:
                return err
            args = merge_tp(args)
            if args.node_names is None:
                # nodeCacheCapable=false form: the reference PANICS here
                # (routes.go:98,103 — SURVEY quirk not replicated);
                # structured 400 instead
                VERB_TOTAL.inc("priorities", "nodes_form_rejected")
                return 400, json.dumps({
                    "Error": "priorities requires the nodeCacheCapable=true "
                             "NodeNames form",
                }).encode(), "application/json"
            return self._verb("priorities", lambda: [
                hp.to_dict() for hp in self.prioritize.handle(args)
            ])
        if path == "/scheduler/bind":
            args, err = self._parse(
                "bind", ExtenderBindingArgs.from_dict, body
            )
            if err is not None:
                return err
            args = merge_tp(args)
            return self._verb(
                "bind", lambda: self.bind.handle(args).to_dict()
            )
        # path == "/scheduler/preemption" (membership checked above)
        args, err = self._parse(
            "preemption", ExtenderPreemptionArgs.from_dict, body
        )
        if err is not None:
            return err
        args = merge_tp(args)
        return self._verb(
            "preemption", lambda: self.preemption.handle(args).to_dict()
        )

    def _route_twin_run(self, raw: bytes) -> tuple[int, bytes, str]:
        """POST /twin/run — run a digital-twin scenario and return its
        report.  Body: TwinScenario fields, all optional ({"mode":
        "synthetic"|"recorded", "duration_s": N, "seed": N, ...}).
        ``recorded`` mode replays this process's own journal through the
        twin; the run builds fresh instances only, so live scheduler
        state, journal sequence and metrics are untouched (the
        tests/test_twin.py isolation guarantee)."""
        try:
            body = json.loads(raw or b"{}")
        except (ValueError, json.JSONDecodeError):
            return 400, b'{"Error": "malformed JSON body"}', "application/json"
        if not isinstance(body, dict):
            return (
                400, b'{"Error": "body must be a JSON object"}',
                "application/json",
            )
        # lazy import: the twin package loads only when a run is asked for
        from ..journal import JOURNAL, read_journal
        from ..twin import TwinScenario, run_scenario

        try:
            scenario = TwinScenario.from_dict(body)
        except (KeyError, TypeError, ValueError) as e:
            return (
                400, json.dumps({"Error": f"bad scenario: {e}"}).encode(),
                "application/json",
            )
        events = None
        if scenario.mode == "recorded":
            # a closed journal keeps its old dir attribute — require a
            # LIVE journal, not a stale path from a previous configure
            if not JOURNAL.enabled or JOURNAL.dir is None:
                return (
                    409,
                    json.dumps({
                        "Error": "recorded mode needs a journal; start "
                        "the scheduler with --journal-dir or run a "
                        "synthetic scenario",
                    }).encode(),
                    "application/json",
                )
            JOURNAL.flush()
            events = read_journal(JOURNAL.dir)
        try:
            report = run_scenario(scenario, events=events)
            return 200, json.dumps(report, indent=1).encode(), "application/json"
        except ValueError as e:
            # scenario/recording mismatch (e.g. a journal with no binds
            # to fit a model from) — the caller's problem, not a crash
            return (
                409, json.dumps({"Error": str(e)}).encode(),
                "application/json",
            )
        except Exception as e:
            log.exception("twin run failed")
            return (
                500, json.dumps({"error": str(e)}).encode(),
                "application/json",
            )

    def _route_defrag_run(self, raw: bytes) -> tuple[int, bytes, str]:
        """POST /defrag/run — run one defrag round.  Body (all optional):
        {"dry_run": bool, "chips": N, "members": M}.  ``dry_run`` plans
        on clones and returns the plan without executing; execution is
        refused in ``off`` mode (409) so a misfired curl cannot migrate
        workloads the operator declared immovable."""
        if self.defrag is None:
            return (
                404,
                json.dumps({"error": "defrag planner not configured"}).encode(),
                "application/json",
            )
        try:
            body = json.loads(raw or b"{}")
        except (ValueError, json.JSONDecodeError):
            return 400, b'{"Error": "malformed JSON body"}', "application/json"
        if not isinstance(body, dict):
            return (
                400, b'{"Error": "body must be a JSON object"}',
                "application/json",
            )
        dry_run = bool(body.get("dry_run", False))
        want = None
        if body.get("chips"):
            try:
                want = (int(body["chips"]), int(body.get("members", 1)))
            except (TypeError, ValueError):
                return (
                    400, b'{"Error": "chips/members must be integers"}',
                    "application/json",
                )
        if not dry_run and self.defrag.mode == "off":
            return (
                409,
                json.dumps({
                    "Error": "defrag mode is off; rerun with dry_run or "
                    "start the scheduler with --defrag=observe|auto",
                }).encode(),
                "application/json",
            )
        try:
            result = self.defrag.run_round(want=want, dry_run=dry_run)
            return 200, json.dumps(result, indent=1).encode(), "application/json"
        except Exception as e:
            log.exception("defrag run failed")
            return (
                500, json.dumps({"Error": f"defrag: {e}"}).encode(),
                "application/json",
            )

    def _route_policy(self, path: str, raw: bytes) -> tuple[int, bytes, str]:
        """Policy-plane control surface:

        POST /policy/load      {"name", "verb", "expr", "canary_pct"?,
                               "tolerance"?, "budget"?, "skip_gate"?,
                               "translation_invariant"?,
                               "whole_chip_compact_first"?}
                               → compile, replay-gate against the live
                               journal, stage as canary (409 when the
                               gate blocks a worse candidate)
        POST /policy/promote   {"verb"} → canary becomes active
        POST /policy/rollback  {"verb", "reason"?} → drop candidate or
                               active policy, restore the built-in

        Introspection lives at GET /debug/policy."""
        if self.policy is None:
            return (
                404,
                json.dumps({"error": "policy plane not configured"}).encode(),
                "application/json",
            )
        try:
            body = json.loads(raw or b"{}")
        except (ValueError, json.JSONDecodeError):
            return 400, b'{"Error": "malformed JSON body"}', "application/json"
        if not isinstance(body, dict):
            return (
                400, b'{"Error": "body must be a JSON object"}',
                "application/json",
            )
        try:
            if path == "/policy/load":
                for req_field in ("name", "verb", "expr"):
                    if not body.get(req_field):
                        return (
                            400,
                            json.dumps({
                                "Error": f"missing field {req_field!r}"
                            }).encode(),
                            "application/json",
                        )
                result = self.policy.load(
                    name=str(body["name"]),
                    verb=str(body["verb"]),
                    expr=str(body["expr"]),
                    canary_pct=float(body.get("canary_pct", 10.0)),
                    tolerance=float(body.get("tolerance", 0.02)),
                    budget=int(body.get("budget", 512)),
                    translation_invariant=bool(
                        body.get("translation_invariant", False)
                    ),
                    whole_chip_compact_first=bool(
                        body.get("whole_chip_compact_first", False)
                    ),
                    skip_gate=bool(body.get("skip_gate", False)),
                )
                code = 409 if result.get("state") == "blocked" else 200
                return (
                    code, json.dumps(result, indent=1).encode(),
                    "application/json",
                )
            if path == "/policy/promote":
                result = self.policy.promote(str(body.get("verb", "score")))
                return (
                    200, json.dumps(result, indent=1).encode(),
                    "application/json",
                )
            if path == "/policy/rollback":
                result = self.policy.rollback(
                    str(body.get("verb", "score")),
                    reason=str(body.get("reason", "operator")),
                )
                return (
                    200, json.dumps(result, indent=1).encode(),
                    "application/json",
                )
            return (
                404, json.dumps({"error": f"no route {path}"}).encode(),
                "application/json",
            )
        except (ValueError, TypeError) as e:
            # compile errors, unknown verbs/names, and wrong-typed body
            # fields (canary_pct: [10]) — malformed client input must
            # never surface as a 500 (the _parse rule)
            return (
                400, json.dumps({"Error": str(e)}).encode(),
                "application/json",
            )
        except Exception as e:
            log.exception("policy route failed")
            return (
                500, json.dumps({"Error": f"policy: {e}"}).encode(),
                "application/json",
            )

    def _route_slo_load(self, raw: bytes) -> tuple[int, bytes, str]:
        """POST /slo/load — install per-class SLO objectives::

            {"window_short_s": 60, "window_long_s": 300,
             "burn_threshold": 1.0,
             "classes": {"serve": {"ttft_p95_ms": 200,
                                   "e2e_p99_ms": 2000,
                                   "availability": 0.99}}}

        Replaces ALL objectives; the load is journaled as an ``slo``
        annotation.  Introspection at GET /debug/slo."""
        try:
            body = json.loads(raw or b"{}")
        except (ValueError, json.JSONDecodeError):
            return 400, b'{"Error": "malformed JSON body"}', "application/json"
        if not isinstance(body, dict):
            return (
                400, b'{"Error": "body must be a JSON object"}',
                "application/json",
            )
        try:
            summary = SLO.load_config(body)
        except (ValueError, TypeError) as e:
            return (
                400, json.dumps({"Error": str(e)}).encode(),
                "application/json",
            )
        return (
            200,
            json.dumps({
                "ok": True,
                "objectives": summary,
                "window_short_s": SLO.window_short_s,
                "window_long_s": SLO.window_long_s,
                "burn_threshold": SLO.burn_threshold,
            }, indent=1).encode(),
            "application/json",
        )

    def _route_faults(self, path: str, raw: bytes) -> tuple[int, bytes, str]:
        """Fault-plane control (deterministic chaos, faultinject/):

        POST /faults/load   {"seed": N, "plans": [{site, kind, p, nth,
                            count, delay_s}, ...]} — replace ALL plans
                            (an empty plan list disables)
        POST /faults/clear  disable every plan

        Introspection at GET /debug/faults.  Served on standbys too —
        chaos drills fault follower-side sites."""
        if path == "/faults/clear":
            FAULTS.clear()
            return (
                200, json.dumps(FAULTS.debug_state()).encode(),
                "application/json",
            )
        if path != "/faults/load":
            return (
                404, json.dumps({"error": f"no route {path}"}).encode(),
                "application/json",
            )
        try:
            FAULTS.load_json((raw or b"{}").decode())
        except (ValueError, json.JSONDecodeError) as e:
            return (
                400, json.dumps({"Error": f"bad fault plan: {e}"}).encode(),
                "application/json",
            )
        return (
            200, json.dumps(FAULTS.debug_state(), indent=1).encode(),
            "application/json",
        )

    def _route_journal_stream(self, query: str):
        """GET /journal/stream — the HA shipping verb (journal/ship.py):
        sealed segments + long-polled live tail in the journal wire
        format.  ``from_seq`` resumes; ``wait_s`` long-polls; the
        X-Journal-Last-Seq header carries the leader's newest assigned
        seq (the follower's lag numerator)."""
        from ..journal.ship import DEFAULT_MAX_BYTES, stream_since

        if not JOURNAL.enabled:
            return (
                404,
                json.dumps({"error": "journal not enabled "
                                     "(--journal-dir)"}).encode(),
                "application/json",
            )
        params = _parse_query(query)
        try:
            from_seq = int(params.get("from_seq", "0"))
            wait_s = min(60.0, max(0.0, float(params.get("wait_s", "0"))))
            max_bytes = min(
                64 << 20,
                max(1 << 16, int(params.get("max_bytes",
                                            str(DEFAULT_MAX_BYTES)))),
            )
        except ValueError:
            return (
                400, b'{"Error": "from_seq/wait_s/max_bytes malformed"}',
                "application/json",
            )
        try:
            payload, last_seq = stream_since(
                JOURNAL, from_seq, max_bytes=max_bytes, wait_s=wait_s
            )
        except OSError as e:
            # injected (ship.stream site) or real I/O failure: the
            # follower re-requests from its seq — a 5xx, never a tear
            # presented as success
            return (
                503, json.dumps({"Error": f"stream: {e}"}).encode(),
                "application/json",
            )
        return (
            200, payload, "application/octet-stream",
            {"X-Journal-Last-Seq": str(last_seq)},
        )

    def _parse(self, verb: str, parser: Callable, body: dict):
        """Wire-type parsing as a structured 400 (malformed client input
        must never surface as a 500 from deep inside a from_dict — the
        fuzz suite pins this)."""
        try:
            return parser(body), None
        except Exception as e:
            VERB_TOTAL.inc(verb, "bad_request")
            return None, (
                400,
                json.dumps({
                    "Error": f"malformed {verb} body: "
                             f"{e.__class__.__name__}: {e}"
                }).encode(),
                "application/json",
            )

    def _verb(self, verb: str, fn: Callable[[], object]) -> tuple[int, bytes, str]:
        try:
            with VERB_LATENCY.time(verb):
                result = fn()
            # handler-level failures are returned in-body (Error field)
            failed = isinstance(result, dict) and result.get("Error")
            VERB_TOTAL.inc(verb, "error" if failed else "ok")
            return 200, json.dumps(result).encode(), "application/json"
        except Exception as e:  # structured 500, never a crash
            log.exception("%s verb failed", verb)
            VERB_TOTAL.inc(verb, "error")
            return 500, json.dumps({"Error": f"{verb}: {e}"}).encode(), "application/json"

    def _make_handler(server_self):
        import socketserver

        class Handler(socketserver.StreamRequestHandler):
            # Nagle + delayed-ACK costs ~40ms per small JSON response body
            disable_nagle_algorithm = True
            rbufsize = 1 << 16
            wbufsize = 1 << 16  # buffer the response; single flush per reply

            def handle(self):
                try:
                    while self._one_request():
                        pass
                except (ConnectionError, BrokenPipeError, TimeoutError):
                    pass

            def _one_request(self) -> bool:
                line = self.rfile.readline(8192)
                if not line:
                    return False
                try:
                    method, target, version = line.decode("latin1").split()
                except ValueError:
                    return False
                clen = 0
                close = version == "HTTP/1.0"
                traceparent = ""
                while True:
                    h = self.rfile.readline(8192)
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.partition(b":")
                    k = k.strip().lower()
                    if k == b"content-length":
                        try:
                            clen = int(v.strip())
                        except ValueError:
                            return False
                    elif k == b"connection" and v.strip().lower() == b"close":
                        close = True
                    elif k == b"traceparent":
                        # W3C trace context: a tracing-aware client's verb
                        # joins its trace (tracing/__init__.py)
                        traceparent = v.strip().decode("latin1")
                raw = self.rfile.read(clen) if clen > 0 else b""
                path, _, query = target.partition("?")
                if method == "GET":
                    result = server_self._route_get(path, query)
                elif method == "POST":
                    result = server_self._route_post(
                        path, raw, traceparent
                    )
                else:
                    result = 405, b"method not allowed", "text/plain"
                code, payload, ctype = result[0], result[1], result[2]
                # optional 4th element: extra response headers (the 503
                # Retry-After floor, the stream's X-Journal-Last-Seq)
                extra = ""
                if len(result) > 3 and result[3]:
                    extra = "".join(
                        f"{k}: {v}\r\n" for k, v in result[3].items()
                    )
                head = (
                    f"HTTP/1.1 {code} {_REASONS.get(code, 'OK')}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    f"{extra}"
                    f"{'Connection: close' + chr(13) + chr(10) if close else ''}"
                    "\r\n"
                ).encode("latin1")
                self.wfile.write(head + payload)
                self.wfile.flush()
                # request debug-logging (reference routes.go:173-179
                # DebugLogging wrapper); guarded so the fast path pays only
                # an isEnabledFor check
                if log.isEnabledFor(logging.DEBUG):
                    log.debug(
                        "http %s %s -> %d (%dB)", method, target, code,
                        len(payload),
                    )
                return not close

        return Handler

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> int:
        """Start serving in a background thread; returns the bound port."""
        self._httpd = _HTTPServer(
            (self.host, self.port), self._make_handler(), pool_size=self.workers
        )
        self._maybe_wrap_tls(self._httpd)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="extender-http", daemon=True
        )
        self._thread.start()
        log.info("extender serving on %s:%d", self.host, self.port)
        return self.port

    def serve_forever(self) -> None:
        self._httpd = _HTTPServer(
            (self.host, self.port), self._make_handler(), pool_size=self.workers
        )
        self._maybe_wrap_tls(self._httpd)
        self._httpd.serve_forever()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
