"""HTTP routing for the extender webhook.

Reference: pkg/routes/routes.go.  Paths kept wire-compatible:

    POST /scheduler/filter      → Predicate
    POST /scheduler/priorities  → Prioritize
    POST /scheduler/bind        → Bind
    GET  /scheduler/status      → per-node chip state dump (routes.go:197-218)
    GET  /version               → version JSON (routes.go:165-171)
    GET  /healthz               → liveness
    GET  /metrics               → Prometheus text (net-new; reference has none)
    GET  /debug/stacks          → all-thread stack dump (pprof analogue;
                                  reference mounts net/http/pprof, pprof.go)

Deviation (SURVEY §5 quirk not replicated): the reference's prioritize route
panics on malformed input (routes.go:98,103,109); here every route returns a
structured error with a 4xx/5xx status instead.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .. import __version__
from ..k8s.extender import ExtenderArgs, ExtenderBindingArgs
from ..metrics import REGISTRY, VERB_LATENCY, VERB_TOTAL
from .handlers import Bind, Predicate, Prioritize

log = logging.getLogger("tpu-scheduler")


class _HTTPServer(ThreadingHTTPServer):
    # Gang binds hold N concurrent connections at the barrier; the stdlib
    # default backlog of 5 resets connections under a 256-member gang.
    request_queue_size = 1024


class ExtenderServer:
    def __init__(
        self,
        predicate: Predicate,
        prioritize: Prioritize,
        bind: Bind,
        status_fn: Callable[[], dict],
        host: str = "0.0.0.0",
        port: int = 39999,
        tls_cert: str = "",
        tls_key: str = "",
    ):
        self.predicate = predicate
        self.prioritize = prioritize
        self.bind = bind
        self.status_fn = status_fn
        self.host = host
        self.port = port
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def _maybe_wrap_tls(self, httpd) -> None:
        """Serve HTTPS when a cert/key pair is configured (the extender
        config's enableHTTPS option; the reference is HTTP-only)."""
        if not self.tls_cert:
            return
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.tls_cert, self.tls_key or None)
        httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)

    # -- request plumbing ----------------------------------------------------

    def _make_handler(server_self):
        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Nagle + delayed-ACK costs ~40ms per small JSON response body;
            # this is a handler attribute (socketserver.StreamRequestHandler)
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):
                log.debug("http: " + fmt, *args)

            def _send(self, code: int, body: bytes, ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, obj) -> None:
                self._send(code, json.dumps(obj).encode())

            def _read_json(self) -> Optional[dict]:
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    return json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    return None

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/version":
                    self._send_json(200, {"version": __version__})
                elif path == "/healthz":
                    self._send(200, b"ok", "text/plain")
                elif path == "/metrics":
                    self._send(200, REGISTRY.expose().encode(), "text/plain")
                elif path == "/scheduler/status":
                    try:
                        self._send_json(200, server_self.status_fn())
                    except Exception as e:
                        self._send_json(500, {"error": str(e)})
                elif path == "/debug/stacks":
                    frames = sys._current_frames()
                    out = []
                    for tid, frame in frames.items():
                        out.append(f"--- thread {tid} ---")
                        out.extend(traceback.format_stack(frame))
                    self._send(200, "".join(out).encode(), "text/plain")
                else:
                    self._send_json(404, {"error": f"no route {path}"})

            def do_POST(self):
                path = self.path.split("?")[0]
                body = self._read_json()
                if body is None:
                    VERB_TOTAL.inc(path.rsplit("/", 1)[-1], "bad_request")
                    self._send_json(400, {"Error": "malformed JSON body"})
                    return
                if path == "/scheduler/filter":
                    self._verb("filter", lambda: server_self.predicate.handle(
                        ExtenderArgs.from_dict(body)).to_dict())
                elif path == "/scheduler/priorities":
                    self._verb("priorities", lambda: [
                        hp.to_dict()
                        for hp in server_self.prioritize.handle(
                            ExtenderArgs.from_dict(body))
                    ])
                elif path == "/scheduler/bind":
                    self._verb("bind", lambda: server_self.bind.handle(
                        ExtenderBindingArgs.from_dict(body)).to_dict())
                else:
                    self._send_json(404, {"error": f"no route {path}"})

            def _verb(self, verb: str, fn: Callable[[], object]) -> None:
                try:
                    with VERB_LATENCY.time(verb):
                        result = fn()
                    # handler-level failures are returned in-body (Error field)
                    failed = isinstance(result, dict) and result.get("Error")
                    VERB_TOTAL.inc(verb, "error" if failed else "ok")
                    self._send_json(200, result)
                except Exception as e:  # structured 500, never a crash
                    log.exception("%s verb failed", verb)
                    VERB_TOTAL.inc(verb, "error")
                    self._send_json(500, {"Error": f"{verb}: {e}"})

        return Handler

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> int:
        """Start serving in a background thread; returns the bound port."""
        self._httpd = _HTTPServer(
            (self.host, self.port), self._make_handler()
        )
        self._maybe_wrap_tls(self._httpd)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="extender-http", daemon=True
        )
        self._thread.start()
        log.info("extender serving on %s:%d", self.host, self.port)
        return self.port

    def serve_forever(self) -> None:
        self._httpd = _HTTPServer(
            (self.host, self.port), self._make_handler()
        )
        self._maybe_wrap_tls(self._httpd)
        self._httpd.serve_forever()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
