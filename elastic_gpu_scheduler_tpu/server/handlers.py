"""Extender verb handlers.

Thin adapters between the HTTP layer and the scheduling engines — the
reference's pkg/server (predicate.go:16-40, priority.go:17-45, bind.go:21-55):
pick the right engine for the pod's requested resource, call
assume/score/bind.  Bind re-fetches the pod and double-checks UID and
completion before committing (reference: bind.go:36-45, pod.go:110-131).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..k8s.extender import (
    ExtenderArgs,
    ExtenderBindingArgs,
    ExtenderBindingResult,
    ExtenderFilterResult,
    HostPriority,
)
from ..k8s.fake import is_not_found
from ..k8s.objects import Pod
from ..scheduler.registry import get_resource_scheduler
from ..scheduler.scheduler import ResourceScheduler

log = logging.getLogger("tpu-scheduler")


class Predicate:
    def __init__(self, registry: dict[str, ResourceScheduler], gang=None):
        self.registry = registry
        self.gang = gang  # optional GangCoordinator

    def handle(self, args: ExtenderArgs) -> ExtenderFilterResult:
        pod = args.pod
        if args.node_names is None:
            return ExtenderFilterResult(
                error="extender requires nodeCacheCapable=true (NodeNames missing)"
            )
        sched = get_resource_scheduler(self.registry, pod)
        if sched is None:
            # no TPU demand → every node passes
            return ExtenderFilterResult(node_names=list(args.node_names))
        from ..core.request import request_from_pod

        if self.gang is not None and self.gang.is_gang_pod(request_from_pod(pod)):
            ok, failed = self.gang.filter(sched, pod, list(args.node_names))
        else:
            ok, failed = sched.assume(list(args.node_names), pod)
        return ExtenderFilterResult(node_names=ok, failed_nodes=failed)


class Prioritize:
    def __init__(self, registry: dict[str, ResourceScheduler]):
        self.registry = registry

    def handle(self, args: ExtenderArgs) -> list[HostPriority]:
        pod = args.pod
        names = list(args.node_names or [])
        sched = get_resource_scheduler(self.registry, pod)
        if sched is None:
            return [HostPriority(host=n, score=0) for n in names]
        scores = sched.score(names, pod)
        return [HostPriority(host=n, score=s) for n, s in zip(names, scores)]


class Bind:
    def __init__(self, registry: dict[str, ResourceScheduler], clientset, gang=None):
        self.registry = registry
        self.clientset = clientset
        self.gang = gang

    def handle(self, args: ExtenderBindingArgs) -> ExtenderBindingResult:
        try:
            pod = self.clientset.get_pod(args.pod_namespace, args.pod_name)
        except Exception as e:
            if is_not_found(e):
                return ExtenderBindingResult(
                    error=f"pod {args.pod_namespace}/{args.pod_name} not found"
                )
            return ExtenderBindingResult(error=f"get pod: {e}")
        # delete/recreate race: the UID the kube-scheduler bound is stale
        if args.pod_uid and pod.metadata.uid != args.pod_uid:
            return ExtenderBindingResult(
                error=f"pod {pod.key}: uid mismatch (recreated?)"
            )
        if pod.is_completed():
            return ExtenderBindingResult(error=f"pod {pod.key} already completed")
        sched = get_resource_scheduler(self.registry, pod)
        if sched is None:
            return ExtenderBindingResult(error=f"pod {pod.key} requests no TPU")
        try:
            if self.gang is not None:
                self.gang.bind(sched, args.node, pod)
            else:
                sched.bind(args.node, pod)
        except Exception as e:
            log.warning("bind %s -> %s failed: %s", pod.key, args.node, e)
            return ExtenderBindingResult(error=str(e))
        return ExtenderBindingResult()
