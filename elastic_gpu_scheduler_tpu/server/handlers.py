"""Extender verb handlers.

Thin adapters between the HTTP layer and the scheduling engines — the
reference's pkg/server (predicate.go:16-40, priority.go:17-45, bind.go:21-55):
pick the right engine for the pod's requested resource, call
assume/score/bind.  Bind re-fetches the pod and double-checks UID and
completion before committing (reference: bind.go:36-45, pod.go:110-131).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..k8s.extender import (
    ExtenderArgs,
    ExtenderBindingArgs,
    ExtenderBindingResult,
    ExtenderFilterResult,
    ExtenderPreemptionArgs,
    ExtenderPreemptionResult,
    HostPriority,
    MetaPod,
    MetaVictims,
)
from ..core.request import pod_gang_key
from ..k8s.fake import is_not_found
from ..k8s.objects import Pod
from ..scheduler.registry import get_resource_scheduler
from ..scheduler.scheduler import ResourceScheduler
from ..tracing import AUDIT, TRACER
from ..utils import consts

log = logging.getLogger("tpu-scheduler")


def _pod_root(pod: Pod, traceparent: str = ""):
    """The pod's trace root, honoring remote context in precedence order:
    explicit wire traceparent, then a submission-time pod annotation, then
    a fresh per-pod trace.  Verb spans for one pod all join this root, so
    filter → priorities → bind forms ONE trace despite arriving as
    independent HTTP requests."""
    parent = traceparent or (pod.metadata.annotations or {}).get(
        consts.ANNOTATION_TRACEPARENT, ""
    )
    return TRACER.pod_span(pod.key, parent=parent or None)


class Predicate:
    def __init__(self, registry: dict[str, ResourceScheduler], gang=None):
        self.registry = registry
        self.gang = gang  # optional GangCoordinator

    def handle(self, args: ExtenderArgs) -> ExtenderFilterResult:
        pod = args.pod
        if args.node_names is None:
            return ExtenderFilterResult(
                error="extender requires nodeCacheCapable=true (NodeNames missing)"
            )
        sched = get_resource_scheduler(self.registry, pod)
        if sched is None:
            # no TPU demand → every node passes
            return ExtenderFilterResult(node_names=list(args.node_names))
        from ..core.request import request_from_pod

        with TRACER.span(
            "extender.filter",
            parent=_pod_root(pod, args.traceparent),
            pod=pod.key,
            candidates=len(args.node_names),
        ) as sp:
            if self.gang is not None and self.gang.is_gang_pod(
                request_from_pod(pod)
            ):
                ok, failed = self.gang.filter(
                    sched, pod, list(args.node_names)
                )
            else:
                ok, failed = sched.assume(list(args.node_names), pod)
            sp.set_attr("feasible", len(ok))
            if failed:
                sp.set_attr("rejected", len(failed))
            if AUDIT.enabled:
                # the per-node verdict IS the audit: which nodes could
                # host the pod, and the named constraint each rejected on
                AUDIT.record(
                    pod.key, "filter", trace_id=sp.trace_id,
                    ok=list(ok), failed=dict(failed),
                )
        return ExtenderFilterResult(node_names=ok, failed_nodes=failed)


class Prioritize:
    def __init__(self, registry: dict[str, ResourceScheduler]):
        self.registry = registry

    def handle(self, args: ExtenderArgs) -> list[HostPriority]:
        pod = args.pod
        names = list(args.node_names or [])
        sched = get_resource_scheduler(self.registry, pod)
        if sched is None:
            return [HostPriority(host=n, score=0) for n in names]
        with TRACER.span(
            "extender.priorities",
            parent=_pod_root(pod, args.traceparent),
            pod=pod.key,
            candidates=len(names),
        ) as sp:
            scores = sched.score(names, pod)
            by_node = dict(zip(names, scores))
            if by_node:
                best = max(by_node, key=by_node.get)
                sp.set_attr("best", f"{best}={by_node[best]}")
            if AUDIT.enabled:
                AUDIT.record(
                    pod.key, "priorities", trace_id=sp.trace_id,
                    scores=by_node,
                )
        return [HostPriority(host=n, score=s) for n, s in zip(names, scores)]


class Preemption:
    """ProcessPreemption verb (net-new vs the reference — see k8s/extender.py).

    For each candidate node, re-evaluate kube-scheduler's proposed victim set
    against the TPU allocation ledger: drop nodes where the preemptor cannot
    fit even with all victims gone, and prune victims whose chips are not
    actually required (kube-scheduler's PDB-violation counts are passed
    through unchanged — this extender has no PDB view, so the original count
    stays an upper bound for the pruned set)."""

    def __init__(self, registry: dict[str, ResourceScheduler], clientset):
        self.registry = registry
        self.clientset = clientset

    def _expand_gang_victims(
        self,
        node: str,
        victims: list[Pod],
        node_pods: Optional[list[Pod]] = None,
    ) -> list[Pod]:
        """Pull same-node co-members of any gang victim into the victim set
        (VERDICT r2 #5a).  Evicting one member of a bound gang kills the
        whole SPMD job; its siblings on this node would otherwise survive as
        dead weight holding chips until something else reaps them.  Listing
        them as victims (a) evicts them with their gang and (b) lets the
        scheduler's simulation count their chips as freed capacity.
        Co-members on OTHER nodes are out of this verb's per-node scope —
        the reconciliation controller frees their chips when the dead job's
        pods terminate.  ``node_pods``: the node's already-fetched pod list
        (the meta-victims path LISTed it moments ago); only the
        full-Victims path pays a fresh LIST, and only when some victim is
        gang-annotated.  Best-effort: a failed LIST leaves the proposal
        unexpanded (never blocks the verb)."""
        gang_keys = {
            g for g in (pod_gang_key(v) for v in victims) if g is not None
        }
        if not gang_keys:
            return victims
        if node_pods is None:
            try:
                node_pods = self.clientset.list_pods(node_name=node)
            except Exception as e:
                log.warning(
                    "preemption: gang expansion list for %s failed: %s",
                    node, e,
                )
                return victims
        present = {v.metadata.uid for v in victims}
        extra = [
            p
            for p in node_pods
            if pod_gang_key(p) in gang_keys
            and p.metadata.uid not in present
            and not p.is_completed()
        ]
        return victims + extra

    def handle(self, args: ExtenderPreemptionArgs) -> ExtenderPreemptionResult:
        pod = args.pod
        with TRACER.span(
            "extender.preemption",
            parent=_pod_root(pod, args.traceparent),
            pod=pod.key,
        ) as sp:
            result = self._handle(args)
            victims = {
                n: len(v.pods)
                for n, v in result.node_name_to_meta_victims.items()
            }
            sp.set_attr("candidate_nodes", len(victims))
            if AUDIT.enabled:
                AUDIT.record(
                    pod.key, "preemption", trace_id=sp.trace_id,
                    nodes=len(victims), victims=victims,
                )
            return result

    def _handle(self, args: ExtenderPreemptionArgs) -> ExtenderPreemptionResult:
        pod = args.pod
        sched = get_resource_scheduler(self.registry, pod)
        # node → (victim Pods | None, pass-through victim UIDs, PDB count).
        # victims=None means "echo the proposal, do not simulate" (the pod
        # LIST failed, so the ledger cannot be consulted safely).
        # Pass-through UIDs are victims we could not resolve to Pod objects
        # (deleted mid-flight, or the pod LIST failed): the conservative
        # answer keeps them in the victim set unchanged — an EMPTY victim
        # set is a positive "no evictions needed" claim kube-scheduler acts
        # on, so resolution failure must never shrink the proposal.
        # the 4th element is the node's already-fetched pod list when one
        # exists (meta path) — gang expansion reuses it instead of re-LISTing
        candidates: dict[
            str,
            tuple[Optional[list[Pod]], list[str], int, Optional[list[Pod]]],
        ] = {}
        for n, v in args.node_name_to_victims.items():
            candidates[n] = (list(v.pods), [], v.num_pdb_violations, None)
        meta_nodes = {
            n: mv
            for n, mv in args.node_name_to_meta_victims.items()
            if n not in candidates
        }
        # Few candidates: node-scoped LISTs (server-side spec.nodeName field
        # selector — victims run on their node).  Many candidates
        # (kube-scheduler passes up to ~100): ONE cluster-wide LIST beats N
        # serial round trips on the verb's critical path.
        cluster_index: Optional[dict[str, Pod]] = None
        if len(meta_nodes) > 4:
            try:
                cluster_index = {
                    p.metadata.uid: p for p in self.clientset.list_pods()
                }
            except Exception as e:
                log.warning("preemption: cluster pod list failed: %s", e)
        for n, mv in meta_nodes.items():
            by_uid: Optional[dict[str, Pod]] = cluster_index
            node_pods: Optional[list[Pod]] = None
            if by_uid is None:
                try:
                    node_pods = list(self.clientset.list_pods(node_name=n))
                    by_uid = {p.metadata.uid: p for p in node_pods}
                except Exception as e:
                    log.warning("preemption: pod list for %s failed: %s", n, e)
            else:
                node_pods = [
                    p for p in by_uid.values() if p.spec.node_name == n
                ]
            if by_uid is None:
                # echo the node's proposal unchanged (no pruning, no
                # dropping — same as an extender without preemptVerb);
                # victims=None marks "echo, do not simulate"
                candidates[n] = (
                    None,
                    [p.uid for p in mv.pods],
                    mv.num_pdb_violations,
                    None,
                )
                continue
            resolved, missing = [], []
            for p in mv.pods:
                v = by_uid.get(p.uid)
                if v is not None:
                    resolved.append(v)
                else:
                    missing.append(p.uid)
            candidates[n] = (resolved, missing, mv.num_pdb_violations, node_pods)

        result: dict[str, MetaVictims] = {}
        for n, (victims, passthrough_uids, pdb, node_pods) in candidates.items():
            if victims is None or sched is None:
                # echo the proposal: either the LIST failed (victims=None)
                # or the pod requests no TPU — no opinion either way
                needed: Optional[list[Pod]] = victims or []
            else:
                victims = self._expand_gang_victims(n, victims, node_pods)
                needed = sched.preempt(n, pod, victims)
                if needed is None and passthrough_uids:
                    # infeasible — but UNRESOLVED victims (deleted
                    # mid-flight, chips still charged until reconciliation
                    # catches up) may hold exactly the capacity we could
                    # not simulate; echo the full proposal instead of
                    # dropping a node that may become feasible
                    needed = victims
            if needed is None:
                continue  # node infeasible even with all victims evicted
            result[n] = MetaVictims(
                pods=[MetaPod(uid=v.metadata.uid) for v in needed]
                + [MetaPod(uid=u) for u in passthrough_uids],
                num_pdb_violations=pdb,
            )
        return ExtenderPreemptionResult(node_name_to_meta_victims=result)


class Bind:
    def __init__(self, registry: dict[str, ResourceScheduler], clientset, gang=None):
        self.registry = registry
        self.clientset = clientset
        self.gang = gang

    def handle(self, args: ExtenderBindingArgs) -> ExtenderBindingResult:
        try:
            pod = self.clientset.get_pod(args.pod_namespace, args.pod_name)
        except Exception as e:
            if is_not_found(e):
                return ExtenderBindingResult(
                    error=f"pod {args.pod_namespace}/{args.pod_name} not found"
                )
            return ExtenderBindingResult(error=f"get pod: {e}")
        # delete/recreate race: the UID the kube-scheduler bound is stale
        if args.pod_uid and pod.metadata.uid != args.pod_uid:
            return ExtenderBindingResult(
                error=f"pod {pod.key}: uid mismatch (recreated?)"
            )
        if pod.is_completed():
            return ExtenderBindingResult(error=f"pod {pod.key} already completed")
        sched = get_resource_scheduler(self.registry, pod)
        if sched is None:
            return ExtenderBindingResult(error=f"pod {pod.key} requests no TPU")
        with TRACER.span(
            "extender.bind",
            parent=_pod_root(pod, args.traceparent),
            pod=pod.key,
            node=args.node,
        ) as sp:
            try:
                if self.gang is not None:
                    self.gang.bind(sched, args.node, pod)
                else:
                    sched.bind(args.node, pod)
            except Exception as e:
                log.warning("bind %s -> %s failed: %s", pod.key, args.node, e)
                sp.set_attr("error", str(e))
                sp.end(status="error")
                if AUDIT.enabled:
                    AUDIT.record(
                        pod.key, "bind", trace_id=sp.trace_id,
                        node=args.node, error=str(e),
                    )
                return ExtenderBindingResult(error=str(e))
        # the pod's scheduling story is complete: close its trace (the
        # commit layer recorded the chips-level audit entry)
        TRACER.finish_pod(pod.key)
        return ExtenderBindingResult()
