"""Mesh defragmentation & live-migration planner.

The scheduler only ever ADDS placements: after enough pod churn the ICI
mesh fragments — free chips scatter across nodes, large gangs stop
fitting (every node's free count drops below the member size even though
the cluster-wide total is ample), and the fragmentation gauges
(``tpu_scheduler_mesh_fragmentation_index``,
``largest_free_submesh_chips``) climb with nothing acting on them.
Tesserae (arxiv 2508.04953) shows migration-aware placement recovers
most of that lost capacity; Gavel (arxiv 2008.09213) shows round-based
re-placement composes cleanly with an existing scheduler.  This module
is that capability for the TPU mesh:

- **Detect.**  The planner consumes the SAME per-node chip state the
  LazyGauge refresher scans (``ChipSet.fragmentation()`` /
  ``largest_free_box()`` on clones — never live state): a round triggers
  when a pending gang's shape cannot fit any node (``try_unblock``, the
  gang filter's admission-retry hook) or when a node's fragmentation
  index exceeds the configured threshold (the auto loop / POST
  /defrag/run).

- **Plan.**  ``plan()`` computes a migration plan — which victims move
  where — as a list of ROUNDS.  Within one round every destination uses
  only chips that were free at round start (placements accumulate into
  the simulation immediately; evictions apply at round END), which makes
  rounds structurally acyclic (no A→B→A in a round: chips freed by a
  round's evictions only become destinations in the NEXT round) and
  makes every move executable in any order.  Victim re-placements are
  scored with the existing machinery: whole-chip shapes through the
  ``plan_gang`` kernel (native C++ when built, bit-identical Python
  fallback), everything else through ``ChipSet.trade`` under the
  engine's own rater.  Victim selection is a documented greedy
  (largest-that-fits first per deficit, smallest-overshoot fallback) —
  a min-cost heuristic, not an ILP.  Plans are chip-conserving by
  construction (the new Option carries the same per-container demand as
  the old; ``option_demand`` guards it again at execution and replay)
  and never touch a pod — or any member of a gang — whose priority
  exceeds ``priority_ceiling``.

- **Execute.**  Each move is a journaled evict→rebind transaction
  (``TPUUnitScheduler.migrate_pod``: destination is charged BEFORE the
  source is freed, so the unsafe direction — double-booking others —
  cannot occur; the journal's new ``migrate`` record captures both
  placements and replay verifies the per-pod chip-count conservation
  invariant).  A round is all-or-nothing: a mid-round failure reverses
  every executed move with compensating migrations.  Nodes involved in
  a round are CORDONED on the engine (filter rejects them; the
  reconciliation controller expires stale cordons) for the duration.
  Migration hooks (``defrag.hooks``) bracket each move with the serving
  plane's drain/elastic-resume path so a migrated serving pod loses at
  most one in-flight chunk.

Modes: ``off`` (default — the only cost anywhere near the bind path is
one attribute check in the gang filter), ``observe`` (plans are computed
and served at /debug/defrag, never executed automatically; POST
/defrag/run may still execute), ``auto`` (the gang filter retries
admission after an unblocking round, and a background tick compacts
nodes over the threshold).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional

from ..core.allocator import (
    ChipSet,
    ContainerAlloc,
    Option,
    iter_bits,
    option_demand,
    plan_gang_fallback,
)
from ..core.request import pod_gang_key
from ..journal.replay import request_from_option
from ..metrics import (
    DEFRAG_EVENTS,
    DEFRAG_RECOVERED,
    DEFRAG_ROUND,
    TimedLock,
)
from .hooks import MigrationHook

log = logging.getLogger("tpu-scheduler")

MODES = ("off", "observe", "auto")


@dataclass(frozen=True)
class Move:
    """One planned migration: a live pod re-homed from one placement to
    another.  ``old``/``new`` carry identical per-container demand
    (chip-conserving by construction)."""

    pod_key: str
    uid: str
    from_node: str
    to_node: str
    old: Option
    new: Option
    chips: int  # whole-chip count moved (fractional moves count their chips)
    priority: int = 0
    gang: str = ""

    def to_dict(self) -> dict:
        return {
            "pod": self.pod_key,
            "from": self.from_node,
            "to": self.to_node,
            "chips": self.chips,
            "priority": self.priority,
            "gang": self.gang or None,
            "coords_from": [
                [list(c) for c in a.coords]
                for a in self.old.allocs if a.needs_tpu
            ],
            "coords_to": [
                [list(c) for c in a.coords]
                for a in self.new.allocs if a.needs_tpu
            ],
        }


@dataclass
class DefragPlan:
    """Rounds of moves plus the predicted effect.  ``rounds[k]``'s
    destinations only use chips free before round k executed."""

    rounds: list = field(default_factory=list)  # list[list[Move]]
    reason: str = ""
    want: Optional[tuple] = None  # (chips_per_member, members) when unblocking
    frag_before: dict = field(default_factory=dict)  # node → (index, largest)
    frag_after: dict = field(default_factory=dict)
    feasible_before: Optional[bool] = None
    feasible_after: Optional[bool] = None

    def moves(self) -> list:
        return [m for rnd in self.rounds for m in rnd]

    @property
    def chips_moved(self) -> int:
        return sum(m.chips for m in self.moves())

    def recovered_submesh_chips(self) -> int:
        """Largest gain in any node's largest-free-contiguous-box — the
        headline 'capacity recovered' number."""
        gain = 0
        for node, (_, after) in self.frag_after.items():
            before = self.frag_before.get(node, (0.0, after))[1]
            gain = max(gain, after - before)
        return gain

    def to_dict(self) -> dict:
        return {
            "reason": self.reason,
            "want": list(self.want) if self.want else None,
            "rounds": [[m.to_dict() for m in rnd] for rnd in self.rounds],
            "moves": len(self.moves()),
            "chips_moved": self.chips_moved,
            "feasible_before": self.feasible_before,
            "feasible_after": self.feasible_after,
            "recovered_submesh_chips": self.recovered_submesh_chips(),
            "frag_before": {
                n: {"index": i, "largest_free_box": l}
                for n, (i, l) in sorted(self.frag_before.items())
            },
            "frag_after": {
                n: {"index": i, "largest_free_box": l}
                for n, (i, l) in sorted(self.frag_after.items())
            },
        }


@dataclass
class _Victim:
    """A movable live pod in the planning snapshot."""

    pod_key: str
    uid: str
    node: str
    option: Option
    priority: int
    gang: str
    whole: bool  # single whole-chip alloc (plan_gang-placeable)
    chips: int  # chips freed on the source node if moved


def best_whole_box(
    cs: ChipSet, count: int, max_candidates: int = 64,
    force_fallback: bool = False,
):
    """Best ``count``-chip contiguous box on ``cs``'s free chips — THE
    defrag scoring entry point into the gang-plan kernel: native
    ``plan_gang`` with members=1 when built, the bit-identical Python
    fallback otherwise (tests/test_defrag.py asserts parity directly on
    this function).  Returns (coords, contiguous) or None when fewer
    than ``count`` chips are free."""
    if cs.free_count() < count:
        return None
    free_list = tuple(cs._mesh_idx[i] for i in iter_bits(cs._free_bits))
    native = None
    if not force_fallback:
        from ..core.native import get_placement

        native = get_placement()
    if native is not None and hasattr(native, "plan_gang"):
        placed = native.plan_gang(
            cs.topo.dims, cs.topo.wrap, [free_list], count, 1, max_candidates
        )
    else:
        placed = plan_gang_fallback(
            cs.topo, [free_list], count, 1, max_candidates
        )
    if not placed:
        return None
    _, idxs, contiguous = placed[0]
    return tuple(cs.topo.coord_of(i) for i in idxs), bool(contiguous)


def _rebuild_option(old: Option, coords, contiguous: bool) -> Option:
    """New Option with the SAME per-container demand as ``old``, its one
    TPU alloc re-targeted at ``coords`` (chip-conserving by construction)."""
    allocs = []
    for a in old.allocs:
        if not a.needs_tpu:
            allocs.append(a)
            continue
        allocs.append(
            ContainerAlloc(
                container=a.container, coords=tuple(coords), whole=a.whole,
                core=a.core, hbm=a.hbm,
                contiguous=bool(contiguous) if a.whole else True,
            )
        )
    return Option(old.request_hash, tuple(allocs), old.score)


class DefragPlanner:
    """Round-based migration planner over one scheduler's engines.

    Thread model: ``_lock`` (TimedLock rank 15 — between the gang
    coordinator (10) and the engine registry lock (20); a round takes
    engine + node locks, and the gang filter calls ``try_unblock``
    AFTER releasing its own lock) serializes planning and execution, so
    at most one round mutates live state at a time.  All planning runs
    on O(words) ChipSet clones; live allocators are only touched by
    ``migrate_pod`` during execution.
    """

    def __init__(
        self,
        engines: Iterable,
        clientset,
        mode: str = "off",
        threshold: float = 0.5,
        max_moves: int = 8,
        max_rounds: int = 4,
        priority_ceiling: int = 0,
        min_interval_s: float = 5.0,
        cordon_ttl_s: float = 120.0,
        interval_s: float = 30.0,
        hooks: Optional[list] = None,
        clock=time.monotonic,
    ):
        if mode not in MODES:
            raise ValueError(f"defrag mode {mode!r} not in {MODES}")
        # unique engines (the registry maps several resource names to one)
        seen: list = []
        for e in engines:
            if all(e is not s for s in seen):
                seen.append(e)
        self.engines = seen
        self.clientset = clientset
        self.mode = mode
        self.threshold = threshold
        self.max_moves = max(1, max_moves)
        self.max_rounds = max(1, max_rounds)
        self.priority_ceiling = priority_ceiling
        self.min_interval_s = min_interval_s
        self.cordon_ttl_s = cordon_ttl_s
        self.interval_s = max(1.0, interval_s)
        self.hooks: list[MigrationHook] = list(hooks or [])
        # HA: callable → bool; standbys must not migrate (the HTTP layer
        # gates verbs the same way).  None = always the leader.
        self.leader_check = None
        # programmable policy plane: a loaded ``defrag`` verb policy
        # replaces the built-in victim orderings below (HIGHER score =
        # move first; a faulting policy falls back per victim).  None /
        # empty plane = one attribute check per round, zero per bind.
        self.policies = None
        self._lock = TimedLock("defrag", rank=15)
        # time source for the rate limiter — the digital twin (twin/)
        # injects a VirtualClock so simulated rounds rate-limit against
        # simulated time; live planners keep time.monotonic
        self.clock = clock
        self._last_round = 0.0  # clock units; rate-limits try_unblock
        self._rounds_run = 0
        self._moves_executed = 0
        self._last_result: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle (auto mode) -----------------------------------------------

    def start(self) -> "DefragPlanner":
        """Start the auto-mode background tick (no-op otherwise)."""
        if self.mode != "auto" or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._auto_loop, name="defrag-auto", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def _is_leader(self) -> bool:
        if self.leader_check is None:
            return True
        try:
            return bool(self.leader_check())
        except Exception:
            return False

    def _auto_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if not self._is_leader():
                continue  # standby: migrating would split-brain the leader
            try:
                for sched in self.engines:
                    snap = sched.frag_snapshot()
                    if any(
                        idx > self.threshold for idx, _ in snap.values()
                    ):
                        self.run_round(sched=sched)
            except Exception:
                log.exception("defrag auto tick failed")

    # -- snapshot -------------------------------------------------------------

    @staticmethod
    def _chip_clones(sched) -> dict:
        """Per-node ChipSet clones only — O(words) each, NO clientset
        round-trips.  The feasibility probe uses this; full planning
        needs ``_snapshot`` (which adds the movable-pod scan)."""
        with sched.lock:
            allocators = dict(sched.allocators)
        clones: dict[str, ChipSet] = {}
        for name, na in allocators.items():
            with na.lock:
                clones[name] = na.chips.clone()
        return clones

    def _snapshot(self, sched):
        """(clones, victims_by_node): per-node ChipSet clones plus the
        MOVABLE live pods.  Ledger under the engine lock, pod objects
        from the clientset (priority/uid/gang), chip state under each
        node's own lock — never the whole registry frozen at once."""
        with sched.lock:
            ledger = dict(sched.pod_maps)
        clones = self._chip_clones(sched)
        # gang priority ceiling: a gang moves as a unit of risk — if ANY
        # member outranks the ceiling, no member is movable
        gang_max_prio: dict[str, int] = {}
        pods: dict[str, object] = {}
        for key, (node, opt) in ledger.items():
            ns, _, name = key.partition("/")
            try:
                pod = self.clientset.get_pod(ns, name)
            except Exception:
                continue
            if pod.is_completed():
                continue
            pods[key] = pod
            g = pod_gang_key(pod)
            if g:
                prio = pod.spec.priority or 0
                gang_max_prio[g] = max(gang_max_prio.get(g, prio), prio)
        victims: dict[str, list[_Victim]] = {}
        for key, (node, opt) in ledger.items():
            pod = pods.get(key)
            if pod is None or node not in clones:
                continue
            prio = pod.spec.priority or 0
            gang = pod_gang_key(pod) or ""
            if prio > self.priority_ceiling:
                continue
            if gang and gang_max_prio.get(gang, 0) > self.priority_ceiling:
                continue
            tpu = [a for a in opt.allocs if a.needs_tpu]
            if len(tpu) != 1:
                continue  # multi-alloc pods: not movable (rare; skip)
            a = tpu[0]
            cs = clones[node]
            if a.whole:
                chips_freed = len(a.coords)
            else:
                # a fractional tenant only returns a WHOLE chip if it is
                # the sole tenant; co-tenanted chips gain nothing whole
                i = cs._slot.get(a.coords[0]) if a.coords else None
                if i is None:
                    continue
                sole = (
                    cs._core_avail[i] + a.core == cs._core_total[i]
                    and cs._hbm_avail[i] + a.hbm == cs._hbm_total[i]
                )
                if not sole:
                    continue
                chips_freed = len(a.coords)
            victims.setdefault(node, []).append(
                _Victim(
                    pod_key=key, uid=pod.metadata.uid, node=node,
                    option=opt, priority=prio, gang=gang,
                    whole=a.whole, chips=chips_freed,
                )
            )
        return clones, victims

    @staticmethod
    def _frag_of(clones: dict) -> dict:
        out = {}
        for n, cs in clones.items():
            idx, largest, _free = cs.fragmentation()  # ONE box scan/node
            out[n] = (idx, largest)
        return out

    @staticmethod
    def _feasible(clones: dict, count: int, members: int) -> bool:
        """Would the gang-plan kernel place all ``members`` now?  Walks
        the SAME per-topology-run stream the gang planner walks."""
        nodes = sorted(clones.items())
        remaining = members
        pos = 0
        while pos < len(nodes) and remaining > 0:
            topo = nodes[pos][1].topo
            end = pos
            while end < len(nodes) and nodes[end][1].topo == topo:
                end += 1
            free_lists = [
                tuple(cs._mesh_idx[i] for i in iter_bits(cs._free_bits))
                for _, cs in nodes[pos:end]
            ]
            placed = plan_gang_fallback(topo, free_lists, count, remaining)
            remaining -= len(placed)
            pos = end
        return remaining <= 0

    # -- planning -------------------------------------------------------------

    def _order_victims(self, pool: list, node_free: int, default) -> list:
        """Victim ordering for the planning rounds: ``default`` key (the
        built-in heuristic) unless a ``defrag`` policy is loaded, in
        which case victims order by DESCENDING policy score — the
        operator's preference for who moves first.  A policy that
        faults on ANY victim falls back to the built-in order for the
        WHOLE pool (journaled as a ``policy_fault`` by the plane) —
        mixing policy scores with built-in key values in one sort would
        order faulted victims arbitrarily, not by either rule."""
        plane = self.policies
        if plane is None or not plane.wants("defrag"):
            return sorted(pool, key=default)
        scores = {}
        for v in pool:
            s = plane.defrag_score({
                "chips": float(v.chips),
                "priority": float(v.priority),
                "whole": 1.0 if v.whole else 0.0,
                "is_gang": 1.0 if v.gang else 0.0,
                "node_free": float(node_free),
            })
            if s is None:
                return sorted(pool, key=default)
            scores[v.pod_key] = s
        return sorted(pool, key=lambda v: (-scores[v.pod_key], default(v)))

    def _place_victim(self, sched, v: _Victim, dest: ChipSet):
        """Re-place one victim on ``dest`` (a round clone: placements
        already applied, evictions NOT — so only round-start-free chips
        are eligible, which is what keeps rounds acyclic).  Returns the
        new Option or None."""
        if v.whole:
            found = best_whole_box(dest, v.chips)
            if found is None:
                return None
            coords, contiguous = found
            return _rebuild_option(v.option, coords, contiguous)
        # fractional: the engine's own rater picks the chip (binpack
        # prefers shared chips, preserving whole-free ones)
        req = request_from_option(v.option, v.pod_key, v.uid)
        opt = dest.trade(req, sched.rater)
        if opt is None:
            return None
        a = next(x for x in opt.allocs if x.needs_tpu)
        return _rebuild_option(v.option, a.coords, a.contiguous)

    def _plan_unblock_round(
        self, sched, clones, victims, count: int, budget: int
    ) -> list:
        """One round of cross-node consolidation toward fitting a
        ``count``-chip member: top up the node with the SMALLEST deficit
        by moving its cheapest victims onto nodes that can absorb them
        without creating a new deficit.  Returns the round's moves
        (possibly empty = stuck)."""
        free = {n: cs.free_count() for n, cs in clones.items()}
        targets = sorted(
            (n for n in clones if 0 < count - free[n]),
            key=lambda n: (count - free[n], n),
        )
        moves: list[Move] = []
        evictions: list[tuple[str, Option]] = []
        for target in targets:
            if budget - len(moves) <= 0:
                break
            deficit = count - free[target]
            pool = self._order_victims(
                victims.get(target, []), free[target], lambda v: -v.chips
            )
            chosen: list[_Victim] = []
            for v in pool:
                if deficit <= 0:
                    break
                if v.chips <= deficit:
                    chosen.append(v)
                    deficit -= v.chips
            if deficit > 0:
                # overshoot fallback: smallest victim that closes it alone
                rest = [v for v in pool if v not in chosen]
                closer = sorted(
                    (v for v in rest if v.chips >= deficit),
                    key=lambda v: v.chips,
                )
                if closer:
                    chosen.append(closer[0])
                    deficit = 0
            if deficit > 0:
                continue  # this node cannot be topped up; try the next
            placed_all = True
            round_moves: list[Move] = []
            for v in chosen:
                if budget - len(moves) - len(round_moves) <= 0:
                    placed_all = False
                    break
                # destination: smallest-free node that fits (keeps the
                # big free pools intact for members), never the target —
                # and never a node that is itself a viable member host
                # which this placement would drop below the member size
                # (destroying a viable host is how consolidation
                # ping-pongs: the next round would target that node and
                # push the victim straight back)
                dests = sorted(
                    (
                        n for n in clones
                        if n != target and not (
                            clones[n].free_count() >= count
                            and clones[n].free_count() - v.chips < count
                        )
                    ),
                    key=lambda n: (clones[n].free_count(), n),
                )
                new_opt = None
                for d in dests:
                    new_opt = self._place_victim(sched, v, clones[d])
                    if new_opt is not None:
                        dest_name = d
                        break
                if new_opt is None:
                    placed_all = False
                    break
                clones[dest_name].transact(new_opt)  # placement: immediate
                round_moves.append(
                    Move(
                        pod_key=v.pod_key, uid=v.uid, from_node=target,
                        to_node=dest_name, old=v.option, new=new_opt,
                        chips=v.chips, priority=v.priority, gang=v.gang,
                    )
                )
            if not placed_all:
                # roll the simulation back for this target's partial set
                for m in reversed(round_moves):
                    clones[m.to_node].cancel(m.new)
                continue
            moves.extend(round_moves)
            for v in chosen:
                evictions.append((target, v.option))
                victims[target] = [
                    x for x in victims[target] if x.pod_key != v.pod_key
                ]
            break  # one target per round: its eviction lands at round end
        # evictions apply at round END — freed chips become destinations
        # only in the NEXT round (the acyclicity rule)
        for node, opt in evictions:
            if clones[node].can_cancel(opt):
                clones[node].cancel(opt)
        return moves

    def _plan_compact_round(
        self, sched, clones, victims, budget: int
    ) -> list:
        """Intra-node compaction: re-place whole-chip victims into spots
        that strictly grow the node's largest free contiguous box.  Only
        round-start-free chips are eligible destinations (the victim's
        own chips stay charged in the simulation until round end), so
        the move is executable with the add-before-forget transaction."""
        moves: list[Move] = []
        evictions: list[tuple[str, Option]] = []
        for node in sorted(clones):
            cs = clones[node]
            idx, largest, _free = cs.fragmentation()
            if idx <= self.threshold:
                continue
            for v in self._order_victims(
                victims.get(node, []), cs.free_count(), lambda v: v.chips
            ):
                if len(moves) >= budget:
                    return self._apply_evictions(clones, evictions, moves)
                if not v.whole:
                    continue
                found = best_whole_box(cs, v.chips)
                if found is None:
                    continue
                coords, contiguous = found
                if set(coords) & set(
                    c for a in v.option.allocs for c in a.coords
                ):
                    continue  # self-overlap cannot happen (own chips busy)
                sim = cs.clone()
                new_opt = _rebuild_option(v.option, coords, contiguous)
                sim.transact(new_opt)
                sim.cancel(v.option)
                if sim.largest_free_box() <= largest:
                    continue  # not an improvement; skip
                cs.transact(new_opt)
                evictions.append((node, v.option))
                victims[node] = [
                    x for x in victims[node] if x.pod_key != v.pod_key
                ]
                moves.append(
                    Move(
                        pod_key=v.pod_key, uid=v.uid, from_node=node,
                        to_node=node, old=v.option, new=new_opt,
                        chips=v.chips, priority=v.priority, gang=v.gang,
                    )
                )
                break  # one move per node per round; re-evaluate next round
        return self._apply_evictions(clones, evictions, moves)

    @staticmethod
    def _apply_evictions(clones, evictions, moves):
        for node, opt in evictions:
            if clones[node].can_cancel(opt):
                clones[node].cancel(opt)
        return moves

    def plan(self, sched, want: Optional[tuple] = None) -> DefragPlan:
        """Compute a migration plan on clones (no live state touched).

        ``want=(chips_per_member, members)`` plans cross-node
        consolidation until that gang shape fits, then spends any
        remaining move budget compacting over-threshold nodes; without
        ``want`` it is compaction-only."""
        clones, victims = self._snapshot(sched)
        plan = DefragPlan(
            want=want,
            reason="unblock" if want else "threshold",
            frag_before=self._frag_of(clones),
        )
        budget = self.max_moves
        if want is not None:
            count, members = want
            plan.feasible_before = self._feasible(clones, count, members)
            total_free = sum(cs.free_count() for cs in clones.values())
            if plan.feasible_before or total_free < count * members:
                # already fits (nothing to do) or CANNOT fit no matter
                # how chips are shuffled (migration conserves free
                # chips) — planning consolidation would only churn
                plan.feasible_after = plan.feasible_before
            else:
                rounds = 0
                while (
                    budget > 0
                    and rounds < self.max_rounds
                    and not self._feasible(clones, count, members)
                ):
                    moves = self._plan_unblock_round(
                        sched, clones, victims, count, budget
                    )
                    if not moves:
                        break  # stuck: no victim/destination combo left
                    plan.rounds.append(moves)
                    budget -= len(moves)
                    rounds += 1
                plan.feasible_after = self._feasible(clones, count, members)
                if plan.rounds and not plan.feasible_after:
                    # partial consolidation that does NOT unblock the
                    # gang is pure disruption (each executed move drains
                    # a live workload) — discard it and let the trailing
                    # compaction pass work on an untouched snapshot
                    DEFRAG_EVENTS.inc("unblock_plan_discarded")
                    plan.rounds = []
                    budget = self.max_moves
                    clones, victims = self._snapshot(sched)
        # compaction pass (threshold mode, or leftover budget after an
        # unblock): strictly-improving intra-node moves only
        rounds = 0
        while budget > 0 and rounds < self.max_rounds:
            moves = self._plan_compact_round(sched, clones, victims, budget)
            if not moves:
                break
            plan.rounds.append(moves)
            budget -= len(moves)
            rounds += 1
        plan.frag_after = self._frag_of(clones)
        return plan

    # -- execution ------------------------------------------------------------

    def _hook_drain(self, mv: Move) -> None:
        for h in self.hooks:
            try:
                h.drain(mv.pod_key, mv.from_node)
            except Exception:
                log.exception("defrag drain hook failed for %s", mv.pod_key)

    def _hook_resume(self, mv: Move) -> None:
        for h in self.hooks:
            try:
                h.resume(mv.pod_key, mv.to_node)
            except Exception:
                log.exception("defrag resume hook failed for %s", mv.pod_key)

    def _execute(self, sched, plan: DefragPlan) -> dict:
        """Run a plan's moves round-by-round as journaled evict→rebind
        transactions.  All-or-nothing: any failure reverses every
        executed move with a compensating migration before raising."""
        nodes = sorted(
            {m.from_node for m in plan.moves()}
            | {m.to_node for m in plan.moves()}
        )
        for n in nodes:
            sched.cordon(n, ttl_s=self.cordon_ttl_s)
        executed: list[Move] = []
        try:
            for rnd in plan.rounds:
                for mv in rnd:
                    ns, _, name = mv.pod_key.partition("/")
                    pod = self.clientset.get_pod(ns, name)
                    if pod.metadata.uid != mv.uid or pod.is_completed():
                        raise RuntimeError(
                            f"plan stale: pod {mv.pod_key} changed"
                        )
                    self._hook_drain(mv)
                    try:
                        sched.migrate_pod(
                            pod, mv.from_node, mv.to_node, mv.old, mv.new,
                            source="defrag",
                        )
                    finally:
                        self._hook_resume(mv)
                    executed.append(mv)
                    DEFRAG_EVENTS.inc("move_executed")
        except Exception as e:
            DEFRAG_EVENTS.inc("round_failed")
            for mv in reversed(executed):
                # compensating move, with the SAME drain/resume hook
                # bracketing as the forward path — the one-chunk loss
                # bound holds for rollbacks too
                rb = Move(
                    pod_key=mv.pod_key, uid=mv.uid,
                    from_node=mv.to_node, to_node=mv.from_node,
                    old=mv.new, new=mv.old, chips=mv.chips,
                    priority=mv.priority, gang=mv.gang,
                )
                try:
                    ns, _, name = mv.pod_key.partition("/")
                    pod = self.clientset.get_pod(ns, name)
                    self._hook_drain(rb)
                    try:
                        sched.migrate_pod(
                            pod, rb.from_node, rb.to_node, rb.old, rb.new,
                            source="defrag_rollback",
                        )
                    finally:
                        self._hook_resume(rb)
                    DEFRAG_EVENTS.inc("move_rolled_back")
                except Exception:
                    DEFRAG_EVENTS.inc("rollback_failed")
                    log.exception(
                        "defrag rollback of %s failed — state may need a "
                        "journal replay audit", mv.pod_key,
                    )
            raise RuntimeError(f"defrag round failed (rolled back): {e}") from e
        finally:
            for n in nodes:
                sched.uncordon(n)
        self._moves_executed += len(executed)
        return {"executed": len(executed)}

    def preview(self, sched=None, want: Optional[tuple] = None) -> dict:
        """Non-blocking dry plan for ``/debug/defrag``: never parks
        behind an executing round (whose per-move drains can take
        seconds each — the observability endpoint must stay responsive
        exactly then), and touches no telemetry or ``last_result``."""
        sched = sched if sched is not None else self.engines[0]
        if not self._lock.acquire(blocking=False):
            return {"in_flight": True, "dry_run": True, "moves": 0}
        try:
            plan = self.plan(sched, want=want)
        finally:
            self._lock.release()
        result = plan.to_dict()
        result["dry_run"] = True
        result["executed"] = 0
        return result

    def run_round(
        self,
        sched=None,
        want: Optional[tuple] = None,
        dry_run: bool = False,
        min_interval_guard: bool = False,
    ) -> dict:
        """Plan (and unless ``dry_run``, execute) one defrag round.
        Returns the plan + execution summary as a JSON-ready dict.

        ``min_interval_guard`` re-checks the rate limiter INSIDE the
        planner lock (try_unblock's pre-check races siblings: two
        members can both read a stale ``_last_round`` while the first
        round is still executing) — a guarded call that lost the race
        returns ``{"rate_limited": True}`` instead of a second round."""
        sched = sched if sched is not None else self.engines[0]
        t0 = time.perf_counter()
        with self._lock:
            if (
                min_interval_guard
                and not dry_run
                and self.clock() - self._last_round < self.min_interval_s
            ):
                DEFRAG_EVENTS.inc("unblock_rate_limited")
                return {"rate_limited": True, "dry_run": False, "executed": 0}
            plan = self.plan(sched, want=want)
            result = plan.to_dict()
            result["dry_run"] = dry_run
            result["executed"] = 0
            if dry_run:
                # simulation only: no telemetry, no last_result — a
                # polled /defrag/run preview must not clobber the record
                # of the last REAL round or pollute the round histogram
                result["round_ms"] = round(
                    (time.perf_counter() - t0) * 1000, 3
                )
                return result
            DEFRAG_EVENTS.inc("round_planned")
            # stamp BEFORE executing: failed (rolled-back) and no-op
            # rounds must count against the rate limiter too, or a
            # persistently-failing round lets every gang-filter retry
            # thrash the cluster with full execute+rollback cycles
            self._last_round = self.clock()
            if plan.moves():
                result["executed"] = self._execute(sched, plan)["executed"]
                self._rounds_run += 1
                DEFRAG_EVENTS.inc("round_executed")
                DEFRAG_RECOVERED.set(
                    value=float(plan.recovered_submesh_chips())
                )
                # refresh the gauges' snapshot so /scheduler/status and
                # the next detection pass see post-round reality
                try:
                    sched._refresh_frag_gauges()
                except Exception:
                    pass
            else:
                DEFRAG_EVENTS.inc("round_noop")
            result["round_ms"] = round((time.perf_counter() - t0) * 1000, 3)
            DEFRAG_ROUND.observe(value=time.perf_counter() - t0)
            self._last_result = result
            return result

    # -- admission-retry hook (gang filter) -----------------------------------

    @staticmethod
    def _want_from_request(req) -> Optional[tuple]:
        """(chips_per_member, members) for a homogeneous single
        whole-chip-unit request (the SPMD gang shape), else None."""
        tpu = [u for u in req.units if u.needs_tpu]
        if len(tpu) != 1 or not tpu[0].wants_whole_chips:
            return None
        members = req.gang_size if req.gang_size > 1 else 1
        return tpu[0].chip_count, members

    def try_unblock(self, sched, req) -> bool:
        """Gang-filter admission retry: in ``auto`` mode, run one
        unblocking round for the rejected shape.  Returns True iff at
        least one move executed (the caller then re-filters).  Rate
        limited by ``min_interval_s`` so a stream of infeasible gangs
        cannot thrash the cluster with migrations."""
        if self.mode != "auto":
            return False
        if not self._is_leader():
            return False  # standbys never migrate (HA split-brain)
        want = self._want_from_request(req)
        if want is None:
            return False
        # probe first: acquiring the planner lock PARKS behind any round
        # in flight (a sibling member's), so when the shape already fits
        # — that round just unblocked it, or the filter failure was a
        # stale-plan/cordon race — the refilter succeeds without a new
        # round and without tripping the rate limiter.  Chip-only clones:
        # a permanently-infeasible gang re-filters every scheduling
        # cycle, and this path must not pay a per-pod clientset scan
        with self._lock:
            if self._feasible(self._chip_clones(sched), *want):
                return True
        now = self.clock()
        if now - self._last_round < self.min_interval_s:
            DEFRAG_EVENTS.inc("unblock_rate_limited")
            return False
        try:
            # guarded: the pre-check above races sibling members (both
            # read _last_round before either round stamps it); the
            # in-lock re-check makes the loser a no-op
            result = self.run_round(
                sched=sched, want=want, min_interval_guard=True
            )
        except RuntimeError:
            return False  # round rolled back; nothing to retry against
        if result.get("rate_limited"):
            return False
        # a refilter can only succeed when the simulated end state fits
        # the gang; executed compaction moves alone are not that (and a
        # plan that could not reach feasibility was discarded unexecuted)
        if result.get("feasible_after"):
            DEFRAG_EVENTS.inc("unblock_retry")
            return True
        return False

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        frag = {}
        cordons: dict[str, float] = {}
        for sched in self.engines:
            try:
                for n, (idx, largest) in sched.frag_snapshot().items():
                    frag[n] = {
                        "index": idx, "largest_free_submesh_chips": largest,
                    }
                cordons.update(sched.prune_cordons())
            except Exception:
                continue
        return {
            "mode": self.mode,
            "threshold": self.threshold,
            "max_moves": self.max_moves,
            "max_rounds": self.max_rounds,
            "priority_ceiling": self.priority_ceiling,
            "rounds_run": self._rounds_run,
            "moves_executed": self._moves_executed,
            "cordoned": sorted(cordons),
            "nodes": dict(sorted(frag.items())),
            "last_result": self._last_result,
        }
