"""Migration hooks: bracket each defrag move with the serving plane's
drain / elastic-resume path.

A live migration re-homes a pod's chips while its workload may be
mid-decode.  The serving engine already owns the two halves of the
story: graceful drain (stop admitting, let the in-flight fused chunk
finish — ``server.inference.drain``) and elastic resume (a spilled or
re-admitted request resumes token-identically; under the overlapped
pipeline a released slot discards AT MOST the one in-flight chunk).
The planner calls ``drain(pod, node)`` before each move and
``resume(pod, node)`` after (including on the failure path), so a
migrated serving pod loses at most one in-flight chunk and re-admits
exactly where it stopped.

The SAME contract brackets gang RESIZES (fleet/resize.py): a membership
change reshards the SPMD gang, so every member is drained at a chunk
boundary before the membership transaction and resumed after — the
per-moved-pod bound extends member-wise to resharding (each paused
member loses at most its one in-flight chunk; greedy streams continue
token-identically, which tests/test_serve_overlap.py pins).

This module is deliberately jax-free (duck-typed against the
``EngineLoop`` surface) so the scheduler plane — and its smoke-tier
tests — never import the model stack.
"""

from __future__ import annotations

import logging
import time

log = logging.getLogger("tpu-scheduler")


class MigrationHook:
    """No-op base: a hook may veto nothing — migration proceeds either
    way (the chip-state transaction is safe regardless); hooks only
    bound how much in-flight work the move costs."""

    def drain(self, pod_key: str, node: str) -> bool:
        """Called BEFORE the pod's allocation moves.  Return True when
        the workload is quiesced (best-effort; False = proceed anyway,
        the overlap pipeline bounds the loss to one chunk)."""
        return True

    def resume(self, pod_key: str, node: str) -> None:
        """Called AFTER the move (success or rollback): re-open
        admissions / resume the workload."""


class CallbackHook(MigrationHook):
    """Adapter for tests and external agents: plain callables."""

    def __init__(self, drain_fn=None, resume_fn=None):
        self._drain = drain_fn
        self._resume = resume_fn

    def drain(self, pod_key: str, node: str) -> bool:
        if self._drain is not None:
            return bool(self._drain(pod_key, node))
        return True

    def resume(self, pod_key: str, node: str) -> None:
        if self._resume is not None:
            self._resume(pod_key, node)


class ServingEngineHook(MigrationHook):
    """Drain/resume a colocated serving ``EngineLoop`` (duck-typed:
    needs ``loop.engine`` with ``draining``/``_work`` and
    ``loop.drained``/``http_inflight`` — the exact surface
    ``server.inference.drain`` drives).

    drain: flips the engine into draining (new submits 503), wakes the
    parked loop, and waits up to ``timeout`` for the loop thread to
    observe idle — the in-flight fused chunk finishes, nothing after it
    dispatches, so the move costs at most that one chunk.
    resume: the elastic-resume half — re-opens admissions and clears the
    drained latch; queued/re-admitted requests continue token-identically
    (the engine's spill/resume machinery owns exactness).
    """

    def __init__(self, loop, timeout: float = 10.0):
        self.loop = loop
        self.timeout = timeout

    def drain(self, pod_key: str, node: str) -> bool:
        loop = self.loop
        engine = loop.engine
        deadline = time.monotonic() + self.timeout  # ONE budget for both waits
        engine.draining = True
        engine._work.set()  # wake a parked loop so it observes the drain
        ok = loop.drained.wait(self.timeout)
        while time.monotonic() < deadline and loop.http_inflight > 0:
            time.sleep(0.01)
        if not ok:
            log.warning(
                "defrag drain of %s timed out after %.1fs; migrating "
                "anyway (at most one in-flight chunk is lost)",
                pod_key, self.timeout,
            )
        return ok

    def resume(self, pod_key: str, node: str) -> None:
        loop = self.loop
        loop.engine.draining = False
        loop.drained.clear()
        loop.engine._work.set()  # wake the loop to resume admissions


class RouterDrainHook(MigrationHook):
    """Fleet-router bracketing for a move/resize: flip the pod's replica
    to draining in the router's ReplicaSet before the move (new sessions
    route elsewhere the moment the engine pauses) and restore it after.
    ``pod_to_replica`` maps pod keys to replica names (identity mapping
    when omitted — replicas named after their pods).  Duck-typed against
    ``fleet.router.ReplicaSet``; jax-free like the rest of this
    module."""

    def __init__(self, replicas, pod_to_replica=None):
        self.replicas = replicas
        self.pod_to_replica = pod_to_replica or (lambda pod_key: pod_key)

    def drain(self, pod_key: str, node: str) -> bool:
        name = self.pod_to_replica(pod_key)
        if name:
            self.replicas.drain(name, reason=f"migration/resize on {node}")
        return True

    def resume(self, pod_key: str, node: str) -> None:
        name = self.pod_to_replica(pod_key)
        if name:
            self.replicas.undrain(name, reason="migration/resize complete")
