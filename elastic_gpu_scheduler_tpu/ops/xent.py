"""Vocab-chunked softmax cross-entropy: the (B·S, V) logits tensor never
materializes.

Motivation (TPU memory, not FLOPs): for the bench flagship (B=8, S=2048,
V=32000) the fp32 logits buffer is 2.1 GB — the single largest activation
in the train step — and at long context (S=32k) it simply does not fit.
The streamed flash kernels (ops/attention.py) already remove the O(S²)
attention buffer; this op removes the O(S·V) loss buffer, so end-to-end
long-context training is bounded by O(S·D) activations only.

Design (one ``lax.scan`` over vocab chunks, everything MXU-shaped):

- forward: for each chunk c of C columns, logits_c = x @ W[:, c] in bf16
  with fp32 accumulation, folded into an ONLINE logsumexp (running max m
  and scaled sum s — the flash-attention recipe applied to the vocab axis)
  plus the gold logit picked up when the target id lands in the chunk.
- backward: recompute logits_c per chunk (2·N·D·C bf16 FLOPs — the price
  of not saving them), form d_logits_c = (softmax_c − onehot_c)·ḡ/N in
  fp32, cast to bf16, and contract immediately: dx += d_logits_c @ W_cᵀ
  (fp32 carry), dW_c = xᵀ @ d_logits_c (each chunk owns its columns, so
  dW needs no cross-chunk accumulation).  Peak extra memory is one
  (N, C) chunk.

The custom VJP exists because autodiff of the scanned forward would save
every chunk's logits as residuals — exactly the buffer this op deletes.
Residuals here: x, W, targets (+ their validity mask), and the (N,)
logsumexp.

Targets outside [0, V) are ignored (torch ``ignore_index`` convention):
zero loss contribution, zero gradient, excluded from the mean's
denominator — same semantics as the dense path.

Numerics: identical reduction tree to the dense path up to fp32 rounding
(both accumulate in fp32); grads match the dense reference to bf16
tolerance (tests/test_xent.py).

Sharding note: under a mesh this composes with data/fsdp/seq-sharded x
(chunking is over V, which those leave whole).  With a tensor-sharded
unembed (parallel/sharding.py: (fsdp, tensor)) use
``chunked_softmax_xent_tp``: a ``shard_map`` manual ONLY over the tensor
axis (data/fsdp/seq stay GSPMD-auto, the pipeline.py composition
pattern) in which each tensor rank scans its own V/T columns in
n_chunks/T chunks and the online logsumexp merges across ranks with one
pmax + psum — the unembed is never all-gathered and the (N, V) logits
still never materialize.

No reference analogue (the reference is a scheduler, SURVEY §2 #19); this
is standard equipment for long-context training frameworks (same role as
fused/linear-CE kernels in GPU stacks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.jaxcompat import pcast


def _chunk_w(w: jax.Array, n_chunks: int) -> jax.Array:
    """(D, V) → (n_chunks, D, C) scan xs."""
    D, V = w.shape
    if n_chunks <= 0 or V % n_chunks:
        raise ValueError(f"vocab {V} not divisible by n_chunks {n_chunks}")
    C = V // n_chunks
    return w.reshape(D, n_chunks, C).transpose(1, 0, 2)


def _fwd_scan_parts(x2d, w, targets, n_chunks, vary_axis=None):
    """Online logsumexp pieces + gold-logit pickup over vocab chunks.

    Returns (m (N,) running max, s (N,) scaled sum, gold (N,)) — all f32,
    combinable across vocab shards (pmax/psum) before logz = m + log(s).
    ``targets`` outside [0, V) pick up nothing (their gold stays 0), which
    is what lets a tensor rank pass locally-shifted ids straight in.
    ``vary_axis``: manual mesh axis the carry varies over (the TP path —
    each rank's w shard differs, so scan-carry vma typing needs the init
    marked varying too)."""
    N = x2d.shape[0]
    V = w.shape[1]
    C = V // n_chunks
    wc = _chunk_w(w, n_chunks)

    def body(carry, inp):
        m, s, gold = carry
        w_c, idx = inp
        logits = jnp.dot(
            x2d, w_c, preferred_element_type=jnp.float32
        )  # (N, C) f32
        m_c = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_c)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        local = targets - idx * C
        in_chunk = (local >= 0) & (local < C)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, C - 1)[:, None], axis=-1
        )[:, 0]
        gold = gold + jnp.where(in_chunk, picked, 0.0)
        return (m_new, s, gold), None

    init = (
        jnp.full((N,), -jnp.inf, jnp.float32),
        jnp.zeros((N,), jnp.float32),
        jnp.zeros((N,), jnp.float32),
    )
    if vary_axis is not None:
        init = jax.tree.map(
            lambda a: pcast(a, vary_axis, to="varying"), init
        )
    (m, s, gold), _ = lax.scan(body, init, (wc, jnp.arange(n_chunks)))
    return m, s, gold


def _fwd_scan(x2d, w, targets, n_chunks):
    """Online logsumexp + gold-logit pickup; returns (logz (N,), gold (N,))."""
    m, s, gold = _fwd_scan_parts(x2d, w, targets, n_chunks)
    return m + jnp.log(s), gold


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_softmax_xent(
    x: jax.Array, w: jax.Array, targets: jax.Array, n_chunks: int
) -> jax.Array:
    """Mean next-token CE of ``(x @ w, targets)`` without materializing
    the logits.

    x: (..., D) hidden states (bf16 or f32); w: (D, V) unembedding;
    targets: (...) int32.  V must divide evenly by ``n_chunks``.
    """
    return _xent_fwd(x, w, targets, n_chunks)[0]


def _xent_fwd(x, w, targets, n_chunks):
    x2d = x.reshape(-1, x.shape[-1])
    # ids outside [0, V) are IGNORED (masked out of sum and denominator) —
    # the torch ignore_index convention, identical to the dense path
    # (models/train.py cross_entropy_loss), so the two loss modes agree on
    # ANY input, not just well-formed ones
    V = w.shape[1]
    t_raw = targets.reshape(-1)
    valid = (t_raw >= 0) & (t_raw < V)
    t = jnp.clip(t_raw, 0, V - 1)
    logz, gold = _fwd_scan(x2d, w, t, n_chunks)
    n_valid = jnp.maximum(jnp.sum(valid), 1)
    loss = jnp.sum(jnp.where(valid, logz - gold, 0.0)) / n_valid
    return loss, (x, w, t, valid, logz)


def _bwd_scan(x2d, w, t, logz, scale, n_chunks, vary_axis=None):
    """Shared backward chunk loop: recompute logits per chunk, form
    d_logits = (softmax − masked onehot)·scale against a (possibly GLOBAL)
    ``logz``, and contract immediately.  Returns (dx2d f32 (N, D),
    dw (D, V)).  ``t`` may be locally-shifted (TP): ids outside any chunk
    get no onehot, only the softmax term — their gold column lives on
    another rank.  ``vary_axis`` marks the dx carry varying over a manual
    mesh axis (TP path, same vma reason as _fwd_scan_parts)."""
    N, D = x2d.shape
    V = w.shape[1]
    C = V // n_chunks
    wc = _chunk_w(w, n_chunks)

    def body(dx_acc, inp):
        w_c, idx = inp
        logits = jnp.dot(x2d, w_c, preferred_element_type=jnp.float32)
        p = jnp.exp(logits - logz[:, None])  # softmax columns of this chunk
        local = t - idx * C
        in_chunk = (local >= 0) & (local < C)
        onehot = (
            jax.nn.one_hot(jnp.clip(local, 0, C - 1), C, dtype=jnp.float32)
            * in_chunk[:, None]
        )
        d_logits = ((p - onehot) * scale[:, None]).astype(x2d.dtype)  # (N, C)
        dx_acc = dx_acc + jnp.dot(
            d_logits, w_c.T, preferred_element_type=jnp.float32
        )
        dw_c = jnp.dot(x2d.T, d_logits, preferred_element_type=jnp.float32)
        return dx_acc, dw_c.astype(w.dtype)

    init = jnp.zeros((N, D), jnp.float32)
    if vary_axis is not None:
        init = pcast(init, vary_axis, to="varying")
    dx2d, dwc = lax.scan(body, init, (wc, jnp.arange(n_chunks)))
    return dx2d, dwc.transpose(1, 0, 2).reshape(D, V)


def _xent_bwd(n_chunks, res, g):
    x, w, t, valid, logz = res
    x2d = x.reshape(-1, x.shape[-1])
    n_valid = jnp.maximum(jnp.sum(valid), 1)
    # per-token cotangent: masked positions get exactly zero gradient
    scale = (g / n_valid) * valid.astype(jnp.float32)  # (N,)
    dx2d, dw = _bwd_scan(x2d, w, t, logz, scale, n_chunks)
    dx = dx2d.astype(x.dtype).reshape(x.shape)
    return dx, dw, None


chunked_softmax_xent.defvjp(_xent_fwd, _xent_bwd)


# -- tensor-parallel variant -------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _xent_tp_shard(x, w_local, targets, n_chunks_local, axis, v_global):
    """Per-shard body: runs on one tensor rank inside ``shard_map`` with
    ``w_local`` = this rank's (D, V/T) unembed columns.  Collectives over
    ``axis`` merge the online logsumexp; the custom VJP keeps the backward
    from saving per-chunk logits (same reason as the single-rank op)."""
    return _xent_tp_fwd(x, w_local, targets, n_chunks_local, axis, v_global)[0]


def _xent_tp_fwd(x, w_local, targets, n_chunks_local, axis, v_global):
    x2d = x.reshape(-1, x.shape[-1])
    v_local = w_local.shape[1]
    t_raw = targets.reshape(-1)
    valid = (t_raw >= 0) & (t_raw < v_global)
    # shift ids into this rank's column space: off-rank ids fall outside
    # [0, v_local) and pick up NO gold (see _fwd_scan_parts) — the psum
    # then contributes each token's gold logit exactly once
    t_local = jnp.clip(t_raw, 0, v_global - 1) - lax.axis_index(axis) * v_local
    m, s, gold = _fwd_scan_parts(
        x2d, w_local, t_local, n_chunks_local, vary_axis=axis
    )
    m_g = lax.pmax(m, axis)
    s_g = lax.psum(s * jnp.exp(m - m_g), axis)
    logz = m_g + jnp.log(s_g)
    gold_g = lax.psum(gold, axis)
    n_valid = jnp.maximum(jnp.sum(valid), 1)
    loss = jnp.sum(jnp.where(valid, logz - gold_g, 0.0)) / n_valid
    return loss, (x, w_local, t_local, valid, logz)


def _xent_tp_bwd(n_chunks_local, axis, v_global, res, g):
    x, w_local, t_local, valid, logz = res
    x2d = x.reshape(-1, x.shape[-1])
    n_valid = jnp.maximum(jnp.sum(valid), 1)
    scale = (g / n_valid) * valid.astype(jnp.float32)  # (N,)
    # logz is GLOBAL and t_local is rank-shifted, so _bwd_scan yields this
    # rank's slice of the global softmax gradient (off-rank gold targets
    # get only the softmax term — their onehot column lives elsewhere)
    dx2d, dw = _bwd_scan(
        x2d, w_local, t_local, logz, scale, n_chunks_local, vary_axis=axis
    )
    # x is replicated across the tensor axis; its cotangent is the sum of
    # every rank's partial (each rank touched its own columns of W)
    dx2d = lax.psum(dx2d, axis)
    dx = dx2d.astype(x.dtype).reshape(x.shape)
    return dx, dw, None


_xent_tp_shard.defvjp(_xent_tp_fwd, _xent_tp_bwd)


def chunked_softmax_xent_tp(
    x: jax.Array,
    w: jax.Array,
    targets: jax.Array,
    n_chunks: int,
    mesh,
    axis: str = "tensor",
) -> jax.Array:
    """Tensor-parallel ``chunked_softmax_xent``: the V-sharded unembed
    stays sharded (never all-gathered) and the (N, V) logits never
    materialize — the composition models/train.py refused before round 3.

    ``shard_map`` is manual ONLY over ``axis`` (parallel/pipeline.py's
    composition pattern): batch/fsdp/seq shardings of ``x``/``targets``
    remain GSPMD-auto, so this drops into any mesh the train step runs
    on.  Each rank scans its V/T columns in ``n_chunks``/T chunks; one
    pmax + two psums merge the online logsumexp and gold logits; the
    backward psums dx (x is tensor-replicated) and keeps dW rank-local.
    """
    from jax.sharding import PartitionSpec as P

    T = mesh.shape[axis]
    V = w.shape[1]
    if V % T:
        raise ValueError(f"vocab {V} not divisible by {axis}={T}")
    if n_chunks % T or (V // T) % (n_chunks // T):
        raise ValueError(
            f"xent_chunks={n_chunks} must be a multiple of {axis}={T} with "
            f"V/{axis} = {V // T} divisible by chunks/{axis} = "
            f"{n_chunks // T} (each rank scans its shard in that many "
            "chunks)"
        )

    def shard_body(x, w_local, targets):
        # positional bind: custom_vjp nondiff args may not pass by keyword
        return _xent_tp_shard(x, w_local, targets, n_chunks // T, axis, V)

    from ..utils.jaxcompat import shard_map

    fn = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(None, axis), P()),
        out_specs=P(),
        axis_names={axis},
    )
    return fn(x, w, targets)
