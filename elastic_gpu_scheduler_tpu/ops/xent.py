"""Vocab-chunked softmax cross-entropy: the (B·S, V) logits tensor never
materializes.

Motivation (TPU memory, not FLOPs): for the bench flagship (B=8, S=2048,
V=32000) the fp32 logits buffer is 2.1 GB — the single largest activation
in the train step — and at long context (S=32k) it simply does not fit.
The streamed flash kernels (ops/attention.py) already remove the O(S²)
attention buffer; this op removes the O(S·V) loss buffer, so end-to-end
long-context training is bounded by O(S·D) activations only.

Design (one ``lax.scan`` over vocab chunks, everything MXU-shaped):

- forward: for each chunk c of C columns, logits_c = x @ W[:, c] in bf16
  with fp32 accumulation, folded into an ONLINE logsumexp (running max m
  and scaled sum s — the flash-attention recipe applied to the vocab axis)
  plus the gold logit picked up when the target id lands in the chunk.
- backward: recompute logits_c per chunk (2·N·D·C bf16 FLOPs — the price
  of not saving them), form d_logits_c = (softmax_c − onehot_c)·ḡ/N in
  fp32, cast to bf16, and contract immediately: dx += d_logits_c @ W_cᵀ
  (fp32 carry), dW_c = xᵀ @ d_logits_c (each chunk owns its columns, so
  dW needs no cross-chunk accumulation).  Peak extra memory is one
  (N, C) chunk.

The custom VJP exists because autodiff of the scanned forward would save
every chunk's logits as residuals — exactly the buffer this op deletes.
Residuals here: x, W, targets (+ their validity mask), and the (N,)
logsumexp.

Targets outside [0, V) are ignored (torch ``ignore_index`` convention):
zero loss contribution, zero gradient, excluded from the mean's
denominator — same semantics as the dense path.

Numerics: identical reduction tree to the dense path up to fp32 rounding
(both accumulate in fp32); grads match the dense reference to bf16
tolerance (tests/test_xent.py).

Sharding note: under a mesh this composes with data/fsdp/seq-sharded x
(chunking is over V, which those leave whole).  With a tensor-sharded
unembed (parallel/sharding.py: (fsdp, tensor)) every chunk slice forces a
reshard — prefer the dense path when tensor > 1.

No reference analogue (the reference is a scheduler, SURVEY §2 #19); this
is standard equipment for long-context training frameworks (same role as
fused/linear-CE kernels in GPU stacks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _chunk_w(w: jax.Array, n_chunks: int) -> jax.Array:
    """(D, V) → (n_chunks, D, C) scan xs."""
    D, V = w.shape
    if n_chunks <= 0 or V % n_chunks:
        raise ValueError(f"vocab {V} not divisible by n_chunks {n_chunks}")
    C = V // n_chunks
    return w.reshape(D, n_chunks, C).transpose(1, 0, 2)


def _fwd_scan(x2d, w, targets, n_chunks):
    """Online logsumexp + gold-logit pickup over vocab chunks.

    Returns (logz (N,) f32, gold (N,) f32)."""
    N = x2d.shape[0]
    V = w.shape[1]
    C = V // n_chunks
    wc = _chunk_w(w, n_chunks)

    def body(carry, inp):
        m, s, gold = carry
        w_c, idx = inp
        logits = jnp.dot(
            x2d, w_c, preferred_element_type=jnp.float32
        )  # (N, C) f32
        m_c = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_c)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        local = targets - idx * C
        in_chunk = (local >= 0) & (local < C)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, C - 1)[:, None], axis=-1
        )[:, 0]
        gold = gold + jnp.where(in_chunk, picked, 0.0)
        return (m_new, s, gold), None

    init = (
        jnp.full((N,), -jnp.inf, jnp.float32),
        jnp.zeros((N,), jnp.float32),
        jnp.zeros((N,), jnp.float32),
    )
    (m, s, gold), _ = lax.scan(body, init, (wc, jnp.arange(n_chunks)))
    return m + jnp.log(s), gold


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_softmax_xent(
    x: jax.Array, w: jax.Array, targets: jax.Array, n_chunks: int
) -> jax.Array:
    """Mean next-token CE of ``(x @ w, targets)`` without materializing
    the logits.

    x: (..., D) hidden states (bf16 or f32); w: (D, V) unembedding;
    targets: (...) int32.  V must divide evenly by ``n_chunks``.
    """
    return _xent_fwd(x, w, targets, n_chunks)[0]


def _xent_fwd(x, w, targets, n_chunks):
    x2d = x.reshape(-1, x.shape[-1])
    # ids outside [0, V) are IGNORED (masked out of sum and denominator) —
    # the torch ignore_index convention, identical to the dense path
    # (models/train.py cross_entropy_loss), so the two loss modes agree on
    # ANY input, not just well-formed ones
    V = w.shape[1]
    t_raw = targets.reshape(-1)
    valid = (t_raw >= 0) & (t_raw < V)
    t = jnp.clip(t_raw, 0, V - 1)
    logz, gold = _fwd_scan(x2d, w, t, n_chunks)
    n_valid = jnp.maximum(jnp.sum(valid), 1)
    loss = jnp.sum(jnp.where(valid, logz - gold, 0.0)) / n_valid
    return loss, (x, w, t, valid, logz)


def _xent_bwd(n_chunks, res, g):
    x, w, t, valid, logz = res
    x2d = x.reshape(-1, x.shape[-1])
    N, D = x2d.shape
    V = w.shape[1]
    C = V // n_chunks
    wc = _chunk_w(w, n_chunks)
    n_valid = jnp.maximum(jnp.sum(valid), 1)
    # per-token cotangent: masked positions get exactly zero gradient
    scale = (g / n_valid) * valid.astype(jnp.float32)  # (N,)

    def body(dx_acc, inp):
        w_c, idx = inp
        logits = jnp.dot(x2d, w_c, preferred_element_type=jnp.float32)
        p = jnp.exp(logits - logz[:, None])  # softmax columns of this chunk
        local = t - idx * C
        in_chunk = (local >= 0) & (local < C)
        onehot = (
            jax.nn.one_hot(jnp.clip(local, 0, C - 1), C, dtype=jnp.float32)
            * in_chunk[:, None]
        )
        d_logits = ((p - onehot) * scale[:, None]).astype(x2d.dtype)  # (N, C)
        dx_acc = dx_acc + jnp.dot(
            d_logits, w_c.T, preferred_element_type=jnp.float32
        )
        dw_c = jnp.dot(x2d.T, d_logits, preferred_element_type=jnp.float32)
        return dx_acc, dw_c.astype(w.dtype)

    dx2d, dwc = lax.scan(
        body, jnp.zeros((N, D), jnp.float32), (wc, jnp.arange(n_chunks))
    )
    dw = dwc.transpose(1, 0, 2).reshape(D, V)
    dx = dx2d.astype(x.dtype).reshape(x.shape)
    return dx, dw, None


chunked_softmax_xent.defvjp(_xent_fwd, _xent_bwd)
