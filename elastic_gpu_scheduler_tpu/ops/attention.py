"""Flash attention: Pallas TPU kernel with a pure-JAX fallback.

The hot op of the flagship model (models/transformer.py).  TPU-first design
(/opt/skills/guides/pallas_guide.md): the forward kernel streams K/V through
VMEM, keeps a running (max, sum, acc) in fp32, hits the MXU with
``preferred_element_type=jnp.float32`` matmuls, and saves the per-row
logsumexp.  Differentiation uses ``jax.custom_vjp``: on TPU the backward is
two blockwise Pallas kernels (dQ over q-blocks, dK/dV over k-blocks) that
recompute p = exp(s − lse) per block — no (Sq, Sk) intermediate at any
context length; off-TPU the backward is an XLA einsum recompute.

No reference-parity obligation: the reference has no kernels (SURVEY §2 #19).
On non-TPU backends (tests run on CPU) the fallback implements identical
math, so the kernel is exercised in interpret mode and numerics are testable
everywhere.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# -- reference implementation (also the CPU fallback) ------------------------


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    window: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Returns (out, lse).  Shapes: q,k,v = (B, H, S, D); out same as q;
    lse = (B, H, S) logsumexp of scaled scores (the flash residual).
    ``window`` > 0 adds sliding-window masking: position q attends only to
    k in (q - window, q] (Mistral-style local attention)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    if causal or window > 0:
        sq, sk = q.shape[2], k.shape[2]
        q_ids = jnp.arange(sq)[:, None] + (sk - sq)
        k_ids = jnp.arange(sk)[None, :]
        mask = jnp.ones((sq, sk), dtype=bool)
        if causal:
            mask &= q_ids >= k_ids
        if window > 0:
            mask &= (q_ids - k_ids) < window
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    p = jnp.exp(logits - lse[..., None])
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    return out.astype(q.dtype), lse


# -- Pallas TPU kernel -------------------------------------------------------


def _flash_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, sm_scale,
                  causal, window=0, q_shift=0):
    """One (batch, head, q-block) program; streams K/V blocks from VMEM.

    Also emits the per-row logsumexp (the flash residual) so the Pallas
    backward kernels can recompute p = exp(s - lse) blockwise without ever
    materializing the (Sq, Sk) score matrix.

    ``q_shift`` = sk - sq aligns rectangular causal masks with
    ``mha_reference`` (query i corresponds to absolute position i + sk - sq,
    i.e. the queries are the LAST sq positions of the key sequence)."""
    import jax.experimental.pallas as pl

    block_q = q_ref.shape[2]
    head_dim = q_ref.shape[3]
    seq_k = k_ref.shape[2]

    # MXU inputs stay in the INPUT dtype (bf16 in the training path) with
    # fp32 accumulation via preferred_element_type — an fp32×fp32 MXU dot
    # runs ~8x slower on v5e than bf16-in/fp32-accum, and the cast was
    # costing exactly that.  sm_scale is applied to the fp32 scores, not to
    # q, so bf16 inputs lose nothing to pre-scaling.
    q = q_ref[0, 0]  # (block_q, d), native dtype

    q_block_idx = pl.program_id(2)
    q_offset = q_block_idx * block_q + q_shift

    num_k_blocks = seq_k // block_k
    start_block = 0
    if causal:
        # blocks entirely above the diagonal are fully masked — skip them
        # (the last visited block still applies the element-wise mask)
        num_k_blocks = jnp.minimum(
            num_k_blocks, pl.cdiv(q_offset + block_q, block_k)
        )
    if window > 0:
        # blocks entirely below the sliding window are also fully masked
        start_block = jnp.maximum(
            0, (q_offset - window + 1) // block_k
        )

    def body(j, carry):
        acc, m_i, l_i = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (block_q, block_k) fp32
        if causal or window > 0:
            s = _mask_boundary_only(s, q_offset, j * block_k, block_q,
                                    block_k, causal, window)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1))
        alpha = jnp.exp(m_i - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_i * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m_i, l_i = jax.lax.fori_loop(
        start_block, num_k_blocks, body, (acc0, m0, l0)
    )

    l_safe = jnp.where(l_i == 0.0, 1.0, l_i)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse carried as (..., block_q, 1): Mosaic requires the last two block
    # dims be (8k, 128k) or equal to the full array dims — a trailing
    # singleton satisfies that where a rank-3 (1, 1, block_q) tile cannot
    lse_ref[0, 0] = (m_i + jnp.log(l_safe))[:, None]



def _block_mask(q_offset, k_offset, block_q, block_k, causal, window):
    """Element mask for one (q-block, k-block) tile in GLOBAL coordinates."""
    q_ids = q_offset + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_ids = k_offset + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    keep = jnp.ones((block_q, block_k), jnp.bool_)
    if causal:
        keep &= q_ids >= k_ids
    if window > 0:
        keep &= (q_ids - k_ids) < window
    return keep


def _mask_boundary_only(s, q_offset, k_offset, block_q, block_k, causal,
                        window):
    """Apply the element mask ONLY on tiles that straddle a band boundary.

    A tile fully inside the causal/window band needs no masking at all —
    and on a (512, 512) fp32 tile the iota + compare + select chain is real
    VPU time on every visited block.  The band-interior test is two scalar
    compares; ``lax.cond`` keeps the masked path off the hot blocks
    (Mosaic lowers it to a scalar branch).
    """
    interior = True
    if causal:
        # every element satisfies q_ids >= k_ids
        interior = k_offset + block_k - 1 <= q_offset
    if window > 0:
        # and every element satisfies q_ids - k_ids < window
        interior = interior & (
            (q_offset + block_q - 1) - k_offset < window
        )
    if interior is True:  # statically maskless (not causal, no window)
        return s

    def masked(s):
        return jnp.where(
            _block_mask(q_offset, k_offset, block_q, block_k, causal, window),
            s, NEG_INF,
        )

    return jax.lax.cond(interior, lambda s: s, masked, s)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                  *, sm_scale, causal, window, q_shift, num_k_blocks):
    """One grid step = one (batch, head, q-block, k-block) tile.

    K/V are STREAMED one block per grid step (the k-block axis is the
    innermost grid dimension, which TPUs iterate sequentially), with the
    running (acc, max, sum) held in VMEM scratch across steps — so VMEM use
    is O(block), not O(S), and Pallas double-buffers the HBM fetches.  The
    logsumexp is emitted on the last k step (the flash residual the Pallas
    backward recomputes p from).

    ``q_shift`` = sk - sq aligns rectangular causal masks with
    ``mha_reference`` (query i corresponds to absolute position i + sk - sq,
    i.e. the queries are the LAST sq positions of the key sequence)."""
    import jax.experimental.pallas as pl

    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]
    j = pl.program_id(3)
    q_offset = pl.program_id(2) * block_q + q_shift
    k_offset = j * block_k

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # skip compute for tiles fully outside the causal/window band (their
    # blocks are still DMA'd — the grid is static — but the MXU work is not
    # done and the running stats are untouched)
    run = True
    if causal:
        run = k_offset < q_offset + block_q
    if window > 0:
        run = run & (k_offset + block_k > q_offset - window + 1)

    @pl.when(run)
    def _compute():
        # native-dtype MXU inputs, fp32 accumulation (see resident kernel)
        q = q_ref[0, 0]  # (block_q, d)
        k_blk = k_ref[0, 0]  # (block_k, d)
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (block_q, block_k) fp32
        if causal or window > 0:
            s = _mask_boundary_only(s, q_offset, k_offset, block_q, block_k,
                                    causal, window)
        m_i = m_ref[0]  # (block_q,)
        l_i = l_ref[0]
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1))
        alpha = jnp.exp(m_i - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_ref[0] = l_i * alpha + jnp.sum(p, axis=1)
        m_ref[0] = m_new
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_k_blocks - 1)
    def _finish():
        l_i = l_ref[0]
        l_safe = jnp.where(l_i == 0.0, 1.0, l_i)
        o_ref[0, 0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        # lse carried as (..., block_q, 1): Mosaic requires the last two
        # block dims be (8k, 128k) or equal to the full array dims — a
        # trailing singleton satisfies that
        lse_ref[0, 0] = (m_ref[0] + jnp.log(l_safe))[:, None]


def _fit_block(n: int, want: int) -> int:
    """Largest block ≤ want that divides n (halving down) — a 768-long
    sequence must not crash just because the preferred block is 512."""
    b = min(want, n)
    while b > 16 and n % b:
        b //= 2
    return b


# K/V (or Q/dO in the dkv backward) stay VMEM-RESIDENT across grid programs
# while they fit this budget — Mosaic skips re-DMA for unchanged block
# indices, so the resident kernels read each operand from HBM once per
# (batch, head) instead of once per q-block (measured ~3x faster at bench
# shapes).  Longer sequences fall back to the streamed kernels whose VMEM
# use is O(block) regardless of context length.
RESIDENT_VMEM_BYTES = 4 * 1024 * 1024


def _resident_fits(seq: int, d: int, itemsize: int) -> bool:
    return 2 * seq * d * itemsize <= RESIDENT_VMEM_BYTES


def _flash_forward_pallas(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret, window=0, return_lse=False,
                          resident=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _fit_block(sq, block_q)
    block_k = _fit_block(sk, block_k)
    assert sq % block_q == 0 and sk % block_k == 0, (
        f"seq lengths ({sq},{sk}) must be multiples of blocks ({block_q},{block_k})"
    )
    num_k_blocks = sk // block_k
    if resident is None:
        resident = _resident_fits(sk, d, k.dtype.itemsize)
    if resident:
        kernel = functools.partial(
            _flash_kernel_resident, block_k=block_k, sm_scale=sm_scale,
            causal=causal, window=window, q_shift=sk - sq,
        )
        out, lse = pl.pallas_call(
            kernel,
            grid=(b, h, sq // block_q),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)
                ),
                pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
                pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)
                ),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
                jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v)
    else:
        kernel = functools.partial(
            _flash_kernel, sm_scale=sm_scale, causal=causal, window=window,
            q_shift=sk - sq, num_k_blocks=num_k_blocks,
        )
        out, lse = pl.pallas_call(
            kernel,
            grid=(b, h, sq // block_q, num_k_blocks),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)
                ),
            ],
            out_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_q, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
                ),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
                jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((1, block_q), jnp.float32),
                pltpu.VMEM((1, block_q), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v)
    if return_lse:
        return out, lse[..., 0]
    return out


def _use_pallas() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# -- Pallas backward kernels (FlashAttention-2 style) ------------------------
#
# The backward never materializes the (Sq, Sk) score matrix: both kernels
# recompute p = exp(q·kᵀ·scale − lse) one block at a time from the saved
# logsumexp.  dQ parallelizes over q-blocks (streaming K/V); dK/dV
# parallelizes over k-blocks (streaming Q/dO) — each a separate pallas_call
# so neither needs atomics or cross-program reductions.


def _flash_bwd_dq_kernel_resident(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, block_k, sm_scale, causal, window, q_shift,
):
    import jax.experimental.pallas as pl

    block_q = q_ref.shape[2]
    seq_k = k_ref.shape[2]
    # native-dtype MXU inputs, fp32 accumulation (see forward kernel)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, :, 0]  # (block_q,) — stored with trailing singleton
    delta = delta_ref[0, 0, :, 0]
    q_offset = pl.program_id(2) * block_q + q_shift

    num_k_blocks = seq_k // block_k
    start_block = 0
    if causal:
        num_k_blocks = jnp.minimum(
            num_k_blocks, pl.cdiv(q_offset + block_q, block_k)
        )
    if window > 0:
        start_block = jnp.maximum(0, (q_offset - window + 1) // block_k)

    def body(j, dq_acc):
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal or window > 0:
            s = _mask_boundary_only(s, q_offset, j * block_k, block_q,
                                    block_k, causal, window)
        p = jnp.exp(s - lse[:, None])  # masked entries → exp(−inf) = 0
        dp = jax.lax.dot_general(
            do, v_blk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(k_blk.dtype)
        return dq_acc + jax.lax.dot_general(
            ds, k_blk, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(
        start_block, num_k_blocks, body,
        jnp.zeros((block_q, q.shape[1]), jnp.float32),
    )
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)



def _flash_bwd_dkv_kernel_resident(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, block_q, sm_scale, causal, window, q_shift,
):
    import jax.experimental.pallas as pl

    block_k = k_ref.shape[2]
    seq_q = q_ref.shape[2]
    d = k_ref.shape[3]
    # native-dtype MXU inputs, fp32 accumulation (see forward kernel)
    k_blk = k_ref[0, 0]  # (block_k, d)
    v_blk = v_ref[0, 0]
    k_offset = pl.program_id(2) * block_k

    num_q_blocks = seq_q // block_q
    start_block = 0
    end_block = num_q_blocks
    if causal:
        # contributes only where q_ids >= k_ids, i.e. qi + q_shift >= k_off
        start_block = jnp.maximum(0, (k_offset - q_shift) // block_q)
    if window > 0:
        # and q_ids - k_ids < window
        end_block = jnp.minimum(
            num_q_blocks,
            pl.cdiv(k_offset + block_k + window - q_shift, block_q),
        )

    def body(i, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, 0, pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[0, 0, pl.ds(i * block_q, block_q), :]
        lse_b = lse_ref[0, 0, pl.ds(i * block_q, block_q), 0]
        delta_b = delta_ref[0, 0, pl.ds(i * block_q, block_q), 0]
        s = jax.lax.dot_general(
            q_blk, k_blk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal or window > 0:
            s = _mask_boundary_only(s, i * block_q + q_shift, k_offset,
                                    block_q, block_k, causal, window)
        p = jnp.exp(s - lse_b[:, None])  # (block_q, block_k) fp32
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta_b[:, None]) * sm_scale).astype(q_blk.dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_blk, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_acc, dv_acc

    zeros = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_block, end_block, body, (zeros, zeros))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)



def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref,
    *, sm_scale, causal, window, q_shift, num_k_blocks,
):
    """Grid (b, h, q-block, k-block): K/V streamed along the innermost axis,
    dq accumulated in VMEM scratch — O(block) VMEM at any context length."""
    import jax.experimental.pallas as pl

    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]
    j = pl.program_id(3)
    q_offset = pl.program_id(2) * block_q + q_shift
    k_offset = j * block_k

    @pl.when(j == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    run = True
    if causal:
        run = k_offset < q_offset + block_q
    if window > 0:
        run = run & (k_offset + block_k > q_offset - window + 1)

    @pl.when(run)
    def _compute():
        # native-dtype MXU inputs, fp32 accumulation (see forward kernel)
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]  # (block_q,)
        delta = delta_ref[0, 0, :, 0]
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k_blk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal or window > 0:
            s = _mask_boundary_only(s, q_offset, k_offset, block_q, block_k,
                                    causal, window)
        p = jnp.exp(s - lse[:, None])  # masked entries → exp(−inf) = 0
        dp = jax.lax.dot_general(
            do, v_blk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(k_blk.dtype)
        dq_acc_ref[...] = dq_acc_ref[...] + jax.lax.dot_general(
            ds, k_blk, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_k_blocks - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, sm_scale, causal, window, q_shift, num_q_blocks,
):
    """Grid (b, h, k-block, q-block): Q/dO/lse/delta streamed along the
    innermost axis, dk/dv accumulated in VMEM scratch."""
    import jax.experimental.pallas as pl

    block_k = k_ref.shape[2]
    block_q = q_ref.shape[2]
    i = pl.program_id(3)
    k_offset = pl.program_id(2) * block_k
    q_offset = i * block_q + q_shift

    @pl.when(i == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    run = True
    if causal:
        # contributes only where q_ids >= k_ids for some element
        run = q_offset + block_q > k_offset
    if window > 0:
        # and q_ids - k_ids < window for some element
        run = run & (q_offset - (k_offset + block_k - 1) < window)

    @pl.when(run)
    def _compute():
        # native-dtype MXU inputs, fp32 accumulation (see forward kernel)
        k_blk = k_ref[0, 0]  # (block_k, d)
        v_blk = v_ref[0, 0]
        q_blk = q_ref[0, 0]  # (block_q, d)
        do_blk = do_ref[0, 0]
        lse_b = lse_ref[0, 0, :, 0]
        delta_b = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(
            q_blk, k_blk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal or window > 0:
            s = _mask_boundary_only(s, q_offset, k_offset, block_q, block_k,
                                    causal, window)
        p = jnp.exp(s - lse_b[:, None])  # (block_q, block_k) fp32
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta_b[:, None]) * sm_scale).astype(q_blk.dtype)
        dk_acc_ref[...] = dk_acc_ref[...] + jax.lax.dot_general(
            ds, q_blk, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == num_q_blocks - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_backward_pallas(
    q, k, v, out, lse, do, causal, sm_scale,
    block_q: int = 512, block_k: int = 512, interpret: bool = False,
    window: int = 0, resident=None,
):
    """Blockwise dq/dk/dv from the saved lse — no (Sq, Sk) intermediate in
    HBM.  Short sequences use the VMEM-resident kernels (operands read from
    HBM once per (batch, head)); long sequences use the streamed kernels
    whose VMEM use is O(block) at any context length."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _fit_block(sq, block_q)
    block_k = _fit_block(sk, block_k)
    q_shift = sk - sq
    dof = do.astype(q.dtype)
    # trailing singleton for Mosaic block-shape constraints (see _flash_kernel)
    lse = lse.reshape(b, h, sq, 1)
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )  # (b, h, sq, 1)
    num_k_blocks = sk // block_k
    num_q_blocks = sq // block_q
    if resident is None:
        resident = _resident_fits(
            max(sq, sk), d, max(k.dtype.itemsize, 4)
        )  # dq holds K/V, dkv holds Q/dO (+fp32 lse/delta)

    if resident:
        dq_kernel = functools.partial(
            _flash_bwd_dq_kernel_resident, block_k=block_k, sm_scale=sm_scale,
            causal=causal, window=window, q_shift=q_shift,
        )
        dq = pl.pallas_call(
            dq_kernel,
            grid=(b, h, num_q_blocks),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
                pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
                pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
                pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)
            ),
            out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            interpret=interpret,
        )(q, k, v, dof, lse, delta)

        dkv_kernel = functools.partial(
            _flash_bwd_dkv_kernel_resident, block_q=block_q, sm_scale=sm_scale,
            causal=causal, window=window, q_shift=q_shift,
        )
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(b, h, num_k_blocks),
            in_specs=[
                pl.BlockSpec((1, 1, sq, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
                pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
                pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
                pl.BlockSpec((1, 1, sq, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
                pl.BlockSpec((1, 1, sq, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
                pl.BlockSpec((1, 1, sq, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
                pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
                jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
            ],
            interpret=interpret,
        )(q, k, v, dof, lse, delta)
        return dq, dk, dv

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal, window=window,
        q_shift=q_shift, num_k_blocks=num_k_blocks,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, num_q_blocks, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, dof, lse, delta)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, window=window,
        q_shift=q_shift, num_q_blocks=num_q_blocks,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, num_k_blocks, num_q_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, dof, lse, delta)
    return dq, dk, dv


# -- public op with custom VJP ----------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None, window: int = 0):
    """Flash attention.  q,k,v: (batch, heads, seq, head_dim) → out like q.
    ``window`` > 0 enables sliding-window (local) attention."""
    return _forward(q, k, v, causal, sm_scale, window)


def _forward(q, k, v, causal, sm_scale, window=0):
    return _fwd(q, k, v, causal, sm_scale, window)[0]


def _fwd(q, k, v, causal, sm_scale, window):
    """Single dispatch site for both the primal and the VJP forward."""
    scale = q.shape[-1] ** -0.5 if sm_scale is None else sm_scale
    if _use_pallas():
        # 512x512 blocks measured ~2x faster than 128x128 on v5e (bigger
        # MXU ops, fewer inner-loop iterations); head_dim 128 is the
        # MXU-native lane width — prefer it when sizing models
        out, lse = _flash_forward_pallas(
            q, k, v, causal, scale, block_q=512, block_k=512, interpret=False,
            window=window, return_lse=True,
        )
    else:
        out, lse = mha_reference(q, k, v, causal, scale, window=window)
    return out, (q, k, v, out, lse)


def _bwd(causal, sm_scale, window, res, do):
    """Flash backward.  On TPU: blockwise Pallas kernels recomputing
    p = exp(s - lse) per block — no (Sq, Sk) intermediate at any context
    length.  Elsewhere: the XLA einsum recompute (materializes scores; fine
    at test sizes, and tests exercise the kernels in interpret mode)."""
    q, k, v, out, lse = res
    scale = q.shape[-1] ** -0.5 if sm_scale is None else sm_scale
    if _use_pallas():
        return _flash_backward_pallas(
            q, k, v, out, lse, do, causal, scale, window=window
        )
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal or window > 0:
        sq, sk = q.shape[2], k.shape[2]
        q_ids = jnp.arange(sq)[:, None] + (sk - sq)
        k_ids = jnp.arange(sk)[None, :]
        mask = jnp.ones((sq, sk), dtype=bool)
        if causal:
            mask &= q_ids >= k_ids
        if window > 0:
            mask &= (q_ids - k_ids) < window
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    p = jnp.exp(logits - lse[..., None])  # (B,H,Sq,Sk)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)


# -- blockwise kernel with softmax stats (ring-attention inner step) ---------


def _flash_stats_kernel(
    qoff_ref, koff_ref, q_ref, k_ref, v_ref, pv_ref, m_ref, l_ref,
    *, block_k, sm_scale, causal,
):
    """One (batch, q-block) program over ALL heads; emits unnormalized
    (pv, m, l) so callers (parallel/ring.py) can merge across K/V shards.

    Head dim stays inside the block so the rank-3 stats outputs tile as
    (1, H, block_q) — H equals the full axis and block_q is lane-sized,
    satisfying Mosaic's (sublane, lane) constraints.  Global q/k offsets
    arrive as SMEM scalars (they vary per ring hop).
    """
    import jax.experimental.pallas as pl

    H = q_ref.shape[1]
    block_q = q_ref.shape[2]
    head_dim = q_ref.shape[3]
    seq_k = k_ref.shape[2]
    q_offset = qoff_ref[0, 0] + pl.program_id(1) * block_q
    k_offset = koff_ref[0, 0]

    num_k_blocks = seq_k // block_k

    for h in range(H):  # static unroll over heads
        # native-dtype MXU inputs, fp32 accumulation (see _flash_kernel)
        q = q_ref[0, h]  # (block_q, d)

        def body(j, carry):
            acc, m_i, l_i = carry
            k_blk = k_ref[0, h, pl.ds(j * block_k, block_k), :]
            v_blk = v_ref[0, h, pl.ds(j * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k_blk,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale
            if causal:
                s = _mask_boundary_only(
                    s, q_offset, k_offset + j * block_k, block_q, block_k,
                    True, 0,
                )
            m_new = jnp.maximum(m_i, jnp.max(s, axis=1))
            alpha = jnp.exp(m_i - m_new)
            p = jnp.exp(s - m_new[:, None])
            l_new = l_i * alpha + jnp.sum(p, axis=1)
            acc = acc * alpha[:, None] + jax.lax.dot_general(
                p.astype(v_blk.dtype), v_blk,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return acc, m_new, l_new

        acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
        m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((block_q,), jnp.float32)
        acc, m_i, l_i = jax.lax.fori_loop(0, num_k_blocks, body, (acc0, m0, l0))
        pv_ref[0, h] = acc
        m_ref[0, h] = m_i
        l_ref[0, h] = l_i


def flash_block_stats(
    q, k, v, q_offset, k_offset,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Blockwise attention with stats: (B,H,Sq,D) x (B,H,Sk,D) →
    (pv (B,H,Sq,D) fp32 unnormalized, m (B,H,Sq), l (B,H,Sq)).

    ``q_offset``/``k_offset`` are global sequence starts (scalars, may be
    traced) for cross-shard causal masking — the ring-attention inner step.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0
    scale = d**-0.5 if sm_scale is None else sm_scale
    grid = (b, sq // block_q)
    kernel = functools.partial(
        _flash_stats_kernel, block_k=block_k, sm_scale=scale, causal=causal
    )
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1, 1)
    koff = jnp.asarray(k_offset, jnp.int32).reshape(1, 1)
    pv, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda bi, qi: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda bi, qi: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, h, block_q, d), lambda bi, qi: (bi, 0, qi, 0)),
            pl.BlockSpec((1, h, sk, d), lambda bi, qi: (bi, 0, 0, 0)),
            pl.BlockSpec((1, h, sk, d), lambda bi, qi: (bi, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, block_q, d), lambda bi, qi: (bi, 0, qi, 0)),
            pl.BlockSpec((1, h, block_q), lambda bi, qi: (bi, 0, qi)),
            pl.BlockSpec((1, h, block_q), lambda bi, qi: (bi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sq), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sq), jnp.float32),
        ],
        interpret=interpret,
    )(qoff, koff, q, k, v)
    return pv, m, l
